"""Adasum: scale-invariant gradient combination.

Re-design of the reference's Adasum (horovod/common/ops/adasum/adasum.h:38 —
pairwise combine a' = (1 - a.b/(2||a||^2)) a + (1 - a.b/(2||b||^2)) b applied
over a recursive-halving binary tree, power-of-two ranks required,
adasum.h:32).

On TPU the tree is a shard_map program over the process set's mesh: each
level every device exchanges its current value with its XOR partner via
`lax.ppermute` (an ICI neighbor transfer) and combines — the pairwise
formula is symmetric, so both partners converge on the same combined value
and after log2(n) levels every rank holds the tree result with no final
broadcast. The association (v0+v1)+(v2+v3)... matches the reference's
recursive-halving order exactly. Because the program is a plain shard_map
over the set mesh it runs identically in single-controller and
multi-process (jax.distributed) mode — the path the reference covers with
AdasumMPI cross-rank communication (adasum_mpi_operations.cc).

`hierarchical=True` (or HOROVOD_ADASUM_HIERARCHICAL=1) selects the
two-level variant of AdasumGpuAllreduceOp::NcclHierarchical
(horovod/common/ops/adasum_gpu_operations.cc:66-243): reduce-scatter (sum)
across the LOCAL mesh axis, Adasum recursive-doubling across the CROSS
axis on each rank's chunk, allgather back across LOCAL. Chunk
coefficients are per-chunk, like the reference's per-rank fused segments
(adasum_gpu_operations.cc:224 notes the same approximation).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..core import basics
from ..core.mesh import CROSS_AXIS, GLOBAL_AXIS, LOCAL_AXIS
from ..core.process_sets import ProcessSet


def _is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def adasum_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """One pairwise Adasum combine (adasum.h:101-131 dot/normsq dispatch +
    :366,406 ScaledAdd). Computed in float32 for stability, cast back.
    Symmetric in (a, b)."""
    dt = a.dtype
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af.ravel(), bf.ravel())
    na = jnp.vdot(af.ravel(), af.ravel())
    nb = jnp.vdot(bf.ravel(), bf.ravel())
    acoef = 1.0 - jnp.where(na > 0, dot / (2.0 * na), 0.0)
    bcoef = 1.0 - jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
    return (acoef * af + bcoef * bf).astype(dt)


def _xor_tree(v: jax.Array, axis: str, n: int) -> jax.Array:
    """Recursive-doubling Adasum over mesh axis `axis` (size n, power of
    two): level l exchanges with partner rank^2^l and combines. All ranks
    hold the tree result afterwards."""
    lvl = 1
    while lvl < n:
        u = lax.ppermute(v, axis, perm=[(i, i ^ lvl) for i in range(n)])
        v = adasum_combine(v, u)
        lvl *= 2
    return v


@functools.lru_cache(maxsize=256)
def _adasum_flat_fn(mesh: Mesh):
    n = mesh.devices.size

    def blk(x):                                   # [1, ...] per-device row
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        v = _xor_tree(v, GLOBAL_AXIS, n)
        return v[None].astype(dt)

    f = shard_map(blk, mesh=mesh, in_specs=P(GLOBAL_AXIS),
                  out_specs=P(GLOBAL_AXIS))
    return jax.jit(f)


@functools.lru_cache(maxsize=256)
def _adasum_hier_fn(mesh: Mesh):
    """Two-level Adasum over a (cross, local) mesh
    (adasum_gpu_operations.cc:135-138: NCCL ReduceScatter — parallelized
    MPI Adasum — NCCL Allgather). The flat element count is padded to a
    local-size multiple like the reference's FUSION_BUFFER_ATOMIC_UNIT
    padding (adasum_gpu_operations.cc:118-123)."""
    cross_n, local_n = mesh.devices.shape

    def blk(x):                                   # [1, ...] per-device row
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        shape = v.shape
        flat = v.reshape(-1)
        m = flat.shape[0]
        pad = (-m) % local_n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        # phase 1: sum-reduce-scatter within the local (ICI) group
        chunk = lax.psum_scatter(flat, LOCAL_AXIS, scatter_dimension=0,
                                 tiled=True)
        # phase 2: Adasum across nodes on this rank's chunk
        chunk = _xor_tree(chunk, CROSS_AXIS, cross_n)
        # phase 3: allgather back within the local group
        full = lax.all_gather(chunk, LOCAL_AXIS, tiled=True)
        if pad:
            full = full[:m]
        return full.reshape(shape)[None].astype(dt)

    f = shard_map(blk, mesh=mesh, in_specs=P((CROSS_AXIS, LOCAL_AXIS)),
                  out_specs=P((CROSS_AXIS, LOCAL_AXIS)))
    return jax.jit(f)


def adasum_allreduce(x: jax.Array, *,
                     process_set: Optional[ProcessSet] = None,
                     hierarchical: Optional[bool] = None,
                     local_size: Optional[int] = None) -> jax.Array:
    """Adasum reduction over the stacked rank axis; all ranks get the result.

    Matches hvd.allreduce(op=hvd.Adasum). Requires a power-of-two set size
    like the reference tree (adasum.h:32 IsPowerOfTwo). `hierarchical`
    (default HOROVOD_ADASUM_HIERARCHICAL, only for the global set) selects
    the AdasumGpuAllreduceOp-style two-level algorithm: local sum
    reduce-scatter, cross-node Adasum, local allgather. `local_size`
    overrides the hier topology's local-group width (default: the
    launcher/host-derived hier mesh from init()).
    """
    ps = basics.get_process_set(process_set)
    n = ps.size()
    if hierarchical is None:
        hierarchical = basics.get_config().adasum_hierarchical and \
            ps.process_set_id == 0
    if local_size is not None and not hierarchical:
        raise ValueError(
            "local_size only applies to hierarchical Adasum; pass "
            "hierarchical=True (or set HOROVOD_ADASUM_HIERARCHICAL=1)")
    from .collective_ops import _place_stacked
    if hierarchical:
        if local_size is not None:
            if local_size <= 0 or n % local_size != 0:
                raise ValueError(
                    f"local_size {local_size} must divide the set size {n}")
            from ..core.mesh import build_hierarchical_mesh
            hier = build_hierarchical_mesh(
                list(ps.mesh.devices.flat), local_size=local_size)
        else:
            hier = basics.get_hier_mesh()
        if ps.process_set_id != 0 or hier.devices.size != n:
            raise ValueError(
                "hierarchical Adasum runs on the global process set only")
        cross_n, local_n = hier.devices.shape
        if not _is_power_of_two(cross_n):
            raise ValueError(
                f"hierarchical Adasum requires a power-of-two cross size, "
                f"got {cross_n}")
        x = _place_stacked(x, ps.mesh, n, "adasum")
        if n == 1:
            return x
        if local_n == 1:          # degenerate: no local group -> flat tree
            return _adasum_flat_fn(ps.mesh)(x)
        from ..core.mesh import stacked_sharding
        xh = jax.device_put(x, stacked_sharding(hier, (CROSS_AXIS,
                                                       LOCAL_AXIS))) \
            if x.is_fully_addressable else x
        out = _adasum_hier_fn(hier)(xh)
        return jax.device_put(out, stacked_sharding(ps.mesh)) \
            if out.is_fully_addressable else out
    if not _is_power_of_two(n):
        raise ValueError(
            f"Adasum requires a power-of-two number of ranks, got {n}")
    x = _place_stacked(x, ps.mesh, n, "adasum")
    if n == 1:
        return x
    return _adasum_flat_fn(ps.mesh)(x)
