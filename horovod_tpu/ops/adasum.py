"""Adasum: scale-invariant gradient combination.

Re-design of the reference's Adasum (horovod/common/ops/adasum/adasum.h:38 —
pairwise combine a' = (1 - a.b/(2||a||^2)) a + (1 - a.b/(2||b||^2)) b applied
over a recursive-halving binary tree, power-of-two ranks required,
adasum.h:32).

On TPU the tree is a shard_map program over the process set's mesh: each
level every device exchanges its current value with its XOR partner via
`lax.ppermute` (an ICI neighbor transfer) and combines — the pairwise
formula is symmetric, so both partners converge on the same combined value
and after log2(n) levels every rank holds the tree result with no final
broadcast. The association (v0+v1)+(v2+v3)... matches the reference's
recursive-halving order exactly. Because the program is a plain shard_map
over the set mesh it runs identically in single-controller and
multi-process (jax.distributed) mode — the path the reference covers with
AdasumMPI cross-rank communication (adasum_mpi_operations.cc).

`hierarchical=True` (or HOROVOD_ADASUM_HIERARCHICAL=1) selects the
two-level variant of AdasumGpuAllreduceOp::NcclHierarchical
(horovod/common/ops/adasum_gpu_operations.cc:66-243): reduce-scatter (sum)
across the LOCAL mesh axis, Adasum recursive-doubling across the CROSS
axis on each rank's chunk, allgather back across LOCAL. Chunk
coefficients are per-chunk, like the reference's per-rank fused segments
(adasum_gpu_operations.cc:224 notes the same approximation).

Quantized transport (`wire="bf16"|"int8"`): only the ppermute payload is
compressed — the EQuARX discipline (arxiv 2506.17615): compress the
transport, never the math. At every tree level BOTH partners combine the
same dequantized pair: rank i locally round-trips its own value through
the wire format (vhat_i) and receives the partner's round-tripped value
(vhat_j), so combine(vhat_i, vhat_j) is evaluated on the same pair on
both sides (the formula is symmetric) and all ranks still converge to
the same value — up to ulp-level rounding from the compiled combine's
multiply-add order, exactly like the uncompressed tree — with no
broadcast. The dot/normsq projection runs on the
dequantized fp32 values, so Adasum's scale-invariance sees one coherent
vector per rank — the property the PR 1 rejection protected (summing
per-rank int8 scales is meaningless; round-tripping per rank is exact
bookkeeping). Int8 additionally carries per-HOP error-feedback residuals
(keyed like the engine's `_ef_residuals`, ops/engine.py): what level l's
quantizer dropped this step is re-injected at level l next step, so the
quantization noise is unbiased over time exactly like the Sum path's EF.
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..core import basics
from ..core.mesh import CROSS_AXIS, GLOBAL_AXIS, LOCAL_AXIS
from ..core.process_sets import ProcessSet
from ..optim.compression import block_dequantize, block_quantize

#: structured rejection messages, single-sourced so the sync path
#: (ops/collective_ops.py) and the engine route (ops/engine.py) raise
#: the SAME error with the supported alternative named — tests assert
#: the two paths match verbatim (docs/benchmarks.md rejection matrix)
ADASUM_JOIN_ERROR = (
    "allreduce(Adasum) is not supported with Join: a joined rank's "
    "zero-filled contribution has zero norm, which corrupts the "
    "scale-sensitive dot/normsq projection; use op=Average (joined "
    "ranks are masked exactly) or keep every rank contributing")
ADASUM_REDUCESCATTER_ERROR = (
    "reducescatter(op=Adasum) is not supported: the Adasum combine "
    "needs every rank's full vector for its dot/normsq projection, so "
    "it has no scatter form; use allreduce(op=Adasum) and slice, or "
    "reducescatter(op=Average)")

#: wire formats the Adasum transport implements ("none" = exact)
ADASUM_WIRE_FORMATS = ("none", "bf16", "int8")


def _is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def adasum_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """One pairwise Adasum combine (adasum.h:101-131 dot/normsq dispatch +
    :366,406 ScaledAdd). Computed in float32 for stability, cast back.
    Symmetric in (a, b)."""
    dt = a.dtype
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af.ravel(), bf.ravel())
    na = jnp.vdot(af.ravel(), af.ravel())
    nb = jnp.vdot(bf.ravel(), bf.ravel())
    acoef = 1.0 - jnp.where(na > 0, dot / (2.0 * na), 0.0)
    bcoef = 1.0 - jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
    return (acoef * af + bcoef * bf).astype(dt)


def _xor_tree(v: jax.Array, axis: str, n: int) -> jax.Array:
    """Recursive-doubling Adasum over mesh axis `axis` (size n, power of
    two): level l exchanges with partner rank^2^l and combines. All ranks
    hold the tree result afterwards."""
    lvl = 1
    while lvl < n:
        u = lax.ppermute(v, axis, perm=[(i, i ^ lvl) for i in range(n)])
        v = adasum_combine(v, u)
        lvl *= 2
    return v


def _xor_tree_bf16(v: jax.Array, axis: str, n: int) -> jax.Array:
    """`_xor_tree` with bf16 ppermute payloads. Each level combines the
    pair (bf16(v_i), bf16(v_j)) — i's own value round-tripped locally, so
    both partners evaluate the symmetric combine on the same pair and
    stay identical to ulp precision, like the exact tree. No residual: bf16 keeps fp32's exponent, the rounding
    noise is relative and needs no feedback (the engine's bf16 fused wire
    makes the same call)."""
    lvl = 1
    while lvl < n:
        perm = [(i, i ^ lvl) for i in range(n)]
        mine = v.astype(jnp.bfloat16)
        u = lax.ppermute(mine, axis, perm=perm).astype(jnp.float32)
        v = adasum_combine(mine.astype(jnp.float32), u)
        lvl *= 2
    return v


def _xor_tree_int8(v: jax.Array, res: jax.Array, axis: str, n: int,
                   block_size: int) -> Tuple[jax.Array, jax.Array]:
    """`_xor_tree` with int8 block-scaled ppermute payloads and per-hop
    error feedback. `v` is the flat fp32 vector, `res` the [hops, len]
    residual carried from the previous call with the same key.

    Per level l: fold in res[l], quantize, keep what the quantizer
    dropped as the NEW res[l] (per-hop keying — each level quantizes a
    different value, so a shared residual would feed level-0 noise into
    level-1's combine), exchange int8+scales, and combine the two
    DEQUANTIZED values. Dequantization is deterministic, so rank i's
    local vhat_i is bit-equal to what its partner reconstructs — the
    symmetric combine keeps every rank identical to ulp precision, same
    as the exact tree."""
    m = v.shape[0]
    lvl, hop = 1, 0
    new_res = []
    while lvl < n:
        perm = [(i, i ^ lvl) for i in range(n)]
        acc = v + res[hop]
        q, s = block_quantize(acc, block_size)
        vhat = block_dequantize(q, s, m)
        new_res.append(acc - vhat)
        qu = lax.ppermute(q, axis, perm=perm)
        su = lax.ppermute(s, axis, perm=perm)
        u = block_dequantize(qu, su, m)
        v = adasum_combine(vhat, u)
        lvl *= 2
        hop += 1
    return v, jnp.stack(new_res)


@functools.lru_cache(maxsize=256)
def _adasum_flat_fn(mesh: Mesh):
    n = mesh.devices.size

    def blk(x):                                   # [1, ...] per-device row
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        v = _xor_tree(v, GLOBAL_AXIS, n)
        return v[None].astype(dt)

    f = shard_map(blk, mesh=mesh, in_specs=P(GLOBAL_AXIS),
                  out_specs=P(GLOBAL_AXIS))
    return jax.jit(f)


@functools.lru_cache(maxsize=256)
def _adasum_flat_bf16_fn(mesh: Mesh):
    n = mesh.devices.size

    def blk(x):                                   # [1, ...] per-device row
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        shape = v.shape
        out = _xor_tree_bf16(v.reshape(-1), GLOBAL_AXIS, n)
        return out.reshape(shape)[None].astype(dt)

    f = shard_map(blk, mesh=mesh, in_specs=P(GLOBAL_AXIS),
                  out_specs=P(GLOBAL_AXIS))
    return jax.jit(f)


@functools.lru_cache(maxsize=256)
def _adasum_flat_int8_fn(mesh: Mesh, block_size: int):
    n = mesh.devices.size

    def blk(x, res):            # x: [1, ...] row, res: [1, hops, len]
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        shape = v.shape
        out, nr = _xor_tree_int8(v.reshape(-1), res[0], GLOBAL_AXIS, n,
                                 block_size)
        return out.reshape(shape)[None].astype(dt), nr[None]

    f = shard_map(blk, mesh=mesh,
                  in_specs=(P(GLOBAL_AXIS), P(GLOBAL_AXIS)),
                  out_specs=(P(GLOBAL_AXIS), P(GLOBAL_AXIS)))
    return jax.jit(f)


def _hier_pad_chunk(m: int, local_n: int) -> Tuple[int, int]:
    """(pad, chunk_len) of the hier path's per-rank scatter chunk."""
    pad = (-m) % local_n
    return pad, (m + pad) // local_n


@functools.lru_cache(maxsize=256)
def _adasum_hier_fn(mesh: Mesh, wire: str = "none", block_size: int = 128):
    """Two-level Adasum over a (cross, local) mesh
    (adasum_gpu_operations.cc:135-138: NCCL ReduceScatter — parallelized
    MPI Adasum — NCCL Allgather). The flat element count is padded to a
    local-size multiple like the reference's FUSION_BUFFER_ATOMIC_UNIT
    padding (adasum_gpu_operations.cc:118-123).

    `wire` compresses ONLY the cross-axis XOR tree — the DCN analog, the
    hop HOROVOD_COMPRESSION_DCN_ONLY exists for; the local (ICI)
    reduce-scatter/allgather stays exact. Int8 takes and returns the
    per-hop EF residual on the scatter chunk."""
    cross_n, local_n = mesh.devices.shape
    ef = wire == "int8" and cross_n > 1

    def blk(x, res=None):                         # [1, ...] per-device row
        dt = x.dtype
        v = x[0].astype(jnp.float32)
        shape = v.shape
        flat = v.reshape(-1)
        m = flat.shape[0]
        pad, _ = _hier_pad_chunk(m, local_n)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
        # phase 1: sum-reduce-scatter within the local (ICI) group
        chunk = lax.psum_scatter(flat, LOCAL_AXIS, scatter_dimension=0,
                                 tiled=True)
        # phase 2: Adasum across nodes on this rank's chunk
        nr = None
        if ef:
            chunk, nr = _xor_tree_int8(chunk, res[0], CROSS_AXIS, cross_n,
                                       block_size)
        elif wire == "bf16" and cross_n > 1:
            chunk = _xor_tree_bf16(chunk, CROSS_AXIS, cross_n)
        else:
            chunk = _xor_tree(chunk, CROSS_AXIS, cross_n)
        # phase 3: allgather back within the local group
        full = lax.all_gather(chunk, LOCAL_AXIS, tiled=True)
        if pad:
            full = full[:m]
        out = full.reshape(shape)[None].astype(dt)
        return (out, nr[None]) if ef else out

    spec = P((CROSS_AXIS, LOCAL_AXIS))
    f = shard_map(blk, mesh=mesh,
                  in_specs=(spec, spec) if ef else spec,
                  out_specs=(spec, spec) if ef else spec)
    return jax.jit(f)


# -- per-hop error-feedback residual store ---------------------------------
# Keyed like the engine's `_ef_residuals` (ops/engine.py): the caller's
# scope (the engine passes its fusion signature + group position, which
# already folds in op/dtype/process-set/pre-post-scale/wire/algo), plus
# everything that changes the exchange pattern or payload layout here —
# topology (flat vs hier chunking AND the set size: a different tree depth
# is a different exchange pattern), wire format, block size, shape, dtype.
# A tuner flipping algorithm or wire mid-run therefore lands on a FRESH
# key and can never fold another exchange pattern's stale residual into
# its combine. Byte-budgeted LRU like the engine's `_ef_budget_bytes`.
_EF_BUDGET_BYTES = 64 << 20
_ef_store: "OrderedDict[tuple, jax.Array]" = OrderedDict()


def _ef_store_key(ef_key, ps: ProcessSet, topo: tuple, wire: str,
                  block_size: int, shape, dtype) -> tuple:
    return (ef_key, ps.process_set_id, ps.mesh, topo, wire,
            int(block_size), tuple(int(s) for s in shape), str(dtype))


def _ef_get(key: tuple, shape: Tuple[int, ...]) -> jax.Array:
    r = _ef_store.get(key)
    if r is None or tuple(r.shape) != tuple(shape):
        r = jnp.zeros(shape, jnp.float32)
    return r


def _place_residual(res: jax.Array, sharding) -> jax.Array:
    """Row-shard a residual for its tree program. Steady state the
    stored residual IS the previous call's sharded output (pass
    through); the first call's host zeros need multi-process-safe
    placement (device_put cannot target non-addressable devices)."""
    if isinstance(res, jax.Array) and not res.is_fully_addressable:
        return res
    from ..core.mesh import place_sharded
    return place_sharded(np.asarray(res), sharding)


def _ef_put(key: tuple, value: jax.Array) -> None:
    _ef_store[key] = value
    _ef_store.move_to_end(key)
    total = sum(4 * v.size for v in _ef_store.values())
    while len(_ef_store) > 1 and total > _EF_BUDGET_BYTES:
        _, dropped = _ef_store.popitem(last=False)
        total -= 4 * dropped.size


def ef_residual_keys() -> Tuple[tuple, ...]:
    """Current residual-store keys (test/introspection surface)."""
    return tuple(_ef_store.keys())


def reset_error_feedback() -> None:
    """Drop all carried residuals (a fresh run must not inherit another
    run's quantization noise; tests call this between cases)."""
    _ef_store.clear()


def adasum_allreduce(x: jax.Array, *,
                     process_set: Optional[ProcessSet] = None,
                     hierarchical: Optional[bool] = None,
                     local_size: Optional[int] = None,
                     wire: str = "none",
                     block_size: int = 128,
                     ef_key=None) -> jax.Array:
    """Adasum reduction over the stacked rank axis; all ranks get the result.

    Matches hvd.allreduce(op=hvd.Adasum). Requires a power-of-two set size
    like the reference tree (adasum.h:32 IsPowerOfTwo). `hierarchical`
    (default HOROVOD_ADASUM_HIERARCHICAL, only for the global set) selects
    the AdasumGpuAllreduceOp-style two-level algorithm: local sum
    reduce-scatter, cross-node Adasum, local allgather. `local_size`
    overrides the hier topology's local-group width (default: the
    launcher/host-derived hier mesh from init()).

    `wire` compresses the exchange transport ("bf16" | "int8"; "none" is
    exact): flat mode every tree hop, hierarchical mode only the cross
    tree (the local ICI phases stay exact — the DCN-only discipline).
    Int8 carries per-hop error-feedback residuals under `ef_key` (the
    engine passes its bucket signature; None derives a key from the
    call's shape/dtype/set/topology — fine for the steady-state
    same-tensor-every-step pattern, see `_ef_store_key`).
    """
    if wire not in ADASUM_WIRE_FORMATS:
        raise ValueError(
            f"adasum wire must be one of {ADASUM_WIRE_FORMATS}; got "
            f"{wire!r}")
    ps = basics.get_process_set(process_set)
    n = ps.size()
    if wire != "none" and not jnp.issubdtype(
            jnp.asarray(x).dtype, jnp.floating):
        raise ValueError(
            f"adasum wire {wire!r} applies to float tensors only; got "
            f"dtype {jnp.asarray(x).dtype} (pass wire='none')")
    if hierarchical is None:
        hierarchical = basics.get_config().adasum_hierarchical and \
            ps.process_set_id == 0
    if local_size is not None and not hierarchical:
        raise ValueError(
            "local_size only applies to hierarchical Adasum; pass "
            "hierarchical=True (or set HOROVOD_ADASUM_HIERARCHICAL=1)")
    from .collective_ops import _place_stacked
    if hierarchical:
        if local_size is not None:
            if local_size <= 0 or n % local_size != 0:
                raise ValueError(
                    f"local_size {local_size} must divide the set size {n}")
            from ..core.mesh import build_hierarchical_mesh
            hier = build_hierarchical_mesh(
                list(ps.mesh.devices.flat), local_size=local_size)
        else:
            hier = basics.get_hier_mesh()
        if ps.process_set_id != 0 or hier.devices.size != n:
            raise ValueError(
                "hierarchical Adasum runs on the global process set only")
        cross_n, local_n = hier.devices.shape
        if not _is_power_of_two(cross_n):
            raise ValueError(
                f"hierarchical Adasum requires a power-of-two cross size, "
                f"got {cross_n}")
        x = _place_stacked(x, ps.mesh, n, "adasum")
        if n == 1:
            return x
        if local_n == 1:          # degenerate: no local group -> flat tree
            return _flat_dispatch(x, ps, n, wire, block_size, ef_key)
        from ..core.mesh import stacked_sharding
        xh = jax.device_put(x, stacked_sharding(hier, (CROSS_AXIS,
                                                       LOCAL_AXIS))) \
            if x.is_fully_addressable else x
        if wire == "int8" and cross_n > 1:
            m = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
            _, chunk = _hier_pad_chunk(m, local_n)
            hops = cross_n.bit_length() - 1
            key = _ef_store_key(ef_key, ps, ("hier", cross_n, local_n),
                                wire, block_size, x.shape, x.dtype)
            res = _ef_get(key, (n, hops, chunk))
            resh = _place_residual(
                res, NamedSharding(hier, P((CROSS_AXIS, LOCAL_AXIS))))
            out, new_res = _adasum_hier_fn(hier, wire, block_size)(xh, resh)
            _ef_put(key, new_res)
        else:
            out = _adasum_hier_fn(hier, wire, block_size)(xh)
        return jax.device_put(out, stacked_sharding(ps.mesh)) \
            if out.is_fully_addressable else out
    if not _is_power_of_two(n):
        raise ValueError(
            f"Adasum requires a power-of-two number of ranks, got {n}")
    x = _place_stacked(x, ps.mesh, n, "adasum")
    if n == 1:
        return x
    return _flat_dispatch(x, ps, n, wire, block_size, ef_key)


def _flat_dispatch(x: jax.Array, ps: ProcessSet, n: int, wire: str,
                   block_size: int, ef_key) -> jax.Array:
    if wire == "bf16":
        return _adasum_flat_bf16_fn(ps.mesh)(x)
    if wire == "int8":
        m = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        hops = n.bit_length() - 1
        key = _ef_store_key(ef_key, ps, ("flat", n), wire, block_size,
                            x.shape, x.dtype)
        res = _ef_get(key, (n, hops, m))
        res = _place_residual(res, NamedSharding(ps.mesh, P(GLOBAL_AXIS)))
        out, new_res = _adasum_flat_int8_fn(ps.mesh, block_size)(x, res)
        _ef_put(key, new_res)
        return out
    return _adasum_flat_fn(ps.mesh)(x)
