"""Sparse allreduce: allgather-based reduction of (indices, values) pairs.

TPU-native re-design of the reference's sparse gradient path
(horovod/torch/mpi_ops.py:567 sparse_allreduce_async): each rank holds a
sparse slice of a gradient as (indices [k_i], values [k_i, ...]) with ragged
k_i across ranks; both are allgathered, duplicate indices are coalesced by
summation, and Average divides by the process-set size.

Instead of re-assembling a framework sparse tensor, the coalesce step is a
jitted segment-sum — XLA lowers it to an MXU/VPU-friendly scatter-add — and
the result is returned either coalesced-sparse (unique indices + summed
values) or dense (scattered into the full dim-0 extent), whichever the
caller asks for. Dense results are replicated over the process-set mesh.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..core import basics
from ..core.process_sets import ProcessSet
from ..core.types import ReduceOp


@functools.lru_cache(maxsize=256)
def _coalesce_fn(num_segments: int, divide_by: int):
    def f(seg_ids, values):
        out = jax.ops.segment_sum(values, seg_ids, num_segments=num_segments)
        if divide_by > 1:
            out = out / divide_by if jnp.issubdtype(out.dtype, jnp.floating) \
                else (out // divide_by).astype(out.dtype)
        return out
    return jax.jit(f)


def sparse_allreduce(
    pairs: Sequence[Tuple[Union[np.ndarray, jax.Array],
                          Union[np.ndarray, jax.Array]]],
    op: ReduceOp = ReduceOp.AVERAGE, *,
    dense_dim0: Optional[int] = None,
    dense: bool = False,
    process_set: Optional[ProcessSet] = None,
    name: Optional[str] = None,
) -> Union[Tuple[np.ndarray, jax.Array], jax.Array]:
    """Reduce ragged per-rank sparse (indices, values) contributions.

    Args:
      pairs: one (indices, values) pair per rank of the process set.
        indices is int [k_i] (row ids into dim 0 of the dense gradient),
        values is [k_i, ...] with identical trailing dims across ranks.
      op: Sum or Average (Average matches the reference's `/ size`,
        torch/mpi_ops.py:584).
      dense_dim0: dim-0 extent of the dense gradient; required when
        dense=True, otherwise defaults to max(index)+1.
      dense: return the full dense [dense_dim0, ...] array instead of a
        coalesced (unique_indices, summed_values) pair.

    Returns:
      (unique_indices, values) coalesced-sparse, or the dense array
      replicated over the set mesh when dense=True.
    """
    ps, mesh = _resolve(process_set)
    n = ps.size()
    from ..core.mesh import local_row_indices, mesh_is_multiprocess
    multiproc = mesh_is_multiprocess(mesh)
    expect = len(local_row_indices(mesh)) if multiproc else n
    if len(pairs) != expect:
        raise ValueError(f"Expected {expect} (indices, values) pairs, got "
                         f"{len(pairs)}")
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("sparse_allreduce supports Sum/Average only "
                         "(reference path likewise sums then divides)")
    idx_list: List[np.ndarray] = []
    val_list = []
    trailing = None
    for r, (idx, val) in enumerate(pairs):
        idx = np.asarray(idx)
        val = jnp.asarray(val)
        if idx.ndim != 1 or val.shape[0] != idx.shape[0]:
            raise ValueError(
                f"rank {r}: indices must be [k] and values [k, ...]; got "
                f"{idx.shape} / {tuple(val.shape)}")
        t = tuple(val.shape[1:])
        if trailing is None:
            trailing = t
        elif t != trailing:
            raise ValueError(
                f"rank {r}: trailing dims {t} != {trailing}")
        idx_list.append(idx.astype(np.int64))
        val_list.append(val)
    divide = n if op == ReduceOp.AVERAGE else 1

    if multiproc:
        # Two engine-routed ragged allgathers — exactly the reference's
        # sparse path (torch/mpi_ops.py:573-580 allgathers indices and
        # values); the engine negotiates per-rank sizes cross-process.
        from . import collective_ops
        base = name or "sparse_allreduce"
        for r, idx in enumerate(idx_list):
            if idx.size and idx.max() >= np.iinfo(np.int32).max:
                raise ValueError(
                    f"rank {r}: sparse index {idx.max()} exceeds int32 "
                    "(TPU index dtype)")
        all_idx = np.asarray(collective_ops.allgather(
            [idx.astype(np.int32) for idx in idx_list],
            process_set=ps, name=f"{base}.idx")).astype(np.int64)
        all_val = collective_ops.allgather(
            val_list, process_set=ps, name=f"{base}.val")
        all_val = jnp.asarray(np.asarray(all_val))
    else:
        # "allgather" of the ragged indices/values: host-side concat, the
        # moral equivalent of the reference's two allgathers
        # (torch/mpi_ops.py:573-580).
        all_idx = np.concatenate(idx_list) if idx_list \
            else np.zeros(0, np.int64)
        all_val = jnp.concatenate(val_list, axis=0)

    if all_idx.size == 0:
        if dense:
            if dense_dim0 is None:
                raise ValueError("dense=True requires dense_dim0")
            from ..core.mesh import place_replicated
            out = np.zeros((dense_dim0,) + trailing,
                           np.dtype(str(all_val.dtype)))
            return place_replicated(out, mesh)
        return np.zeros(0, np.int64), all_val

    if all_idx.min() < 0:
        raise ValueError(f"negative sparse index {all_idx.min()}")
    if dense:
        if dense_dim0 is None:
            raise ValueError("dense=True requires dense_dim0")
        if all_idx.max() >= dense_dim0:
            raise ValueError(
                f"index {all_idx.max()} out of range for dense_dim0="
                f"{dense_dim0}")
        from ..core.mesh import place_replicated
        out = _coalesce_fn(dense_dim0, divide)(jnp.asarray(all_idx), all_val)
        return place_replicated(out, mesh)

    # coalesce: unique indices (static, host) + jitted segment-sum of values
    uniq, inverse = np.unique(all_idx, return_inverse=True)
    vals = _coalesce_fn(int(uniq.shape[0]), divide)(
        jnp.asarray(inverse), all_val)
    return uniq, vals


def _resolve(process_set: Optional[ProcessSet]):
    ps = basics.get_process_set(process_set)
    return ps, ps.mesh


def sparse_allreduce_async(
    pairs: Sequence[Tuple[Union[np.ndarray, jax.Array],
                          Union[np.ndarray, jax.Array]]],
    op: ReduceOp = ReduceOp.AVERAGE, *,
    dense_dim0: Optional[int] = None,
    dense: bool = False,
    process_set: Optional[ProcessSet] = None,
    name: Optional[str] = None,
):
    """Async handle form of sparse_allreduce — the reference's surface
    (torch/mpi_ops.py:567 sparse_allreduce_async returns a handle resolved
    by synchronize). Work runs on one shared helper thread (per-call
    ordering preserved — important for multi-process mode, where the
    underlying ragged allgathers serialize through the engine)."""
    from .engine import Handle, _auto_name
    from ..core.types import Status

    name = name or _auto_name("sparse_allreduce")
    handle = Handle(name)

    def _run():
        try:
            result = sparse_allreduce(
                pairs, op, dense_dim0=dense_dim0, dense=dense,
                process_set=process_set, name=name)
            handle._resolve(result, Status.ok())
        except Exception as e:  # noqa: BLE001 - surfaced via handle.wait()
            handle._resolve(None, Status.unknown(str(e)))

    _sparse_executor().submit(_run)
    return handle


import threading as _threading

_executor = None
_executor_lock = _threading.Lock()


def _sparse_executor():
    """Lazy single-thread executor: FIFO per process, no per-call thread
    churn."""
    global _executor
    with _executor_lock:
        if _executor is None:
            from concurrent.futures import ThreadPoolExecutor
            _executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="hvd-sparse")
    return _executor
