"""Topology-aware collective algorithm selection (the algorithm plane).

The engine used to have exactly one algorithm per collective: flat
`lax.psum` for everything, with `two_level_allreduce` as an
all-or-nothing toggle. Both "A Generalization of the Allreduce
Operation" and "Optimizing Allreduce Operations for Modern
Heterogeneous Architectures" (PAPERS.md) show the winning algorithm
flips with tensor size and topology: latency-bound small buckets want
few-hop schedules (recursive halving/doubling, direct psum),
bandwidth-bound large buckets want the ring decomposition
(reduce-scatter + allgather) or the two-level hierarchy that keeps
expensive DCN bytes L-fold smaller.

This module is the pure-math half of that plane — jax-free so
`core.config` can validate knob values without importing the backend:

* `ALGORITHMS` — the registry of allreduce strategies the data plane
  implements (`ops/collective_ops.py` programs + `ops/cross.py`):

  ========== =========================================================
  direct     one fused XLA all-reduce (`lax.psum`) — a single HLO,
             the lowest launch overhead
  rs_ag      reduce-scatter + allgather (`lax.psum_scatter` +
             `lax.all_gather`), the bandwidth-optimal ring
             decomposition with explicit phases
  rhd        recursive halving/doubling over `lax.ppermute` —
             2*log2(P) hops instead of 2*(P-1), latency-optimal for
             small buckets on power-of-two worlds
  two_level  local-RS / cross-AR / local-AG over the (cross, local)
             hierarchical mesh (`ops/cross.py`) — DCN bytes shrink by
             the local size
  ========== =========================================================

* an analytic alpha-beta cost model (`predict_cost`) with per-link
  latency/bandwidth/launch terms and a closed-form size-threshold
  crossover (`crossover_bytes`), and

* `resolve` — the one place algorithm choice happens, combining the
  `HOROVOD_COLLECTIVE_ALGO` override, the legacy hierarchical/torus
  toggles, the autotuner's learned per-regime choices
  (`collective_algo_small` / `collective_algo_large`, split at the
  crossover threshold) and the cost model, in that precedence order.
  Every input is either round-synchronized config or a property of the
  bucket itself, so all ranks resolve identically (the PR 1
  rank-invariance discipline).
"""
from __future__ import annotations

from dataclasses import dataclass
from math import log2
from typing import Optional, Tuple

#: allreduce strategy registry, in deterministic tie-break order
ALGORITHMS = ("direct", "rs_ag", "rhd", "two_level")

#: values HOROVOD_COLLECTIVE_ALGO accepts
ALGO_CHOICES = ("auto",) + ALGORITHMS


@dataclass(frozen=True)
class LinkModel:
    """Alpha-beta-gamma link cost: per-hop latency `alpha_s`, inverse
    bandwidth `beta_s_per_byte`, and per-HLO dispatch cost `launch_s`
    (the gamma term that separates one-program `direct` from multi-phase
    schedules at tiny sizes)."""

    alpha_s: float
    beta_s_per_byte: float
    launch_s: float


#: ICI defaults: ~1 us/hop, ~100 GB/s per link (TPU v4/v5 class)
ICI = LinkModel(alpha_s=1e-6, beta_s_per_byte=1.0 / 100e9, launch_s=2e-6)
#: DCN defaults: ~50 us/hop, ~12.5 GB/s (100 Gb NIC class)
DCN = LinkModel(alpha_s=50e-6, beta_s_per_byte=1.0 / 12.5e9, launch_s=2e-6)

#: rhd's byte-term handicap: halving/doubling exchanges non-contiguous
#: halves with distance-2^k partners, which on ring/torus links means
#: multi-hop routing contention the per-neighbor ring never pays — the
#: classic reason MPI/NCCL switch to ring schedules for large payloads
#: (Thakur et al.; both PAPERS.md allreduce surveys). Without it the
#: model would (wrongly) pick rhd at every size on power-of-two worlds.
RHD_BW_PENALTY = 1.5

#: below this the MODEL always answers "direct": sub-KB payloads
#: (barrier tokens, control-plane probes) are launch-overhead-dominated
#: — no schedule beats one fused HLO, and churning compiled variants
#: for them costs real compile time for zero wire savings. The tuner's
#: learned per-regime choices and explicit overrides are NOT floored:
#: a measured preference always stands.
MIN_MODEL_BYTES = 1024


def is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def predict_cost(algo: str, nbytes: int, world: int, *,
                 hier_shape: Optional[Tuple[int, int]] = None,
                 dcn: bool = False,
                 ici: LinkModel = ICI, dcn_link: LinkModel = DCN) -> float:
    """Predicted seconds for one allreduce of `nbytes` per rank.

    `dcn=True` models a mesh whose flat ring crosses DCN links (the
    multi-host regime): flat algorithms then pay DCN alpha/beta on every
    hop, which is exactly what makes `two_level` attractive — its cross
    phase moves nbytes/local_size.
    """
    if world <= 1:
        return 0.0
    link = dcn_link if dcn else ici
    P = world
    N = float(max(nbytes, 0))
    ring_bw = 2.0 * N * (P - 1) / P * link.beta_s_per_byte
    if algo == "direct":
        return link.launch_s + 2 * (P - 1) * link.alpha_s + ring_bw
    if algo == "rs_ag":
        # modelled as direct + one extra launch: in the alpha-beta
        # abstraction both are bandwidth-optimal rings, so the ANALYTIC
        # selector never picks rs_ag — deliberately. Where the explicit
        # decomposition beats the fused psum (scheduling/memory effects
        # the link model cannot see; bench.py --collectives measures it
        # winning the large regime on the CPU mesh), the AUTOTUNER's
        # per-regime dims are the mechanism that finds it. Keeping it
        # costed (not inf) preserves explicit-override and tuner
        # legality.
        return 2 * link.launch_s + 2 * (P - 1) * link.alpha_s + ring_bw
    if algo == "rhd":
        if not is_pow2(P):
            return float("inf")
        r = int(log2(P))
        return 2 * r * (link.launch_s + link.alpha_s) \
            + RHD_BW_PENALTY * ring_bw
    if algo == "two_level":
        if not hier_shape or hier_shape[0] * hier_shape[1] != P:
            return float("inf")
        C, L = hier_shape
        cross_link = dcn_link if dcn else ici
        # local RS + local AG over ICI
        t = 2 * ici.launch_s + 2 * max(L - 1, 0) * ici.alpha_s \
            + 2.0 * N * max(L - 1, 0) / max(L, 1) * ici.beta_s_per_byte
        # cross allreduce on the L-fold smaller piece
        t += cross_link.launch_s + 2 * max(C - 1, 0) * cross_link.alpha_s \
            + 2.0 * (N / max(L, 1)) * max(C - 1, 0) / max(C, 1) \
            * cross_link.beta_s_per_byte
        return t
    raise ValueError(f"unknown collective algorithm {algo!r}; expected one "
                     f"of {ALGORITHMS}")


def crossover_bytes(world: int, *, dcn: bool = False,
                    ici: LinkModel = ICI, dcn_link: LinkModel = DCN) -> int:
    """The latency/bandwidth crossover: bucket bytes where the ring's
    hop term equals its byte term (2*(P-1)*alpha == 2*N*(P-1)/P * beta,
    i.e. N* = alpha*P/beta). Below it a bucket is latency-bound (few-hop
    schedules win), above it bandwidth-bound. Also the small/large split
    the autotuner's per-regime categorical dims learn around."""
    link = dcn_link if dcn else ici
    return max(int(link.alpha_s * max(world, 1) / link.beta_s_per_byte), 1)


def hier_legal(world: int, hier_shape: Optional[Tuple[int, int]], *,
               require_cross: bool = True) -> bool:
    """One home for 'is this hierarchy real': a (cross, local) shape
    covering the world with local>1. `require_cross=False` admits the
    degenerate cross==1 mesh — runnable when FORCED (the legacy toggle
    contract) but pointless to auto-select or DCN-compress, since the
    cross phase is a no-op."""
    return bool(hier_shape) and hier_shape[1] > 1 and \
        hier_shape[0] * hier_shape[1] == world and \
        (hier_shape[0] > 1 or not require_cross)


def runnable_algorithms(world: int,
                        hier_shape: Optional[Tuple[int, int]] = None, *,
                        require_cross: bool = True) -> Tuple[str, ...]:
    """Strategies this deployment can structurally run — the ONE home of
    the candidacy rule (selection, the tuner's choice vocabulary and the
    bench sweep all call this): rhd needs a power-of-two world >1,
    two_level a real hierarchy per `hier_legal`."""
    cands = ["direct", "rs_ag"]
    if is_pow2(world) and world > 1:
        cands.append("rhd")
    if hier_legal(world, hier_shape, require_cross=require_cross):
        cands.append("two_level")
    return tuple(cands)


def select_algorithm(nbytes: int, world: int, *,
                     hier_shape: Optional[Tuple[int, int]] = None,
                     dcn: bool = False,
                     ici: LinkModel = ICI,
                     dcn_link: LinkModel = DCN) -> str:
    """Cost-model pick among the structurally legal algorithms.

    `hier_shape` (cross, local) is considered only when both axes are
    real (>1); ties break in `ALGORITHMS` order so selection is
    deterministic — every rank computes the same answer from the same
    (bytes, world, topology) inputs."""
    if world <= 1 or nbytes < MIN_MODEL_BYTES:
        return "direct"
    cands = runnable_algorithms(world, hier_shape)
    return min(cands, key=lambda a: (
        predict_cost(a, nbytes, world, hier_shape=hier_shape, dcn=dcn,
                     ici=ici, dcn_link=dcn_link), ALGORITHMS.index(a)))


def threshold_bytes(cfg, world: int, *, dcn: bool = False) -> int:
    """Small/large bucket split: the explicit
    HOROVOD_COLLECTIVE_ALGO_THRESHOLD when set, else the analytic
    crossover."""
    t = getattr(cfg, "collective_algo_threshold_bytes", 0)
    return t if t > 0 else crossover_bytes(world, dcn=dcn)


def _legalize(algo: str, world: int, hier_ok: bool, *,
              explicit: bool = False) -> str:
    """Map a requested algorithm onto what this bucket/world can run.

    Fallbacks are pure functions of rank-invariant inputs. An EXPLICIT
    env-forced rhd on a non-power-of-two world fails fast (the setting
    can never take effect); two_level falls back silently like the
    legacy hierarchical toggle always did (per-bucket legality — scale,
    join mask, process set — varies call to call by design)."""
    if algo == "rhd" and not (is_pow2(world) and world > 1):
        if explicit:
            raise ValueError(
                f"HOROVOD_COLLECTIVE_ALGO=rhd requires a power-of-two "
                f"world size (recursive halving/doubling); world is "
                f"{world}. Use 'auto', 'direct' or 'rs_ag'.")
        return "direct"
    if algo == "two_level" and not hier_ok:
        return "direct"
    return algo


def resolve(cfg, nbytes: int, world: int, *, requested: Optional[str] = None,
            hier_ok: bool = False,
            hier_shape: Optional[Tuple[int, int]] = None,
            dcn: bool = False) -> str:
    """Resolve the allreduce algorithm for one bucket.

    Precedence: per-call `requested` > explicit HOROVOD_COLLECTIVE_ALGO
    > legacy hierarchical/torus toggles > autotuner-learned per-regime
    choices (small/large split at `threshold_bytes`) > analytic cost
    model. All inputs are round-synchronized config or bucket
    properties, so resolution is rank-invariant by construction.
    """
    req = (requested or "").strip().lower() or None
    explicit = requested is not None
    if req is None:
        if cfg.collective_algo != "auto":
            req = cfg.collective_algo
            explicit = cfg.collective_algo_set
        elif cfg.hierarchical_allreduce or cfg.torus_allreduce:
            req = "two_level"
    if req is not None and req != "auto":
        if req not in ALGORITHMS:
            raise ValueError(
                f"unknown collective algorithm {req!r}; expected one of "
                f"{ALGO_CHOICES}")
        return _legalize(req, world, hier_ok, explicit=explicit)
    small = getattr(cfg, "collective_algo_small", "")
    large = getattr(cfg, "collective_algo_large", "")
    if small or large:
        cand = small if nbytes < threshold_bytes(cfg, world, dcn=dcn) \
            else large
        if cand and cand != "auto":
            return _legalize(cand, world, hier_ok)
    return select_algorithm(nbytes, world,
                            hier_shape=hier_shape if hier_ok else None,
                            dcn=dcn)
