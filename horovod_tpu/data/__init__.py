"""Data utilities: loaders, sharding, and the compute (data) service.

Re-design of horovod/data/ (BaseDataLoader/AsyncDataLoaderMixin,
data_loader_base.py) and the tf.data-service integration
(tensorflow/data/compute_service.py).
"""
from .loader import (                                          # noqa: F401
    AsyncDataLoaderMixin, BaseDataLoader, shard_indices,
)
from .compute_service import (                                 # noqa: F401
    ComputeClient, ComputeConfig, ComputeService, ComputeWorker,
)
