"""Data compute service: run the input pipeline in a separate job.

Re-design of the reference's tf.data-service integration
(horovod/tensorflow/data/compute_service.py:34 `TfDataServiceConfig`,
compute_worker.py, and the registration protocol in
horovod/runner/common/service/compute_service.py:97,219): a "compute" job
of worker processes runs the user's data pipeline on CPU hosts, and the
training job's ranks stream ready batches from it — decoupling input
preprocessing from accelerator stepping.

TPU-native architecture: the dispatcher is the existing HTTP KV server
(worker registration + discovery — the ComputeService registration role);
each compute worker serves pickled batches over a length-prefixed TCP
socket. Sharding follows the tf.data-service "distributed epoch" mode:
batches are handed out first-come-first-served, so consumers collectively
see every batch exactly once per epoch regardless of relative speed; a
per-consumer round-robin mode mirrors the deterministic sharding mode.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, List, Optional

from ..runner.http_kv import KVStoreClient, KVStoreServer, make_secret

_SCOPE = "compute_workers"
_END = b"__END_OF_EPOCH__"


@dataclass
class ComputeConfig:
    """Serializable handle to a running compute service (the reference's
    TfDataServiceConfig role: everything a training rank needs to
    connect)."""
    dispatcher_addr: str
    dispatcher_port: int
    secret: str
    num_workers: int
    extra: dict = field(default_factory=dict)


class ComputeService:
    """Dispatcher: worker registry on the KV server."""

    def __init__(self, num_workers: int) -> None:
        self.num_workers = num_workers
        self.secret = make_secret()
        self._server = KVStoreServer(secret=self.secret)
        self.port = self._server.start()
        self.addr = "127.0.0.1"

    def config(self, addr: Optional[str] = None) -> ComputeConfig:
        return ComputeConfig(addr or self.addr, self.port, self.secret,
                             self.num_workers)

    def wait_for_workers(self, timeout: float = 60.0) -> List[str]:
        """Block until all workers registered; returns their addresses."""
        kv = KVStoreClient("127.0.0.1", self.port, secret=self.secret)
        deadline = time.monotonic() + timeout
        while True:
            addrs = [kv.get(_SCOPE, str(i)) for i in range(self.num_workers)]
            if all(a is not None for a in addrs):
                return [a.decode() for a in addrs]
            if time.monotonic() > deadline:
                missing = [i for i, a in enumerate(addrs) if a is None]
                raise TimeoutError(
                    f"compute workers {missing} did not register")
            time.sleep(0.05)

    def shutdown(self) -> None:
        self._server.stop()


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("!Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, 8)
    (n,) = struct.unpack("!Q", hdr)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("compute service peer closed")
        buf += chunk
    return buf


class ComputeWorker:
    """One compute-job process: runs `dataset_fn()` (an iterable factory)
    and serves its batches over TCP (compute_worker.py role).

    First-come-first-served batch handout; `reset()` (a new `epoch` id in
    the request) restarts the iterator — the consumer side advances epochs
    collectively.
    """

    def __init__(self, index: int, config: ComputeConfig,
                 dataset_fn: Callable[[], Any]) -> None:
        self.index = index
        self.config = config
        self.dataset_fn = dataset_fn
        self._lock = threading.Lock()
        self._epoch = -1
        self._it: Optional[Iterator] = None
        worker = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    while True:
                        req = pickle.loads(_recv_msg(self.request))
                        _send_msg(self.request,
                                  worker._next_batch(req["epoch"]))
                except (ConnectionError, EOFError):
                    pass

        self._srv = socketserver.ThreadingTCPServer(
            ("0.0.0.0", 0), Handler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        # register with the dispatcher
        kv = KVStoreClient(config.dispatcher_addr, config.dispatcher_port,
                           secret=config.secret)
        kv.put(_SCOPE, str(index),
               f"{socket.gethostname()}:{self.port}".encode())

    def _next_batch(self, epoch: int) -> bytes:
        with self._lock:
            if epoch != self._epoch:
                self._epoch = epoch
                self._it = iter(self.dataset_fn())
            try:
                return pickle.dumps(next(self._it))
            except StopIteration:
                return _END

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class ComputeClient:
    """Training-rank side: pull batches from every worker (reference
    compute-side `ComputeClient`, runner/common/service/compute_service.py:219).

    Iterating yields each worker's batches first-come-first-served until
    all workers are exhausted for the epoch. With `deterministic=True`
    and (rank, num_consumers), rank r only takes workers w where
    w % num_consumers == r — the deterministic sharding mode.
    """

    def __init__(self, config: ComputeConfig, *, rank: int = 0,
                 num_consumers: int = 1, deterministic: bool = False,
                 connect_timeout: float = 60.0) -> None:
        self.config = config
        self.rank = rank
        self.num_consumers = num_consumers
        self.deterministic = deterministic
        kv = KVStoreClient(config.dispatcher_addr, config.dispatcher_port,
                           secret=config.secret)
        deadline = time.monotonic() + connect_timeout
        addrs: List[Optional[bytes]] = []
        while True:
            addrs = [kv.get(_SCOPE, str(i))
                     for i in range(config.num_workers)]
            if all(a is not None for a in addrs):
                break
            if time.monotonic() > deadline:
                raise TimeoutError("compute workers not available")
            time.sleep(0.05)
        self._workers = []
        for i, a in enumerate(addrs):
            if deterministic and i % num_consumers != rank:
                continue
            host, port = a.decode().rsplit(":", 1)
            if host == socket.gethostname():
                host = "127.0.0.1"
            s = socket.create_connection((host, int(port)),
                                         timeout=connect_timeout)
            self._workers.append(s)
        self._epoch = 0

    def batches(self) -> Iterator[Any]:
        """One epoch of batches across this consumer's workers."""
        live = list(self._workers)
        epoch = self._epoch
        self._epoch += 1
        req = pickle.dumps({"epoch": epoch})
        while live:
            for s in list(live):
                _send_msg(s, req)
                payload = _recv_msg(s)
                if payload == _END:
                    live.remove(s)
                    continue
                yield pickle.loads(payload)

    def close(self) -> None:
        for s in self._workers:
            try:
                s.close()
            except OSError:
                pass
