"""Data loading utilities: base loader + async prefetch + shard helper.

Re-design of horovod/data/data_loader_base.py (BaseDataLoader,
AsyncDataLoaderMixin — background-thread prefetch queue) plus the sharding
convention the reference's examples use (DistributedSampler with
num_replicas=hvd.size(), rank=hvd.rank()).

TPU note: the prefetch thread overlaps host-side batch prep with device
steps; pair with `training.shard_batch` to land batches directly in their
mesh sharding (one host->HBM transfer per step).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Iterator, Optional


class BaseDataLoader:
    """Iterable loader contract (data_loader_base.py BaseDataLoader)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Prefetch batches on a background thread
    (data_loader_base.py AsyncDataLoaderMixin).

    Mix in BEFORE the loader class:
        class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader): ...
    """

    def __init__(self, *args, async_loader_queue_size: int = 5, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        self._async_queue: Optional[queue.Queue] = None
        self._async_thread: Optional[threading.Thread] = None
        self._async_stop = threading.Event()
        super().__init__(*args, **kwargs)

    def close_async_loader(self) -> None:
        self._async_stop.set()
        if self._async_queue is not None:
            try:
                while True:
                    self._async_queue.get_nowait()
            except queue.Empty:
                pass
        if self._async_thread is not None:
            self._async_thread.join(timeout=5)
            self._async_thread = None

    def _producer(self) -> None:
        try:
            for batch in super()._iterate():
                if self._async_stop.is_set():
                    return
                self._async_queue.put(batch)
        finally:
            self._async_queue.put(None)  # sentinel

    def __iter__(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            yield from super()._iterate()
            return
        self._async_stop.clear()
        self._async_queue = queue.Queue(self.async_loader_queue_size)
        self._async_thread = threading.Thread(target=self._producer,
                                              daemon=True)
        self._async_thread.start()
        while True:
            batch = self._async_queue.get()
            if batch is None:
                break
            yield batch
        self._async_thread.join(timeout=5)
        self._async_thread = None


def shard_indices(dataset_size: int, rank: int, num_replicas: int,
                  shuffle: bool = False, seed: int = 0,
                  drop_remainder: bool = False):
    """Deterministic per-rank index shard (DistributedSampler semantics)."""
    import random
    idx = list(range(dataset_size))
    if shuffle:
        random.Random(seed).shuffle(idx)
    if drop_remainder:
        per = dataset_size // num_replicas
        idx = idx[: per * num_replicas]
    elif len(idx) % num_replicas != 0:
        idx += idx[: num_replicas - len(idx) % num_replicas]
    return idx[rank::num_replicas]
