"""Ray Tune integration: distributed trials over the actor fleet.

Re-design of the reference's hyperparameter-search flow
(/root/reference/docs/hyperparameter_search.rst: Ray Tune's
DistributedTrainableCreator adapts a Horovod training function so each
Tune trial is itself a distributed job). Here the adapter wraps
RayExecutor: one trial = one fleet running `func(config)` on every
worker, results returned rank-ordered; Tune schedules trials in
parallel subject to the placement resources.

    from horovod_tpu.ray.tune import DistributedTrainableCreator
    trainable = DistributedTrainableCreator(training_function,
                                            num_workers=2)
    analysis = tune.run(trainable, config={"lr": tune.grid_search(...)})

`func(config)` runs on every worker of the trial's fleet with the
launcher identity env set (HOROVOD_RANK/SIZE/...); report metrics from
rank 0 (`ray.tune.report` under real Tune, or just return them — the
trainable returns rank 0's result as the trial result dict).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .runner import RayExecutor


def DistributedTrainableCreator(func: Callable[[Dict], Any],
                                num_workers: int = 1, *,
                                num_slots: Optional[int] = None,
                                num_hosts: Optional[int] = None,
                                workers_per_host: Optional[int] = None,
                                cpus_per_worker: float = 1.0,
                                tpus_per_worker: float = 0.0,
                                use_gpu: bool = False,
                                backend: Optional[Any] = None
                                ) -> Callable[[Dict], Any]:
    """Adapt `func(config)` into a Tune function-trainable whose every
    trial is a `num_workers`-rank distributed job.

    Reference-signature compatibility: `num_slots` (the reference's
    per-trial worker count) and `num_hosts` map onto
    num_workers/workers_per_host; `use_gpu` is accepted and ignored
    (workers use the TPU/XLA data plane). `backend` injects a non-Ray
    actor transport (tests / local debugging).
    """
    if num_slots is not None or num_hosts is not None:
        # reference signature: total = hosts x slots (each defaults 1)
        slots = num_slots if num_slots is not None else 1
        num_workers = slots * (num_hosts or 1)
        if num_hosts is not None and workers_per_host is None:
            workers_per_host = slots

    def trainable(config: Dict, checkpoint_dir: Optional[str] = None):
        ex = RayExecutor(num_workers=num_workers,
                         workers_per_host=workers_per_host,
                         cpus_per_worker=cpus_per_worker,
                         tpus_per_worker=tpus_per_worker,
                         backend=backend)
        ex.start()
        try:
            results = ex.run(func, args=(dict(config),))
        finally:
            ex.shutdown()
        # rank 0's return value is the trial result (dict-valued
        # results integrate with tune.run's analysis dataframes)
        return results[0]

    trainable.__name__ = getattr(func, "__name__", "hvd_trainable")
    return trainable


def run_grid_search(func: Callable[[Dict], Any],
                    param_grid: Dict[str, list],
                    num_workers: int = 1, *,
                    backend: Optional[Any] = None,
                    metric: Optional[str] = None,
                    mode: str = "min") -> Dict[str, Any]:
    """Tune-less fallback: exhaustively run the cartesian grid, one
    distributed trial per point, and return the best config
    (`hyperparameter_search.rst`'s flow without a Ray installation —
    trials run sequentially on the shared fleet resources).

    Each trial's result is rank 0's return value; with `metric` given
    it must be a dict containing that key.
    """
    import itertools

    trainable = DistributedTrainableCreator(func, num_workers,
                                            backend=backend)
    keys = sorted(param_grid)
    best = None
    trials = []
    for values in itertools.product(*(param_grid[k] for k in keys)):
        config = dict(zip(keys, values))
        result = trainable(config)
        trials.append({"config": config, "result": result})
        if metric is not None:
            score = result[metric]
            if best is None or \
                    (score < best[0] if mode == "min" else score > best[0]):
                best = (score, config, result)
    out = {"trials": trials}
    if best is not None:
        out["best_config"] = best[1]
        out["best_result"] = best[2]
    return out
