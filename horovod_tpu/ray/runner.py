"""Ray actor fleet executor.

Re-design of the reference's Ray integration (horovod/ray/runner.py:
`RayExecutor` at :168, `Coordinator` at :45): a fleet of Ray actors is
placed via a placement group, the driver collects each actor's hostname,
assigns Horovod ranks (dense by host, like the reference Coordinator's
node-grouped rank map), pushes the `HOROVOD_*` identity env plus the
native KV-store rendezvous address onto every actor, and then runs user
functions on all workers.

Architecture differences from the reference (TPU-first):

* No Gloo rendezvous: workers get `HOROVOD_NATIVE_KV_ADDR/PORT` pointing at
  the driver's native TCP store (csrc/store.cc) — the same control plane the
  `hvdrun` launcher uses — and the data plane is XLA collectives over the
  worker's local mesh.
* Ray is an optional dependency: all placement/rank logic is pure Python
  (strategy.py, `Coordinator`), and the actor transport is an injectable
  `backend` so tests (and non-Ray schedulers) can run the same executor
  with an in-process backend.
"""
from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runner.hosts import SlotInfo, assign_from_hostnames
from .strategy import PlacementPlan, colocated_plan, spread_plan


class Coordinator:
    """Assign ranks from actor hostnames (reference Coordinator,
    horovod/ray/runner.py:45: node-grouped dense ranks)."""

    def __init__(self) -> None:
        self._hostnames: List[str] = []       # per worker id, in order

    def register(self, hostname: str) -> int:
        """Register one worker; returns its worker id."""
        self._hostnames.append(hostname)
        return len(self._hostnames) - 1

    @property
    def world_size(self) -> int:
        return len(self._hostnames)

    def slots(self) -> List[SlotInfo]:
        """SlotInfo per worker id: workers grouped by host (first-seen host
        order, like the reference's registration-ordered node list), dense
        global ranks by host then arrival."""
        return assign_from_hostnames(self._hostnames)


def worker_env(slot: SlotInfo, kv_addr: Optional[str], kv_port: Optional[int],
               extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The identity env pushed onto each actor (gloo_run.py:66-78 names)."""
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_HOSTNAME": slot.hostname,
    }
    if kv_addr is not None:
        env["HOROVOD_NATIVE_KV_ADDR"] = kv_addr
        env["HOROVOD_NATIVE_KV_PORT"] = str(kv_port)
    if extra:
        env.update(extra)
    return env


class BaseHorovodWorker:
    """The actor body (reference BaseHorovodWorker, horovod/ray/worker.py).

    Instantiated remotely (ray.remote) or in-process (tests/local backend).
    """

    def __init__(self, world_rank: int = 0) -> None:
        self.world_rank = world_rank

    def hostname(self) -> str:
        return socket.gethostname()

    def update_env_vars(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def env_vars(self) -> Dict[str, str]:
        return dict(os.environ)

    def execute(self, fn: Callable, args: Sequence = (),
                kwargs: Optional[dict] = None) -> Any:
        return fn(*args, **(kwargs or {}))


class _LocalBackend:
    """In-process actor transport: same surface the Ray backend provides,
    used by tests and usable for single-host debugging without Ray."""

    def start_workers(self, plan: PlacementPlan) -> List[Any]:
        return [BaseHorovodWorker(world_rank=i)
                for i in range(plan.num_workers)]

    def call(self, worker: Any, method: str, *args: Any, **kw: Any) -> Any:
        return getattr(worker, method)(*args, **kw)

    def call_all(self, workers: List[Any], method: str,
                 argss: Optional[List[tuple]] = None) -> List[Any]:
        argss = argss or [() for _ in workers]
        return [getattr(w, method)(*a) for w, a in zip(workers, argss)]

    def wait(self, refs: List[Any]) -> List[Any]:
        return list(refs)

    def stop_workers(self, workers: List[Any]) -> None:
        pass


class _RayBackend:
    """Ray actor transport: placement group + one actor per worker."""

    def __init__(self) -> None:
        import ray                                     # gated import
        self._ray = ray
        self._pg = None

    def start_workers(self, plan: PlacementPlan) -> List[Any]:
        ray = self._ray
        from ray.util.placement_group import placement_group
        self._pg = placement_group(plan.bundles, strategy=plan.strategy)
        # bounded wait: an infeasible group (node died since discovery)
        # must surface as a round failure, not block forever
        ray.get(self._pg.ready(), timeout=120)
        RemoteWorker = ray.remote(BaseHorovodWorker)
        workers, rank = [], 0
        for bundle_idx, w in enumerate(plan.workers_per_bundle):
            for _ in range(w):
                workers.append(
                    RemoteWorker.options(
                        num_cpus=plan.worker_resources.get("CPU", 1),
                        resources={k: v for k, v in
                                   plan.worker_resources.items()
                                   if k not in ("CPU", "GPU")} or None,
                        placement_group=self._pg,
                        placement_group_bundle_index=bundle_idx,
                    ).remote(world_rank=rank))
                rank += 1
        return workers

    def call(self, worker: Any, method: str, *args: Any, **kw: Any) -> Any:
        return self._ray.get(getattr(worker, method).remote(*args, **kw))

    def call_all(self, workers: List[Any], method: str,
                 argss: Optional[List[tuple]] = None) -> List[Any]:
        argss = argss or [() for _ in workers]
        return self._ray.get([getattr(w, method).remote(*a)
                              for w, a in zip(workers, argss)])

    def wait(self, refs: List[Any]) -> List[Any]:
        return self._ray.get(refs)

    def stop_workers(self, workers: List[Any]) -> None:
        for w in workers:
            self._ray.kill(w, no_restart=True)
        if self._pg is not None:
            from ray.util.placement_group import remove_placement_group
            remove_placement_group(self._pg)
            self._pg = None


def establish_rendezvous(backend, workers, env_vars=None, extra_env=None):
    """Shared fleet-rendezvous tail (the Coordinator.establish_rendezvous
    role in the reference): rank assignment from the actors' REAL
    placement + KV-store control-plane setup + identity env push.
    Returns (slots, kv_server-or-None). Used by RayExecutor.start and
    ElasticRayExecutor.run so the two paths cannot diverge."""
    coord = Coordinator()
    hostnames = backend.call_all(workers, "hostname")
    for hn in hostnames:
        coord.register(hn)
    slots = coord.slots()
    kv_addr = kv_port = kv_server = None
    try:
        from ..native.store import StoreServer
        kv_server = StoreServer()
        kv_addr, kv_port = socket.gethostname(), kv_server.port
        # loopback ONLY when the single worker host IS this driver host —
        # a remote single-host fleet must still dial the driver
        if set(hostnames) == {socket.gethostname()}:
            kv_addr = "127.0.0.1"
    except Exception:  # noqa: BLE001 — toolchain-less driver host
        kv_server = None
    try:
        backend.call_all(
            workers, "update_env_vars",
            [(dict(worker_env(s, kv_addr, kv_port, env_vars),
                   **(extra_env or {})),)
             for s in slots])
    except Exception:
        # a failed env push means the server never reaches the caller —
        # close it here or the socket lingers for the exception's lifetime
        if kv_server is not None:
            kv_server.close()
        raise
    return slots, kv_server


class RayExecutor:
    """Driver-side fleet manager (reference RayExecutor,
    horovod/ray/runner.py:168).

    Usage::

        ex = RayExecutor(num_workers=4, workers_per_host=2)
        ex.start()
        results = ex.run(train_fn, args=(cfg,))
        ex.shutdown()
    """

    def __init__(self, num_workers: int = 1, *,
                 workers_per_host: Optional[int] = None,
                 cpus_per_worker: float = 1.0,
                 tpus_per_worker: float = 0.0,
                 use_current_placement_group: bool = False,
                 env_vars: Optional[Dict[str, str]] = None,
                 backend: Optional[Any] = None) -> None:
        self.num_workers = num_workers
        self.env_vars = dict(env_vars or {})
        if workers_per_host:
            self.plan = colocated_plan(num_workers, workers_per_host,
                                       cpus_per_worker, tpus_per_worker)
        else:
            self.plan = spread_plan(num_workers, cpus_per_worker,
                                    tpus_per_worker)
        self.use_current_placement_group = use_current_placement_group
        self._backend = backend            # None -> Ray, lazily
        self.workers: List[Any] = []
        self.slots: List[SlotInfo] = []
        self._kv_server = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._backend is None:
            self._backend = _RayBackend()
        self.workers = self._backend.start_workers(self.plan)
        self.slots, self._kv_server = establish_rendezvous(
            self._backend, self.workers, self.env_vars)

    def shutdown(self) -> None:
        if self._backend is not None and self.workers:
            self._backend.stop_workers(self.workers)
        self.workers = []
        if self._kv_server is not None:
            self._kv_server.close()
            self._kv_server = None

    # -- execution (reference run/run_remote/execute/execute_single) -------
    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        """Run fn on every worker; returns per-rank results."""
        self._require_started()
        return self._backend.call_all(
            self.workers, "execute",
            [(fn, args, kwargs) for _ in self.workers])

    def run_remote(self, fn: Callable, args: Sequence = (),
                   kwargs: Optional[dict] = None) -> List[Any]:
        """Async variant: returns backend refs; resolve with wait()."""
        self._require_started()
        ray = getattr(self._backend, "_ray", None)
        if ray is None:                    # local backend is synchronous
            return self.run(fn, args, kwargs)
        return [w.execute.remote(fn, args, kwargs) for w in self.workers]

    def wait(self, refs: List[Any]) -> List[Any]:
        self._require_started()
        return self._backend.wait(refs)

    def execute(self, fn: Callable[[Any], Any]) -> List[Any]:
        """Apply fn(worker_local_state=None) on every worker."""
        return self.run(fn)

    def execute_single(self, fn: Callable, args: Sequence = (),
                       kwargs: Optional[dict] = None) -> Any:
        """Run fn on rank 0 only."""
        self._require_started()
        idx = next(i for i, s in enumerate(self.slots) if s.rank == 0)
        return self._backend.call(self.workers[idx], "execute",
                                  fn, args, kwargs)

    def _require_started(self) -> None:
        if not self.workers:
            raise RuntimeError("RayExecutor.start() has not been called")
