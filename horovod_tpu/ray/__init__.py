"""Ray integration: actor-fleet executor + elastic host discovery.

Re-design of horovod/ray/ (RayExecutor runner.py:168, strategies
strategy.py, RayHostDiscovery elastic.py) with Ray as an optional
dependency: placement/rank logic is pure Python, the actor transport is
injectable, and the data plane on each worker is horovod_tpu's XLA
collectives.
"""
from .runner import (                                          # noqa: F401
    BaseHorovodWorker, Coordinator, RayExecutor, worker_env,
)
from .strategy import (                                        # noqa: F401
    PlacementPlan, colocated_plan, spread_plan,
)
from .elastic import ElasticRayExecutor, RayHostDiscovery      # noqa: F401
from .tune import (                                            # noqa: F401
    DistributedTrainableCreator, run_grid_search,
)
