"""Placement planning for the Ray executor.

Re-design of the reference's placement strategies
(horovod/ray/strategy.py: ColocatedStrategy / PackStrategy — placement-group
bundle layout deciding how workers land on hosts). The bundle math is pure
Python here so it is unit-testable without a Ray cluster; the executor feeds
the resulting spec to `ray.util.placement_group` at start time.

TPU angle: one worker per host is the natural layout (a single jax process
drives every local chip), which is `workers_per_host=1` colocated bundles
with `tpus_per_worker` custom resources.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass(frozen=True)
class PlacementPlan:
    """Bundle list + ray placement strategy + per-worker resource needs."""
    bundles: List[Dict[str, float]]
    strategy: str                      # "PACK" | "STRICT_PACK" | "SPREAD"
    workers_per_bundle: List[int]      # how many workers share each bundle
    worker_resources: Dict[str, float] = field(default_factory=dict)

    @property
    def num_workers(self) -> int:
        return sum(self.workers_per_bundle)


def colocated_plan(num_workers: int, workers_per_host: int,
                   cpus_per_worker: float = 1.0,
                   tpus_per_worker: float = 0.0,
                   extra_resources: Optional[Dict[str, float]] = None,
                   ) -> PlacementPlan:
    """Whole-host bundles: each bundle holds `workers_per_host` workers.

    Mirrors the reference ColocatedStrategy (horovod/ray/strategy.py): the
    last bundle may be partial when num_workers % workers_per_host != 0.
    STRICT_PACK pins each bundle to one node so local collectives ride
    shared memory / ICI.
    """
    if num_workers <= 0 or workers_per_host <= 0:
        raise ValueError("num_workers and workers_per_host must be positive")
    extra = dict(extra_resources or {})
    per_worker: Dict[str, float] = {"CPU": cpus_per_worker, **extra}
    if tpus_per_worker:
        per_worker["TPU"] = tpus_per_worker
    bundles, per_bundle_workers = [], []
    remaining = num_workers
    while remaining > 0:
        w = min(workers_per_host, remaining)
        bundles.append({k: v * w for k, v in per_worker.items()})
        per_bundle_workers.append(w)
        remaining -= w
    return PlacementPlan(bundles=bundles, strategy="STRICT_PACK",
                         workers_per_bundle=per_bundle_workers,
                         worker_resources=per_worker)


def spread_plan(num_workers: int, cpus_per_worker: float = 1.0,
                tpus_per_worker: float = 0.0,
                extra_resources: Optional[Dict[str, float]] = None,
                ) -> PlacementPlan:
    """One worker per bundle, spread across hosts (reference PackStrategy
    with SPREAD scheduling): maximizes per-worker bandwidth on CPU
    clusters."""
    if num_workers <= 0:
        raise ValueError("num_workers must be positive")
    extra = dict(extra_resources or {})
    per_worker: Dict[str, float] = {"CPU": cpus_per_worker, **extra}
    if tpus_per_worker:
        per_worker["TPU"] = tpus_per_worker
    return PlacementPlan(bundles=[dict(per_worker)] * num_workers,
                         strategy="SPREAD",
                         workers_per_bundle=[1] * num_workers,
                         worker_resources=per_worker)
