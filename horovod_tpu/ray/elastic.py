"""Ray-native host discovery for elastic training.

Re-design of the reference's `RayHostDiscovery`
(horovod/ray/elastic.py): instead of polling a user shell script, ask the
Ray GCS for the current set of alive nodes and their resources, and present
them through the same `HostDiscovery` interface the elastic driver polls
(elastic/discovery.py) — so `ElasticDriver` works unchanged on a Ray
cluster that autoscales.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ..elastic.discovery import HostDiscovery


def _default_nodes() -> List[dict]:
    import ray                                         # gated import
    return ray.nodes()


class RayHostDiscovery(HostDiscovery):
    """Map alive Ray nodes to {hostname: slots}.

    slots per host = floor(resource / per-worker need), using TPU custom
    resources when `use_tpu` else CPUs — the reference's GPU/CPU logic
    (horovod/ray/elastic.py RayHostDiscovery.find_available_hosts_and_slots)
    re-targeted at TPU resources.
    """

    def __init__(self, use_tpu: bool = False, cpus_per_slot: float = 1.0,
                 tpus_per_slot: float = 1.0,
                 nodes_fn: Optional[Callable[[], List[dict]]] = None) -> None:
        self.use_tpu = use_tpu
        self.cpus_per_slot = cpus_per_slot
        self.tpus_per_slot = tpus_per_slot
        self._nodes_fn = nodes_fn or _default_nodes

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        hosts: Dict[str, int] = {}
        for node in self._nodes_fn():
            if not node.get("Alive", False):
                continue
            resources: Dict[str, Any] = node.get("Resources", {}) or {}
            hostname = node.get("NodeManagerHostname") or \
                node.get("NodeManagerAddress")
            if not hostname:
                continue
            if self.use_tpu:
                slots = int(resources.get("TPU", 0) // self.tpus_per_slot)
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            if slots > 0:
                hosts[hostname] = hosts.get(hostname, 0) + slots
        return hosts
