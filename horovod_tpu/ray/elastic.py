"""Ray-native elastic training: autoscaler-aware discovery + the
fault-tolerant executor loop.

Re-design of the reference's `RayHostDiscovery` + `ElasticRayExecutor`
(horovod/ray/elastic.py:479, elastic_v2.py): discovery asks the Ray GCS
for alive nodes; the executor runs rounds of actors over the discovered
topology, blacklists hosts whose actors die, and relaunches until the
user function completes (bounded by reset_limit) — the Ray flavor of
runner/elastic/driver.py supervision.
"""
from __future__ import annotations

import logging
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..elastic.discovery import HostDiscovery, HostManager

logger = logging.getLogger("horovod_tpu")


def _default_nodes() -> List[dict]:
    import ray                                         # gated import
    return ray.nodes()


class RayHostDiscovery(HostDiscovery):
    """Map alive Ray nodes to {hostname: slots}.

    slots per host = floor(resource / per-worker need), using TPU custom
    resources when `use_tpu` else CPUs — the reference's GPU/CPU logic
    (horovod/ray/elastic.py RayHostDiscovery.find_available_hosts_and_slots)
    re-targeted at TPU resources.
    """

    def __init__(self, use_tpu: bool = False, cpus_per_slot: float = 1.0,
                 tpus_per_slot: float = 1.0,
                 nodes_fn: Optional[Callable[[], List[dict]]] = None) -> None:
        self.use_tpu = use_tpu
        self.cpus_per_slot = cpus_per_slot
        self.tpus_per_slot = tpus_per_slot
        self._nodes_fn = nodes_fn or _default_nodes

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        hosts: Dict[str, int] = {}
        for node in self._nodes_fn():
            if not node.get("Alive", False):
                continue
            resources: Dict[str, Any] = node.get("Resources", {}) or {}
            hostname = node.get("NodeManagerHostname") or \
                node.get("NodeManagerAddress")
            if not hostname:
                continue
            if self.use_tpu:
                slots = int(resources.get("TPU", 0) // self.tpus_per_slot)
            else:
                slots = int(resources.get("CPU", 0) // self.cpus_per_slot)
            if slots > 0:
                hosts[hostname] = hosts.get(hostname, 0) + slots
        return hosts


class ElasticRayExecutor:
    """Fault-tolerant actor-fleet executor (reference ElasticRayExecutor,
    horovod/ray/elastic.py:479 / elastic_v2.py ElasticAdapter).

    Each round: poll discovery -> pick the world size (min_np..max_np
    over non-blacklisted hosts) -> start one actor per slot, assigning
    ranks from the actors' REAL placement -> run `fn` on all. An actor
    failure blacklists its host (cooldown + resurrection via HostManager)
    and starts the next round; `fn` is responsible for resuming from
    committed state (hvd.elastic.run / FileBackedState), exactly as in the
    launcher-based elastic path. `reset_limit` bounds rounds.

    `backend` is injectable (tests use an in-process backend; production
    uses the Ray actor backend from ray/runner.py)."""

    def __init__(self, discovery: HostDiscovery, *, min_np: int = 1,
                 max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 env_vars: Optional[Dict[str, str]] = None,
                 backend: Optional[Any] = None,
                 cpus_per_worker: float = 1.0) -> None:
        self.manager = HostManager(discovery)
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        self.env_vars = dict(env_vars or {})
        self.cpus_per_worker = cpus_per_worker
        self._backend = backend
        self.resets = 0

    def _current_np(self) -> Optional[int]:
        """World size for the next round from non-blacklisted discovery,
        clamped to [min_np, max_np]. Rank blocks are assigned later from
        the actors' REAL placement (establish_rendezvous), so only the
        count matters here. NOTE: actor placement itself is Ray's choice
        — a blacklisted-but-alive node that Ray reuses fails its next
        round too, refreshing the blacklist until its cooldown passes;
        rounds are bounded by reset_limit."""
        hosts = self.manager.current_hosts()
        np_ = sum(h.slots for h in hosts)
        if self.max_np is not None:
            np_ = min(np_, self.max_np)
        return np_ if np_ >= self.min_np else None

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        """Run fn elastically; returns the per-rank results of the first
        round that completes on every worker."""
        import time

        from .runner import _RayBackend, establish_rendezvous, spread_plan

        if self._backend is None:
            self._backend = _RayBackend()
        while True:
            np_ = self._current_np()
            if np_ is None:
                time.sleep(1.0)
                continue
            workers: List[Any] = []
            kv_server = None
            worker_hosts: List[Optional[str]] = []
            try:
                # actor startup is part of the round: a placement failure
                # (node died since discovery) resets like any other
                plan = spread_plan(np_, self.cpus_per_worker, 0.0)
                workers = self._backend.start_workers(plan)
                worker_hosts = [None] * len(workers)
                # rank assignment from ACTUAL placement + KV rendezvous +
                # identity env (shared with RayExecutor.start)
                shm_gen = str(uuid.uuid4().int & ((1 << 62) - 1))
                slots, kv_server = establish_rendezvous(
                    self._backend, workers, self.env_vars,
                    extra_env={"HOROVOD_SHM_GEN": shm_gen})
                worker_hosts = [s.hostname for s in slots]
                return self._backend.call_all(
                    workers, "execute",
                    [(fn, tuple(args), kwargs) for _ in workers])
            except Exception as e:  # noqa: BLE001 - actor death / fn error
                failed = self._failed_hosts(workers, worker_hosts)
                logger.warning(
                    "elastic ray round failed (%s); blacklisting %s and "
                    "resetting", e, failed or "nothing")
                for hn in failed:
                    self.manager.blacklist(hn)
                self.resets += 1
                if self.reset_limit is not None and \
                        self.resets > self.reset_limit:
                    raise RuntimeError(
                        f"reset_limit ({self.reset_limit}) exceeded") from e
            finally:
                if kv_server is not None:
                    kv_server.close()
                try:
                    self._backend.stop_workers(workers)
                except Exception:  # noqa: BLE001
                    pass

    def _failed_hosts(self, workers,
                      worker_hosts: List[Optional[str]]) -> List[str]:
        """Probe which actors are dead after a failed round, reporting
        the hosts recorded at placement time (ElasticDriver's
        _handle_worker_exit analog: exit -> blacklist). Deaths before the
        placement query leave the host unknown — nothing is blacklisted
        and the next round simply retries."""
        failed = []
        for w, hn in zip(workers, worker_hosts):
            try:
                self._backend.call(w, "hostname")
            except Exception:  # noqa: BLE001 - actor is gone
                if hn:
                    failed.append(hn)
        return failed
