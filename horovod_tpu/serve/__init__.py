"""horovod_tpu.serve: TPU-native continuous-batching inference.

The first request-path subsystem of the tree: an Orca/vLLM-style
continuous batcher over the pjit-sharded decoder models, reusing the
training stack's mesh/TP machinery for the forward path and the
timeline for observability. See docs/serving.md for the architecture
and the bucket/no-recompile contract.

    queue.py     admission control: bounded queue, deadlines, load shed
    kv_cache.py  KV storage: slotted rows and vLLM-style paged blocks
                 (BlockPool free-list allocator, per-block crc ledger)
    prefix.py    radix prefix cache: shared system prompts computed
                 once, refcounted block runs, CoW at divergence, LRU
                 eviction, weight-version flush
    batcher.py   iteration-level scheduler over fixed bucket shapes,
                 with optional speculative decoding (draft proposes k,
                 target verifies in one fused step; greedy accept is
                 bit-identical, sampled accept is rejection-sampling
                 distribution-correct)
    executor.py  the one jitted step, sharded via parallel/tp rules;
                 decode kernel (HOROVOD_SERVE_KERNEL: fused Pallas vs
                 XLA oracle, ops/pallas_paged.py) and on-device
                 sampling (temperature/top-p, per-request seeds as
                 row data) resolved/fused at build
    http.py      optional stdlib front end (/generate, /healthz)
    fleet.py     health-aware router over N replicas: accrual-driven
                 ejection, at-most-once failover, drain-on-SIGTERM,
                 re-admission on fresh streamed weights
    wire.py      framed dispatch protocol + retryable-vs-fatal
                 classification for the multi-process fleet
    worker.py    one replica as one OS process: endpoint with replay
                 dedupe, KV heartbeats, startup weight gate
    proc_fleet.py multi-process fleet router: accrual sweep over real
                 heartbeat keys, dispatch over the resilience ladder,
                 SIGKILL-survivable respawn gated on fresh weights
    disagg.py    prefill/decode DISAGGREGATED serving: two dedicated
                 worker-process pools, prompt KV computed in the
                 prefill pool and MIGRATED block-by-block to a decode
                 replica (bit-identical continuation, bounded
                 re-prefill on any failure, per-pool healthz)
    kv_migrate.py live paged-KV block migration: pack/verify/install
                 with per-block crc32 ledgers, binary wire frames and
                 weight-version fencing (plan/transport split)
    kvtier/      fleet-wide KV tier: router-side radix index over
                 cached prefix runs (cross-replica prefix routing +
                 run pulls) and the per-replica HBM -> host-RAM ->
                 disk eviction ladder with crc-verified promotion
                 and weight-version fencing
    soak.py      serving SLO soaks under seeded chaos plans — in-
                 process, multi-process and disaggregated
                 (tools/serve_soak.py CLI; docs/serving.md)
"""
from .batcher import ContinuousBatcher, ReplicaDead            # noqa: F401
from .disagg import DisaggRouter                               # noqa: F401
from .executor import ShardedExecutor                          # noqa: F401
from .fleet import FleetHandle, FleetRouter, Replica           # noqa: F401
from .http import (                                            # noqa: F401
    make_fleet_server, make_server, retry_after_seconds, serve_http,
)
from .proc_fleet import ProcessFleetRouter, ProcessReplica     # noqa: F401
from .kv_cache import (                                        # noqa: F401
    BlockPool, PagedKVCache, SlotKVCache, cached_attention,
    masked_attention, paged_attention, paged_model_kwargs,
    pool_blocks_for, write_kv, write_kv_paged,
)
from .kvtier import (                                          # noqa: F401
    DiskTier, FleetRadixIndex, HostRing, ReplicaKVTier, TierEntry,
    prefer_holders, read_spill_file,
)
from .prefix import RadixPrefixCache                           # noqa: F401
from .queue import (                                           # noqa: F401
    AdmissionQueue, AdmitDropped, Rejected, ServeHandle, ServeRequest,
)
