"""horovod_tpu.serve: TPU-native continuous-batching inference.

The first request-path subsystem of the tree: an Orca/vLLM-style
continuous batcher over the pjit-sharded decoder models, reusing the
training stack's mesh/TP machinery for the forward path and the
timeline for observability. See docs/serving.md for the architecture
and the bucket/no-recompile contract.

    queue.py     admission control: bounded queue, deadlines, load shed
    kv_cache.py  slotted KV cache: device-side math + host accounting
    batcher.py   iteration-level scheduler over fixed bucket shapes
    executor.py  the one jitted step, sharded via parallel/tp rules
    http.py      optional stdlib front end (/generate, /healthz)
"""
from .batcher import ContinuousBatcher                         # noqa: F401
from .executor import ShardedExecutor                          # noqa: F401
from .http import make_server, serve_http                      # noqa: F401
from .kv_cache import SlotKVCache, cached_attention, write_kv  # noqa: F401
from .queue import (                                           # noqa: F401
    AdmissionQueue, Rejected, ServeHandle, ServeRequest,
)
