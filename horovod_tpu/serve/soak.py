"""Serving SLO soak: prove bad days are survivable, don't claim it.

The serve-plane sibling of chaos/soak.py (same philosophy, same
verdict discipline): ``run_serve_soak`` stands up an N-replica
:class:`~horovod_tpu.serve.fleet.FleetRouter` over a tiny decode-mode
GPT, drives CLOSED-LOOP synthetic traffic at a fixed offered load
(``clients`` concurrent requesters, each with at most one request
outstanding), and fires a seeded serve-profile chaos plan at it —
one replica crashed mid-decode, a second partitioned from the router,
a KV block corrupted (a slot when running the slotted layout), one
replica slowed past the suspect threshold, one admission dropped at
the queue door — while a training-side
:class:`~horovod_tpu.redist.stream.WeightPublisher` pushes a fresh
weight version mid-incident. The verdict (a JSON-able dict,
``tools/serve_soak.py`` prints it and exits non-zero unless every
invariant holds) asserts:

* **zero silent drops** — every submitted request reached a terminal
  state (answered, deadline, clean error, or rejected), and every
  shed/rejected answer carries ``retry_after_ms``;
* **at-most-once** — no request was answered twice (``resolutions``
  <= 1 on every handle; late ghost answers are counted as suppressed
  duplicates, not deliveries);
* **KV containment** — the injected cache corruption was caught by the
  crc ledger (per-BLOCK when paged, per-slot when slotted;
  ``detected >= injected > 0``): a corrupted sequence re-prefills or
  fails cleanly, never returns garbage;
* **bounded failover** — the crashed replica was ejected within
  ``2 x suspect_s`` of the crash (detection in O(heartbeat), not
  O(request timeout));
* **SLO held outside recovery windows** — p99 latency and error rate
  of requests that do not overlap any fault's
  ``[t_fault, t_fault + recovery_window_s]`` stay under the declared
  bounds (inside the windows, shed-with-retry-after is the contract);
* **capacity restored on fresh weights** — the fleet ends with every
  replica up and every replica (the restarted victim included) serving
  the NEWEST published weight version.

``evaluate_serve`` is the pure records->verdict core, unit-testable on
synthetic logs exactly like chaos/soak.py's ``evaluate``.

``run_fleet_soak`` / ``evaluate_fleet`` are the MULTI-PROCESS siblings
(``tools/serve_soak.py --processes``): real replica worker processes
behind a ``ProcessFleetRouter``, a seeded plan that SIGKILLs one
worker mid-traffic and fires ``conn_reset``/``flaky`` blips on the
dispatch wire, and a verdict that additionally asserts the blips were
absorbed by the retry ladder with ZERO failovers, replayed dispatches
were served deduped results (answered-exactly-once across the process
boundary), and the respawned victim re-admitted on the newest
published weight version.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("horovod_tpu")

DEFAULT_REPLICAS = 3
DEFAULT_CLIENTS = 6
DEFAULT_STEPS = 240          # scheduler-iteration horizon the plan lands in
DEFAULT_SUSPECT_S = 1.0
DEFAULT_INTERVAL_S = 0.25
DEFAULT_SLO_P99_MS = 15000.0
DEFAULT_SLO_ERROR_RATE = 0.02
DEFAULT_RECOVERY_WINDOW_S = 6.0
#: disruptions that open a recovery window in the SLO evaluation
_DISRUPTIVE = ("crash", "slow_rank", "partition", "corrupt", "drop",
               "delay")
#: the PROCESS-fleet soak's default suspect threshold: heartbeats now
#: cross a real process boundary, and on a small/oversubscribed box
#: (CI runs this on 2 cores) two worker processes can co-stall past
#: 1 s without either being dead — a margin that tight turns scheduler
#: hiccups into unscheduled failovers the verdict rightly refuses to
#: call green. 2 s keeps detection O(heartbeat) (bound 2x = 4 s) while
#: staying honest about what a loaded host can promise.
FLEET_SUSPECT_S = 2.0


def _resolve_plan(plan, seed: int, replicas: int, steps: int):
    from ..chaos.plan import ChaosPlan, random_plan
    if plan is None or plan == "random":
        return random_plan(seed, replicas, steps, profile="serve")
    if isinstance(plan, ChaosPlan):
        return plan
    return ChaosPlan.parse(str(plan))


def evaluate_serve(records: List[dict], events: List[dict], plan,
                   fleet_stats: dict, *, replicas: int,
                   suspect_s: float, slo_p99_ms: float,
                   slo_error_rate: float, recovery_window_s: float,
                   newest_version: Optional[int],
                   kv_injected: int, kv_detected: int) -> dict:
    """Pure records->verdict core. ``records`` is one dict per client
    request ({fid, t0, t1, status, retry_after_ms, latency_ms,
    resolutions}); ``events`` mixes injector ({kind: "chaos", ...})
    and router ({kind: "fleet", event: eject/readmit, ...}) entries,
    each with a wall-clock ``t``."""
    v: Dict[str, Any] = {
        "submitted": len(records),
        "statuses": {},
        "no_silent_drops": None, "answered_once": None,
        "shed_carry_retry_after": None, "kv_containment": None,
        "failover_bounded": None, "failover_s": None,
        "slo_held": None, "p99_outside_ms": None,
        "error_rate_outside": None, "clean_ok_samples": None,
        "capacity_restored": None, "victim": None,
        "kv_injected": kv_injected, "kv_detected": kv_detected,
        "duplicates_suppressed":
            fleet_stats.get("duplicates_suppressed", 0),
    }
    statuses: Dict[str, int] = {}
    for r in records:
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    v["statuses"] = statuses

    # -- zero silent drops: every request reached a terminal state
    v["no_silent_drops"] = (
        len(records) > 0
        and all(r["status"] != "pending" for r in records)
        and fleet_stats.get("inflight", 0) == 0)

    # -- at-most-once: no handle resolved twice
    v["answered_once"] = all(r.get("resolutions", 1) <= 1
                             for r in records)

    # -- every shed/rejected answer carries a retry hint
    shed = [r for r in records if r["status"] in ("shed", "rejected")]
    v["shed_carry_retry_after"] = all(
        (r.get("retry_after_ms") or 0) > 0 for r in shed)

    # -- KV containment: the scheduled corruption actually flipped
    # bytes AND the crc caught it (a plan that schedules a corrupt
    # which never lands proves nothing — fail, don't skip). Keyed on
    # the serve.kv site: a serve.migrate corrupt is the DISAGG soak's
    # business (evaluate_disagg migrate_corrupt_caught), not this
    # counter pair's.
    has_corrupt = any(f.kind == "corrupt" and f.site == "serve.kv"
                      for f in plan.faults)
    if has_corrupt:
        v["kv_containment"] = kv_injected > 0 and \
            kv_detected >= kv_injected
    # requests must never carry garbage: an "ok" that raced a detected
    # corruption is impossible by construction (verify-before-resolve),
    # so the evidence is the counter pair above.

    # -- bounded failover for the crashed replica
    crash = next((f for f in plan.faults if f.kind == "crash"), None)
    if crash is not None:
        v["victim"] = crash.peer
        t_crash = next((e["t"] for e in events
                        if e.get("kind") == "chaos"
                        and e.get("fault") == "crash"), None)
        t_eject = next((e["t"] for e in events
                        if e.get("kind") == "fleet"
                        and e.get("event") == "eject"
                        and e.get("replica") == crash.peer
                        and (t_crash is None or e["t"] >= t_crash)),
                       None)
        if t_crash is None or t_eject is None:
            v["failover_bounded"] = False   # never exercised: fail
        else:
            v["failover_s"] = round(t_eject - t_crash, 3)
            v["failover_bounded"] = \
                v["failover_s"] <= 2 * suspect_s

    # -- SLO outside recovery windows
    windows = [(e["t"], e["t"] + recovery_window_s) for e in events
               if e.get("kind") == "chaos"
               and e.get("fault") in _DISRUPTIVE]
    # an ejection's repair tail counts as disruption too (restart +
    # rewarm of the victim)
    windows += [(e["t"], e["t"] + recovery_window_s) for e in events
                if e.get("kind") == "fleet"
                and e.get("event") == "eject"]

    def outside(r):
        return not any(r["t0"] < hi and r["t1"] > lo
                       for lo, hi in windows)

    clean = [r for r in records if outside(r)]
    oks = sorted(r["latency_ms"] for r in clean
                 if r["status"] == "ok"
                 and r.get("latency_ms") is not None)
    v["clean_ok_samples"] = len(oks)
    served = [r for r in clean
              if r["status"] not in ("shed", "rejected")]
    errs = [r for r in served if r["status"] in ("error", "expired")]
    if len(oks) >= 20:
        # nearest-rank p99 over the outside-window completions
        v["p99_outside_ms"] = round(
            oks[min(len(oks) - 1, int(0.99 * len(oks)))], 1)
        v["error_rate_outside"] = round(
            len(errs) / max(len(served), 1), 4)
        v["slo_held"] = (v["p99_outside_ms"] <= slo_p99_ms
                         and v["error_rate_outside"] <= slo_error_rate)
    else:
        v["slo_held"] = False   # too few clean samples to claim an SLO

    # -- capacity restored on fresh weights
    versions = [r.get("weights_version")
                for r in fleet_stats.get("replicas", {}).values()]
    readmitted = (crash is None or any(
        e.get("kind") == "fleet" and e.get("event") == "readmit"
        and e.get("replica") == crash.peer for e in events))
    v["capacity_restored"] = (
        fleet_stats.get("replicas_up") == replicas
        and readmitted
        and newest_version is not None
        and all(ver == newest_version for ver in versions))

    v["ok"] = all(v[k] is not False for k in (
        "no_silent_drops", "answered_once", "shed_carry_retry_after",
        "kv_containment", "failover_bounded", "slo_held",
        "capacity_restored"))
    return v


def evaluate_fleet(records: List[dict], events: List[dict], plan,
                   fleet_stats: dict, *, replicas: int,
                   suspect_s: float, slo_p99_ms: float,
                   slo_error_rate: float, recovery_window_s: float,
                   newest_version: Optional[int],
                   dispatch_absorbed: int,
                   dedupe_hits: int) -> dict:
    """The MULTI-PROCESS fleet verdict: everything
    :func:`evaluate_serve` asserts (no silent drops, answered-once,
    shed-carries-retry-after, bounded failover, SLO outside recovery
    windows, capacity restored on the newest weights), plus the
    process-boundary invariants:

    * **blips_absorbed** — the scheduled ``serve.dispatch``
      ``conn_reset``/``flaky`` blips were absorbed by the retry ladder
      (``hvd_net_retries_total{site="serve.dispatch",
      outcome="absorbed"}`` > 0) …
    * **failovers_only_kills** — … and triggered ZERO failovers: the
      fleet's failover count equals exactly the number of SCHEDULED
      process kills. A blip that escalated into an ejection fails
      this.
    * **replays_deduped** — a ``conn_reset`` severs the dispatch
      socket AFTER the request frame was sent, so its ladder replay
      MUST have been served the worker's deduped result (worker
      ``dedupe_hits`` > 0): the evidence that a lost reply never
      became a duplicate execution.
    * **respawned_on_newest** — the killed replica's re-admission
      event carries the newest published weight version (the respawn
      weight gate actually gated).
    """
    v = evaluate_serve(
        records, events, plan, fleet_stats, replicas=replicas,
        suspect_s=suspect_s, slo_p99_ms=slo_p99_ms,
        slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version, kv_injected=0, kv_detected=0)
    kills = [f for f in plan.faults if f.kind == "crash"]
    blips = [f for f in plan.faults
             if f.site == "serve.dispatch"
             and f.kind in ("conn_reset", "flaky")]
    v["dispatch_absorbed"] = int(dispatch_absorbed)
    v["dedupe_hits"] = int(dedupe_hits)
    v["respawns"] = fleet_stats.get("respawns", 0)
    if blips:
        v["blips_absorbed"] = dispatch_absorbed > 0
    v["failovers_only_kills"] = \
        fleet_stats.get("failovers", 0) == len(kills)
    if any(f.kind == "conn_reset" for f in blips):
        v["replays_deduped"] = dedupe_hits > 0
    if kills:
        victim = kills[0].peer
        readmit = next((e for e in events
                        if e.get("kind") == "fleet"
                        and e.get("event") == "readmit"
                        and e.get("replica") == victim), None)
        v["respawned_on_newest"] = (
            readmit is not None and newest_version is not None
            and readmit.get("weights_version") == newest_version)
    v["ok"] = all(v.get(k) is not False for k in (
        "ok", "blips_absorbed", "failovers_only_kills",
        "replays_deduped", "respawned_on_newest"))
    return v


def evaluate_disagg(records: List[dict], events: List[dict], plan,
                    fleet_stats: dict, *, replicas: int,
                    suspect_s: float, slo_p99_ms: float,
                    slo_error_rate: float, recovery_window_s: float,
                    newest_version: Optional[int],
                    migrations_in: int, migrate_absorbed: int,
                    migrate_corrupt_detected: int,
                    reprefills: int,
                    traces: Optional[List[dict]] = None,
                    trace_slow_ms: float = 2000.0) -> dict:
    """The DISAGGREGATED-fleet verdict: everything
    :func:`evaluate_serve` asserts (no silent drops, answered-once,
    shed-carries-retry-after, bounded failover for the SIGKILLed
    prefill worker, SLO outside recovery windows, capacity restored on
    the newest weights), plus the migration-plane invariants:

    * **migrations_ok** — KV-block migration actually carried traffic
      (decode-pool installs > 0): a soak where every request happened
      to resolve at prefill proves nothing about the new plane.
    * **migrate_corrupt_caught** — the scheduled ``serve.migrate``
      ``corrupt`` (one payload bit flipped BEFORE framing, so the
      frame crc passes) was caught by the per-BLOCK crc ledger on
      arrival, before any token could be generated from the blocks.
    * **migrate_blips_recovered** — the scheduled ``conn_reset``
      (socket severed AFTER the kv_install frame landed) was survived:
      either the push ladder's replay was served the decode endpoint's
      deduped install ack (``migrate_absorbed`` > 0), or the request
      re-prefilled exactly once (``reprefills`` counts stay bounded by
      the at-most-once bookkeeping either way).
    * **failovers_only_kills** — pool ejections equal exactly the
      scheduled process kills: neither migration chaos kind may
      escalate into an ejection.
    * **respawned_on_newest** — the killed prefill worker re-admitted
      on the newest published weight version.
    * **traces_complete** (only when ``traces`` — the tracer's
      retained set — is passed, back-compat None skips it) — every
      interesting request the CLIENTS saw (errored / expired /
      async-shed / slower than ``trace_slow_ms``) has a retained
      trace under its fid; every synchronous front-door shed has a
      rid-less ``shed`` trace; and ≥99% of retained traces' leg
      decomposition tiles the router-measured e2e within 5% (the
      tiling error is the clock-alignment error — docs/tracing.md).
    """
    v = evaluate_serve(
        records, events, plan, fleet_stats, replicas=replicas,
        suspect_s=suspect_s, slo_p99_ms=slo_p99_ms,
        slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version, kv_injected=0, kv_detected=0)
    kills = [f for f in plan.faults if f.kind == "crash"]
    v["migrations_in"] = int(migrations_in)
    v["migrate_absorbed"] = int(migrate_absorbed)
    v["migrate_corrupt_detected"] = int(migrate_corrupt_detected)
    v["reprefills"] = int(reprefills)
    v["respawns"] = fleet_stats.get("respawns", 0)
    v["migrations_ok"] = migrations_in > 0
    if any(f.site == "serve.migrate" and f.kind == "corrupt"
           for f in plan.faults):
        v["migrate_corrupt_caught"] = migrate_corrupt_detected > 0
    if any(f.site == "serve.migrate" and f.kind == "conn_reset"
           for f in plan.faults):
        v["migrate_blips_recovered"] = (migrate_absorbed > 0
                                        or reprefills > 0)
    v["failovers_only_kills"] = \
        fleet_stats.get("failovers", 0) == len(kills)
    if kills:
        victim = kills[0].peer
        readmit = next((e for e in events
                        if e.get("kind") == "fleet"
                        and e.get("event") == "readmit"
                        and e.get("replica") == victim), None)
        v["respawned_on_newest"] = (
            readmit is not None and newest_version is not None
            and readmit.get("weights_version") == newest_version)
    if traces is not None:
        by_rid: Dict[object, List[dict]] = {}
        for t in traces:
            if t.get("rid") is not None:
                by_rid.setdefault(t["rid"], []).append(t)
        interesting = missing = 0
        for r in records:
            if r.get("fid") is None:
                continue
            slow = (r.get("latency_ms") is not None
                    and float(r["latency_ms"]) >= float(trace_slow_ms))
            if r.get("status") in ("error", "expired", "rejected") \
                    or slow:
                interesting += 1
                if r["fid"] not in by_rid:
                    missing += 1
        sync_sheds = sum(1 for r in records
                         if r.get("fid") is None
                         and r.get("status") == "shed")
        shed_traces = sum(1 for t in traces
                          if t.get("rid") is None
                          and t.get("status") == "shed")
        checked = bad = 0
        for t in traces:
            e2e, legs = t.get("e2e_ms"), t.get("legs_ms") or {}
            if e2e is None or not legs or float(e2e) <= 0.0:
                continue
            checked += 1
            if abs(sum(legs.values()) - float(e2e)) \
                    > 0.05 * float(e2e):
                bad += 1
        v["traces_retained"] = len(traces)
        v["traces_interesting"] = interesting
        v["traces_missing"] = missing
        v["trace_sync_sheds"] = sync_sheds
        v["trace_shed_traces"] = shed_traces
        v["trace_legs_checked"] = checked
        v["trace_leg_mismatches"] = bad
        v["traces_complete"] = (
            missing == 0
            and (sync_sheds == 0 or shed_traces >= sync_sheds)
            and (checked == 0 or (checked - bad) / checked >= 0.99))
    v["ok"] = all(v.get(k) is not False for k in (
        "ok", "migrations_ok", "migrate_corrupt_caught",
        "migrate_blips_recovered", "failovers_only_kills",
        "respawned_on_newest", "traces_complete"))
    return v


def run_disagg_soak(out_dir: Optional[str] = None, *,
                    prefill: int = 2,
                    decode: int = 1,
                    clients: int = 4,
                    seed: int = 0, plan=None,
                    steps: int = DEFAULT_STEPS,
                    suspect_s: float = FLEET_SUSPECT_S,
                    interval_s: float = DEFAULT_INTERVAL_S,
                    slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                    slo_error_rate: float = DEFAULT_SLO_ERROR_RATE,
                    recovery_window_s: float = 8.0,
                    min_duration_s: float = 8.0,
                    max_duration_s: float = 180.0,
                    max_new_tokens: int = 8,
                    deadline_ms: float = 20000.0,
                    spec_k: int = 0,
                    kv_crc: Optional[bool] = None,
                    prefix_cache: Optional[bool] = None,
                    spawn_timeout_s: float = 120.0,
                    trace: bool = True) -> dict:
    """The DISAGGREGATED serve soak (acceptance for the disagg
    tentpole): ``prefill`` + ``decode`` worker processes behind a
    :class:`~horovod_tpu.serve.disagg.DisaggRouter`, a seeded
    disagg-profile plan (one PREFILL worker SIGKILLed mid-traffic, a
    ``serve.migrate`` ``conn_reset`` severing a migration after its
    frame landed, a ``corrupt`` flipping a payload bit the block crc
    must catch), closed-loop traffic, and a v2 weight publish
    mid-incident. ``trace=True`` (the default) arms the distributed-
    tracing plane for the run — the verdict gains ``traces_complete``
    and the out dir ``traces.jsonl`` + ``trace.json`` (merged Chrome
    trace, docs/tracing.md). Returns the :func:`evaluate_disagg`
    verdict; never raises on a failed invariant."""
    import tempfile

    from ..chaos import inject
    from ..native.store import StoreServer
    from ..redist.stream import WeightPublisher
    from .disagg import DisaggRouter
    from .worker import tiny_gpt_builder

    from ..chaos.plan import ChaosPlan, random_plan
    if plan is None or plan == "random":
        resolved = random_plan(seed, prefill + decode, steps,
                               profile="disagg", prefill=prefill)
    elif isinstance(plan, ChaosPlan):
        resolved = plan
    else:
        resolved = ChaosPlan.parse(str(plan))

    work_dir = out_dir or tempfile.mkdtemp(prefix="hvd_disagg_soak.")
    os.makedirs(work_dir, exist_ok=True)
    events_dir = os.path.join(work_dir, "worker_events")
    channel = f"disaggsoak{seed}"

    events: List[dict] = []
    records: List[dict] = []
    ev_lock = threading.Lock()

    def log_event(kind: str, ev: dict) -> None:
        with ev_lock:
            events.append(dict(ev, kind=kind))

    srv = StoreServer()
    built = tiny_gpt_builder(seed=seed, paged=True, draft=spec_k > 0)
    pub = WeightPublisher(channel, kv_addr="127.0.0.1",
                          kv_port=srv.port, resume_timeout=0.05)
    pub.publish(built["params"])              # version 1, pre-incident

    stop = threading.Event()
    torn_down = []
    router = None

    def _teardown() -> None:
        # idempotent and reached on EVERY exit path — INCLUDING a
        # router-construction or injector-install failure, so the
        # store server/publisher/global injector never leak into the
        # caller's process, and the two pools' real OS processes
        # never outlive the soak
        if torn_down:
            return
        torn_down.append(True)
        stop.set()
        if router is not None:
            try:
                router.close()
            except Exception:  # noqa: BLE001
                pass
        inject.uninstall()
        try:
            pub.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            srv.close()
        except Exception:  # noqa: BLE001
            pass

    try:
        worker = {
            "builder": "horovod_tpu.serve.worker:tiny_gpt_builder",
            "builder_kwargs": {"seed": seed, "paged": True,
                               "draft": spec_k > 0},
            "buckets": [8], "max_queue": max(32, 4 * clients),
            "deadline_ms": deadline_ms,
            "kv_crc": True if kv_crc is None else kv_crc,
            "spec_k": spec_k,
            "prefix_cache": True if prefix_cache is None
            else prefix_cache}
        # arm tracing for the router's assembler_from_env read, then
        # restore — the soak must not leak the knob into the caller
        # knob: exempt (harness save/restore around router construction)
        prev_trace = os.environ.get("HOROVOD_TRACE")
        if trace:
            # knob: exempt (harness arms the knob for the construction)
            os.environ["HOROVOD_TRACE"] = "1"
        try:
            router = DisaggRouter(
                prefill, decode, kv_addr="127.0.0.1",
                kv_port=srv.port,
                prefill_worker=dict(worker, spec_k=0),
                decode_worker=worker,
                channel=channel, ns=f"dsoak{seed}",
                interval_s=interval_s,
                suspect_s=suspect_s, chaos_plan=resolved,
                events_dir=events_dir,
                log_dir=os.path.join(work_dir, "logs"),
                spawn_timeout_s=spawn_timeout_s)
        finally:
            if trace:
                if prev_trace is None:
                    os.environ.pop("HOROVOD_TRACE", None)
                else:
                    # knob: exempt (harness restores the caller's env)
                    os.environ["HOROVOD_TRACE"] = prev_trace
        router.add_listener(lambda ev: log_event("fleet", ev))

        inj = inject.install(resolved, rank=0)
        inj.add_listener(lambda ev: log_event(
            "chaos", {"fault": ev["kind"],
                      **{k: x for k, x in ev.items() if k != "kind"}}))
        if router.tracer is not None:
            # feed chaos injections into the flight recorder's event
            # ring (fleet events already arrive via the pool routers)
            inj.add_listener(lambda ev: router.tracer.note_event(
                {"kind": "chaos", **ev}))

        crash_scheduled = any(f.kind == "crash"
                              for f in resolved.faults)
        eject_seen = threading.Event()
        if not crash_scheduled:
            eject_seen.set()

        def watch_eject(ev):
            if ev.get("event") == "eject":
                eject_seen.set()
        router.add_listener(watch_eject)

        return _disagg_soak_body(
            router, resolved, events, records, ev_lock, events_dir,
            work_dir, pub, built, eject_seen, stop, _teardown,
            prefill=prefill, decode=decode, clients=clients,
            suspect_s=suspect_s, slo_p99_ms=slo_p99_ms,
            slo_error_rate=slo_error_rate,
            recovery_window_s=recovery_window_s,
            min_duration_s=min_duration_s,
            max_duration_s=max_duration_s,
            max_new_tokens=max_new_tokens, deadline_ms=deadline_ms,
            spec_k=spec_k)
    finally:
        _teardown()


def _disagg_soak_body(router, resolved, events, records, ev_lock,
                      events_dir, work_dir, pub, built, eject_seen,
                      stop, teardown, *, prefill, decode, clients,
                      suspect_s, slo_p99_ms, slo_error_rate,
                      recovery_window_s, min_duration_s,
                      max_duration_s, max_new_tokens, deadline_ms,
                      spec_k) -> dict:
    """The guarded body of :func:`run_disagg_soak` — every exit path
    runs the caller's teardown."""
    import glob

    from .queue import Rejected

    router.start()
    replicas = prefill + decode

    def publish_fresh():
        eject_seen.wait(timeout=max_duration_s / 2.0)
        time.sleep(0.5)
        try:
            pub.publish(built["params"])      # version 2, same values
        except Exception as e:  # noqa: BLE001
            logger.error("disagg soak: mid-incident publish failed: "
                         "%s", e)

    threading.Thread(target=publish_fresh, daemon=True).start()

    rec_lock = threading.Lock()

    def client(cid: int) -> None:
        import numpy as np
        rng = np.random.RandomState(30_000 + cid)
        while not stop.is_set():
            prompt = list(rng.randint(1, 64, int(rng.randint(2, 8))))
            # WALL-clock stamps: the verdict intersects these with the
            # event ledger's time.time() recovery windows
            t0 = time.time()
            rec = {"fid": None, "t0": t0, "t1": None,
                   "status": "pending", "latency_ms": None,
                   "retry_after_ms": None, "resolutions": 0,
                   "replica": None, "client": cid}
            try:
                h = router.submit(prompt,
                                  max_new_tokens=max_new_tokens)
            except Rejected as e:
                rec.update(status="shed",
                           retry_after_ms=e.retry_after_ms,
                           t1=time.time())
                with rec_lock:
                    records.append(rec)
                time.sleep(min((e.retry_after_ms or 100.0), 500.0)
                           / 1000.0)
                continue
            h.wait(timeout=deadline_ms / 1000.0 + 60.0)
            rec.update(fid=h.fid, t1=time.time(),
                       status=h.status, latency_ms=h.latency_ms,
                       retry_after_ms=h.retry_after_ms,
                       resolutions=h.resolutions, replica=h.replica)
            with rec_lock:
                records.append(rec)
            if h.status == "rejected" and h.retry_after_ms:
                time.sleep(min(h.retry_after_ms, 500.0) / 1000.0)
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    def worker_chaos_events() -> List[dict]:
        out = []
        for path in sorted(glob.glob(
                os.path.join(events_dir, "*.events.jsonl"))):
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        ev = json.loads(line)
                        out.append({"kind": "chaos",
                                    "fault": ev.get("kind"),
                                    **{k: x for k, x in ev.items()
                                       if k != "kind"}})
            except (OSError, ValueError):
                # resilience: exempt (local event-ledger file read —
                # a half-written line is re-read next poll)
                continue
        return out

    want = {(f.site, f.kind, f.peer) for f in resolved.faults
            if f.kind != "flaky"}

    def faults_all_fired(worker_evs: List[dict]) -> bool:
        with ev_lock:
            got = {(e.get("site"), e.get("fault"), e.get("peer"))
                   for e in events if e.get("kind") == "chaos"}
        got |= {(e.get("site"), e.get("fault"), e.get("peer"))
                for e in worker_evs}
        return want <= got

    def recovered() -> bool:
        s = router.stats()
        newest = pub._version
        return (s["replicas_up"] == replicas and newest >= 2
                and all(r["weights_version"] == newest
                        for r in s["replicas"].values()))

    dwell_s = 2 * suspect_s + 1.0
    last_unhealed = time.monotonic()
    while time.monotonic() - t_start < max_duration_s:
        if not (faults_all_fired(worker_chaos_events())
                and recovered()):
            last_unhealed = time.monotonic()
        elif time.monotonic() - last_unhealed >= dwell_s \
                and time.monotonic() - t_start >= min_duration_s:
            break
        time.sleep(0.25)
    stop.set()
    for t in threads:
        t.join(timeout=deadline_ms / 1000.0 + 65.0)

    # final evidence pulls, per replica with the cached-sweep fallback
    # (same rule as the fleet soak: one missed last poll must not
    # evaporate evidence a fault DID recover)
    migrations_in = migrate_corrupt = migrate_absorbed = 0
    for pool in (router.prefill, router.decode):
        for rep in pool.replicas.values():
            h = pool._fetch_healthz(rep, timeout=1.0) or \
                rep.healthz_cache or {}
            migrations_in += int(h.get("migrations_in") or 0)
            migrate_corrupt += int(
                h.get("migrate_corrupt_detected") or 0)
            migrate_absorbed += int(h.get("migrate_absorbed") or 0)
    fleet_stats = router.stats()
    newest_version = pub._version
    worker_evs = worker_chaos_events()
    with ev_lock:
        all_events = sorted(events + worker_evs,
                            key=lambda e: e.get("t", 0.0))
    traces = None
    if router.tracer is not None:
        # pull the retained set + merged artifacts BEFORE teardown
        # tears the pools down (the assembler is in-memory state)
        traces = router.tracer.retained()
        try:
            router.tracer.write_jsonl(
                os.path.join(work_dir, "traces.jsonl"))
            router.tracer.write_chrome(
                os.path.join(work_dir, "trace.json"))
        except OSError as e:
            # resilience: exempt (local filesystem write of a soak
            # artifact — not a wire fault; the verdict still runs)
            logger.warning(
                "disagg soak: trace artifact write failed: %s", e)
    teardown()

    verdict = evaluate_disagg(
        records, all_events, resolved, fleet_stats,
        replicas=replicas, suspect_s=suspect_s,
        slo_p99_ms=slo_p99_ms, slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version,
        migrations_in=migrations_in,
        migrate_absorbed=migrate_absorbed,
        migrate_corrupt_detected=migrate_corrupt,
        reprefills=fleet_stats.get("reprefills", 0),
        traces=traces)
    verdict.update({
        "seed": resolved.seed, "prefill": prefill, "decode": decode,
        "clients": clients, "processes": True, "disagg": True,
        "traced": traces is not None,
        "spec_k": int(spec_k), "suspect_s": suspect_s,
        "wall_s": round(time.monotonic() - t_start, 2),
        "plan": json.loads(resolved.to_json()),
        "fleet": fleet_stats,
        "out_dir": work_dir,
    })
    with open(os.path.join(work_dir, "events.jsonl"), "w") as f:
        for e in all_events:
            f.write(json.dumps(e, default=str) + "\n")
    with open(os.path.join(work_dir, "requests.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    with open(os.path.join(work_dir, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    return verdict


def run_fleet_soak(out_dir: Optional[str] = None, *,
                   replicas: int = 2,
                   clients: int = 4,
                   seed: int = 0, plan=None,
                   steps: int = DEFAULT_STEPS,
                   suspect_s: float = FLEET_SUSPECT_S,
                   interval_s: float = DEFAULT_INTERVAL_S,
                   slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                   slo_error_rate: float = DEFAULT_SLO_ERROR_RATE,
                   recovery_window_s: float = 8.0,
                   min_duration_s: float = 8.0,
                   max_duration_s: float = 150.0,
                   max_new_tokens: int = 8,
                   deadline_ms: float = 20000.0,
                   spec_k: int = 0,
                   paged: bool = True,
                   kv_crc: Optional[bool] = None,
                   prefix_cache: Optional[bool] = None,
                   spawn_timeout_s: float = 120.0) -> dict:
    """The MULTI-PROCESS serve soak (acceptance for the process-fleet
    tentpole): N replica WORKER PROCESSES behind a
    :class:`~horovod_tpu.serve.proc_fleet.ProcessFleetRouter`, a
    seeded serve-profile plan with ``processes=True`` (one worker
    SIGKILLed mid-traffic, ``conn_reset``/``flaky`` blips on the
    dispatch wire, an admission drop), closed-loop traffic, and a v2
    weight publish mid-incident. Returns the :func:`evaluate_fleet`
    verdict; never raises on a failed invariant."""
    import tempfile

    from ..chaos import inject
    from ..native.store import StoreServer
    from ..redist.stream import WeightPublisher
    from .proc_fleet import ProcessFleetRouter
    from .worker import tiny_gpt_builder

    from ..chaos.plan import ChaosPlan, random_plan
    if plan is None or plan == "random":
        resolved = random_plan(seed, replicas, steps, profile="serve",
                               processes=True)
    elif isinstance(plan, ChaosPlan):
        resolved = plan
    else:
        resolved = ChaosPlan.parse(str(plan))

    work_dir = out_dir or tempfile.mkdtemp(prefix="hvd_fleet_soak.")
    os.makedirs(work_dir, exist_ok=True)
    events_dir = os.path.join(work_dir, "worker_events")
    channel = f"fleetsoak{seed}"

    events: List[dict] = []
    records: List[dict] = []
    ev_lock = threading.Lock()

    def log_event(kind: str, ev: dict) -> None:
        with ev_lock:
            events.append(dict(ev, kind=kind))

    srv = StoreServer()
    # the publisher derives the SAME params every worker builds
    # (deterministic per seed) — v1 lands before any worker spawns, so
    # every startup passes the weight gate against a live channel
    built = tiny_gpt_builder(seed=seed, paged=paged,
                             draft=spec_k > 0)
    pub = WeightPublisher(channel, kv_addr="127.0.0.1",
                          kv_port=srv.port, resume_timeout=0.05)
    pub.publish(built["params"])              # version 1, pre-incident

    router = ProcessFleetRouter(
        replicas, kv_addr="127.0.0.1", kv_port=srv.port,
        worker={
            "builder": "horovod_tpu.serve.worker:tiny_gpt_builder",
            "builder_kwargs": {"seed": seed, "paged": paged,
                               "draft": spec_k > 0},
            "buckets": [8], "max_queue": max(32, 4 * clients),
            "deadline_ms": deadline_ms,
            "kv_crc": True if kv_crc is None else kv_crc,
            "spec_k": spec_k,
            "prefix_cache": paged if prefix_cache is None
            else prefix_cache},
        channel=channel, ns=f"soak{seed}", interval_s=interval_s,
        suspect_s=suspect_s, chaos_plan=resolved,
        events_dir=events_dir,
        log_dir=os.path.join(work_dir, "logs"),
        spawn_timeout_s=spawn_timeout_s)
    router.add_listener(lambda ev: log_event("fleet", ev))

    # arm the ROUTER process (serve.dispatch fires here; serve.proc /
    # serve.admit fire inside the workers, which install the same plan
    # from their spawn config and ledger into events_dir)
    inj = inject.install(resolved, rank=0)
    inj.add_listener(lambda ev: log_event(
        "chaos", {"fault": ev["kind"],
                  **{k: x for k, x in ev.items() if k != "kind"}}))

    crash_scheduled = any(f.kind == "crash" for f in resolved.faults)
    eject_seen = threading.Event()
    if not crash_scheduled:
        eject_seen.set()

    def watch_eject(ev):
        if ev.get("event") == "eject":
            eject_seen.set()
    router.add_listener(watch_eject)

    stop = threading.Event()
    torn_down = []

    def _teardown() -> None:
        # idempotent, best-effort, and REACHED ON EVERY EXIT PATH: the
        # replicas are real OS processes in their own sessions — an
        # exception anywhere in the soak body must not orphan them
        # spinning forever
        if torn_down:
            return
        torn_down.append(True)
        stop.set()
        try:
            router.close()
        except Exception:  # noqa: BLE001
            pass
        inject.uninstall()
        try:
            pub.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            srv.close()
        except Exception:  # noqa: BLE001
            pass

    try:
        return _fleet_soak_body(
            router, resolved, events, records, ev_lock, events_dir,
            work_dir, pub, built, eject_seen, stop, _teardown,
            replicas=replicas, clients=clients,
            suspect_s=suspect_s, slo_p99_ms=slo_p99_ms,
            slo_error_rate=slo_error_rate,
            recovery_window_s=recovery_window_s,
            min_duration_s=min_duration_s,
            max_duration_s=max_duration_s,
            max_new_tokens=max_new_tokens, deadline_ms=deadline_ms,
            spec_k=spec_k, paged=paged)
    finally:
        _teardown()


def _fleet_soak_body(router, resolved, events, records, ev_lock,
                     events_dir, work_dir, pub, built, eject_seen,
                     stop, teardown, *, replicas, clients, suspect_s,
                     slo_p99_ms, slo_error_rate, recovery_window_s,
                     min_duration_s, max_duration_s, max_new_tokens,
                     deadline_ms, spec_k, paged) -> dict:
    """The guarded body of :func:`run_fleet_soak` — every exit path
    runs the caller's teardown (worker processes must never outlive
    the soak)."""
    import glob

    from .queue import Rejected

    router.start()

    def publish_fresh():
        # the online-learning leg: v2 lands while the fleet is mid-
        # incident; the RESPAWNED victim must come back gated on it
        eject_seen.wait(timeout=max_duration_s / 2.0)
        time.sleep(0.5)
        try:
            pub.publish(built["params"])      # version 2, same values
        except Exception as e:  # noqa: BLE001
            logger.error("fleet soak: mid-incident publish failed: %s",
                         e)

    threading.Thread(target=publish_fresh, daemon=True).start()

    rec_lock = threading.Lock()

    def client(cid: int) -> None:
        import numpy as np
        rng = np.random.RandomState(20_000 + cid)
        while not stop.is_set():
            prompt = list(rng.randint(1, 64, int(rng.randint(2, 8))))
            # WALL-clock stamps: the verdict intersects these with the
            # event ledger's time.time() recovery windows — a monotonic
            # stamp here would make every request look "outside" every
            # window and quietly disable the SLO exclusion
            t0 = time.time()
            rec = {"fid": None, "t0": t0, "t1": None,
                   "status": "pending", "latency_ms": None,
                   "retry_after_ms": None, "resolutions": 0,
                   "replica": None, "client": cid}
            try:
                h = router.submit(prompt,
                                  max_new_tokens=max_new_tokens)
            except Rejected as e:
                rec.update(status="shed",
                           retry_after_ms=e.retry_after_ms,
                           t1=time.time())
                with rec_lock:
                    records.append(rec)
                time.sleep(min((e.retry_after_ms or 100.0), 500.0)
                           / 1000.0)
                continue
            h.wait(timeout=deadline_ms / 1000.0 + 60.0)
            rec.update(fid=h.fid, t1=time.time(),
                       status=h.status, latency_ms=h.latency_ms,
                       retry_after_ms=h.retry_after_ms,
                       resolutions=h.resolutions, replica=h.replica)
            with rec_lock:
                records.append(rec)
            if h.status == "rejected" and h.retry_after_ms:
                time.sleep(min(h.retry_after_ms, 500.0) / 1000.0)
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    def worker_chaos_events() -> List[dict]:
        """Read the workers' fsync'd injector ledgers (the victim's
        SIGKILL is recorded there a syscall before it dies)."""
        out = []
        for path in sorted(glob.glob(
                os.path.join(events_dir, "*.events.jsonl"))):
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        ev = json.loads(line)
                        out.append({"kind": "chaos",
                                    "fault": ev.get("kind"),
                                    **{k: x for k, x in ev.items()
                                       if k != "kind"}})
            except (OSError, ValueError):
                # resilience: exempt (local event-ledger file read, not
                # a wire path — a half-written line is re-read next poll)
                continue
        return out

    # distinct scheduled faults only, flaky excluded: its seeded draws
    # may legitimately never hit inside the window, and waiting on a
    # fault that cannot be forced would stall the soak to its cap
    want = {(f.site, f.kind, f.peer) for f in resolved.faults
            if f.kind != "flaky"}

    def faults_all_fired(worker_evs: List[dict]) -> bool:
        with ev_lock:
            got = {(e.get("site"), e.get("fault"), e.get("peer"))
                   for e in events if e.get("kind") == "chaos"}
        got |= {(e.get("site"), e.get("fault"), e.get("peer"))
                for e in worker_evs}
        return want <= got

    def recovered() -> bool:
        s = router.stats()
        newest = pub._version
        return (s["replicas_up"] == replicas and newest >= 2
                and all(r["weights_version"] == newest
                        for r in s["replicas"].values()))

    dwell_s = 2 * suspect_s + 1.0
    last_unhealed = time.monotonic()
    while time.monotonic() - t_start < max_duration_s:
        if not (faults_all_fired(worker_chaos_events())
                and recovered()):
            last_unhealed = time.monotonic()
        elif time.monotonic() - last_unhealed >= dwell_s \
                and time.monotonic() - t_start >= min_duration_s:
            break
        time.sleep(0.25)
    stop.set()
    for t in threads:
        t.join(timeout=deadline_ms / 1000.0 + 65.0)

    # final, fresh evidence pulls before teardown; per replica, a
    # missed probe (loaded box, transient connect failure) falls back
    # to the sweep's cached count — evidence the dedupe DID happen
    # must not evaporate because one last poll did
    dedupe_hits = 0
    for rep in router.replicas.values():
        h = router._fetch_healthz(rep, timeout=1.0)
        probed = int(h.get("dedupe_hits") or 0) if h is not None else 0
        dedupe_hits += max(probed, int(rep.dedupe_hits or 0))
    fleet_stats = router.stats()
    from ..obs import metrics as obs_metrics
    from ..native.resilience import RETRIES_HELP
    dispatch_absorbed = int(obs_metrics.get_registry().counter(
        "hvd_net_retries_total", RETRIES_HELP,
        {"site": "serve.dispatch", "outcome": "absorbed"}).value)
    newest_version = pub._version
    worker_evs = worker_chaos_events()
    with ev_lock:
        all_events = sorted(events + worker_evs,
                            key=lambda e: e.get("t", 0.0))
    teardown()

    verdict = evaluate_fleet(
        records, all_events, resolved, fleet_stats,
        replicas=replicas, suspect_s=suspect_s,
        slo_p99_ms=slo_p99_ms, slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version,
        dispatch_absorbed=dispatch_absorbed,
        dedupe_hits=dedupe_hits)
    verdict.update({
        "seed": resolved.seed, "replicas": replicas,
        "clients": clients, "processes": True,
        "paged": bool(paged), "spec_k": int(spec_k),
        "suspect_s": suspect_s,
        "wall_s": round(time.monotonic() - t_start, 2),
        "plan": json.loads(resolved.to_json()),
        "fleet": fleet_stats,
        "out_dir": work_dir,
    })
    with open(os.path.join(work_dir, "events.jsonl"), "w") as f:
        for e in all_events:
            f.write(json.dumps(e, default=str) + "\n")
    with open(os.path.join(work_dir, "requests.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    with open(os.path.join(work_dir, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    return verdict


def run_serve_soak(out_dir: Optional[str] = None, *,
                   replicas: int = DEFAULT_REPLICAS,
                   clients: int = DEFAULT_CLIENTS,
                   seed: int = 0, plan=None,
                   steps: int = DEFAULT_STEPS,
                   suspect_s: float = DEFAULT_SUSPECT_S,
                   interval_s: float = DEFAULT_INTERVAL_S,
                   slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                   slo_error_rate: float = DEFAULT_SLO_ERROR_RATE,
                   recovery_window_s: float = DEFAULT_RECOVERY_WINDOW_S,
                   min_duration_s: float = 8.0,
                   max_duration_s: float = 45.0,
                   max_new_tokens: int = 8,
                   deadline_ms: float = 20000.0,
                   kv_crc: Optional[bool] = None,
                   paged: bool = True,
                   prefix_cache: Optional[bool] = None,
                   spec_k: int = 3,
                   sigterm_drain: bool = False) -> dict:
    """Run the serving soak in-process and return the verdict dict.
    Never raises on a failed invariant — the verdict carries the
    evidence; it raises only on harness misuse."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..chaos import inject
    from ..models.gpt import GPT, GPTConfig
    from ..native.store import StoreServer
    from ..redist.stream import WeightPublisher, WeightSubscriber
    from .executor import ShardedExecutor
    from .fleet import FleetRouter, Replica
    from .queue import Rejected

    if kv_crc is None:
        kv_crc = True   # the corrupt invariant NEEDS the crc ledger
    if prefix_cache is None:
        prefix_cache = paged   # paged-only feature
    resolved = _resolve_plan(plan, seed, replicas, steps)

    # -- tiny decode-mode model: identical params on every replica.
    # The soak's DEFAULT configuration is the full serving tier —
    # paged KV blocks + radix prefix cache + speculative decoding —
    # because this soak is the regression harness for those paths: a
    # serve.kv corrupt must be caught by the per-BLOCK crc, failover
    # must survive block-table teardown, and the version fence must
    # flush prefix runs on the mid-incident weight publish.
    kw = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
              max_seq_len=48, dtype=jnp.float32,
              attention_impl="reference")
    paged_kw = dict(kv_block_size=4, kv_pool_blocks=32) if paged else {}
    model = GPT(GPTConfig(decode=True, **kw, **paged_kw))
    params = GPT(GPTConfig(**kw)).init(
        jax.random.PRNGKey(seed), jnp.zeros((2, 8), jnp.int32))["params"]
    # the drafter shares the target's params (a perfectly distilled
    # proposer): the accept path runs hot while the verify step keeps
    # the bit-identical guarantee for whatever the drafter proposes
    draft_model = GPT(GPTConfig(decode=True, **kw)) if spec_k else None

    events: List[dict] = []
    records: List[dict] = []
    ev_lock = threading.Lock()

    def log_event(kind: str, ev: dict) -> None:
        with ev_lock:
            events.append(dict(ev, kind=kind))

    srv = StoreServer()
    pub = WeightPublisher("soak", kv_addr="127.0.0.1",
                          kv_port=srv.port, resume_timeout=0.05)
    pub.publish(params)                       # version 1, pre-incident
    reps = [
        Replica(i,
                ShardedExecutor(model, params, max_batch=4, max_len=48,
                                replica_id=i),
                buckets=(8,), max_queue=max(32, 4 * clients),
                deadline_ms=deadline_ms, kv_crc=kv_crc,
                draft_executor=(None if draft_model is None else
                                ShardedExecutor(
                                    draft_model, params, max_batch=4,
                                    max_len=48, replica_id=i,
                                    role="draft")),
                spec_k=spec_k, prefix_cache=prefix_cache,
                subscriber=WeightSubscriber(
                    "soak", kv_addr="127.0.0.1", kv_port=srv.port,
                    template=params))
        for i in range(replicas)]
    router = FleetRouter(reps, interval_s=interval_s,
                         suspect_s=suspect_s)
    router.add_listener(lambda ev: log_event("fleet", ev))

    inj = inject.install(resolved, rank=0)
    # the injector's "kind" names the FAULT; the event ledger's "kind"
    # names the record type (chaos/fleet) — same renaming as chaos/soak
    inj.add_listener(lambda ev: log_event(
        "chaos", {"fault": ev["kind"],
                  **{k: x for k, x in ev.items() if k != "kind"}}))

    router.start()
    if sigterm_drain:        # CLI mode (main thread): orderly shutdown
        router.install_sigterm()

    stop = threading.Event()
    crash_seen = threading.Event()
    for f in resolved.faults:
        if f.kind == "crash":
            break
    else:
        crash_seen.set()   # crash-free custom plan: publish mid-run

    def watch_crash(ev):
        if ev.get("kind") == "crash":
            crash_seen.set()
    inj.add_listener(watch_crash)

    def publish_fresh():
        # the online-learning leg: a NEW weight version lands while the
        # fleet is mid-incident; the restarted victim must come back on
        # it (and every healthy replica must adopt it) before the
        # verdict calls the fleet recovered
        crash_seen.wait(timeout=max_duration_s / 2.0)
        time.sleep(0.5)
        try:
            pub.publish(params)               # version 2, same values
        except Exception as e:  # noqa: BLE001
            logger.error("soak: mid-incident publish failed: %s", e)

    pub_thread = threading.Thread(target=publish_fresh, daemon=True)
    pub_thread.start()

    rec_lock = threading.Lock()

    def client(cid: int) -> None:
        rng = np.random.RandomState(10_000 + cid)
        while not stop.is_set():
            prompt = list(rng.randint(1, 64, int(rng.randint(2, 8))))
            # WALL-clock stamps: the recovery windows in the verdict
            # are built from the event ledger's time.time() — monotonic
            # stamps here would never intersect them, silently
            # disabling the SLO window exclusion
            t0 = time.time()
            rec = {"fid": None, "t0": t0, "t1": None,
                   "status": "pending", "latency_ms": None,
                   "retry_after_ms": None, "resolutions": 0,
                   "replica": None, "client": cid}
            try:
                h = router.submit(prompt,
                                  max_new_tokens=max_new_tokens)
            except Rejected as e:
                rec.update(status="shed",
                           retry_after_ms=e.retry_after_ms,
                           t1=time.time())
                with rec_lock:
                    records.append(rec)
                # honor the hint (capped so the soak keeps offering)
                time.sleep(min((e.retry_after_ms or 100.0), 500.0)
                           / 1000.0)
                continue
            h.wait(timeout=deadline_ms / 1000.0 + 30.0)
            rec.update(fid=h.fid, t1=time.time(),
                       status=h.status, latency_ms=h.latency_ms,
                       retry_after_ms=h.retry_after_ms,
                       resolutions=h.resolutions, replica=h.replica)
            with rec_lock:
                records.append(rec)
            time.sleep(0.005)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    def recovered() -> bool:
        s = router.stats()
        newest = pub._version
        return (s["replicas_up"] == replicas and newest >= 2
                and all(r["weights_version"] == newest
                        for r in s["replicas"].values()))

    # distinct scheduled faults only: the injector also emits synthetic
    # partition-window refusals, which must not count as "fired"
    want = {(f.site, f.kind, f.peer) for f in resolved.faults}

    def faults_all_fired() -> bool:
        with ev_lock:
            got = {(e.get("site"), e.get("fault"), e.get("peer"))
                   for e in events if e.get("kind") == "chaos"}
        return want <= got

    # run until the WHOLE incident has played out (every scheduled
    # fault fired) AND the fleet healed — and STAYED healed for a
    # dwell longer than the detector's reaction time: a just-fired
    # slow fault leaves the fleet looking healthy for up to suspect_s
    # before its ejection lands, and sampling that gap would declare
    # victory mid-incident. Traffic keeps flowing during recovery so
    # the adoption/readmission paths run under load, like production
    # would. (Bounded by max_duration_s either way.)
    dwell_s = 2 * suspect_s + 1.0
    last_unhealed = time.monotonic()
    while time.monotonic() - t_start < max_duration_s:
        if not (faults_all_fired() and recovered()):
            last_unhealed = time.monotonic()
        elif time.monotonic() - last_unhealed >= dwell_s \
                and time.monotonic() - t_start >= min_duration_s:
            break
        time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=deadline_ms / 1000.0 + 35.0)

    fleet_stats = router.stats()
    kv_injected = sum(r.batcher.kv_corruptions_injected
                      for r in reps if r.batcher is not None)
    kv_detected = sum(r.batcher.kv_corruptions_detected
                      for r in reps if r.batcher is not None)
    prefix_hits = sum(r.batcher.prefix.hits for r in reps
                      if r.batcher is not None
                      and r.batcher.prefix is not None)
    prefix_saved = sum(r.batcher.prefix.tokens_saved for r in reps
                       if r.batcher is not None
                       and r.batcher.prefix is not None)
    spec_steps = sum(r.batcher.gen_steps for r in reps
                     if r.batcher is not None)
    spec_tokens = sum(r.batcher.gen_tokens for r in reps
                      if r.batcher is not None)
    newest_version = pub._version
    router.close()
    inject.uninstall()
    pub.close()
    for r in reps:
        if r.subscriber is not None:
            r.subscriber.close()
    srv.close()

    verdict = evaluate_serve(
        records, sorted(events, key=lambda e: e.get("t", 0.0)),
        resolved, fleet_stats, replicas=replicas, suspect_s=suspect_s,
        slo_p99_ms=slo_p99_ms, slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version, kv_injected=kv_injected,
        kv_detected=kv_detected)
    verdict.update({
        "seed": resolved.seed, "replicas": replicas,
        "clients": clients, "kv_crc": bool(kv_crc),
        "paged": bool(paged), "prefix_cache": bool(prefix_cache),
        "spec_k": int(spec_k),
        "prefix_hits": prefix_hits,
        "prefix_tokens_saved": prefix_saved,
        # target steps per generated token since the LAST rebuild of
        # each surviving batcher — informational; the bench gate is
        # where the < 0.7 bound is asserted
        "target_steps_per_token": (
            round(spec_steps / spec_tokens, 3) if spec_tokens else None),
        "suspect_s": suspect_s,
        "wall_s": round(time.monotonic() - t_start, 2),
        "plan": json.loads(resolved.to_json()),
        "fleet": fleet_stats,
    })
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "events.jsonl"), "w") as f:
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        with open(os.path.join(out_dir, "requests.jsonl"), "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        with open(os.path.join(out_dir, "verdict.json"), "w") as f:
            json.dump(verdict, f, indent=2, sort_keys=True)
    return verdict

def evaluate_autoscale(records: List[dict], events: List[dict], plan,
                       fleet_stats: dict, *, slo_p99_ms: float,
                       slo_error_rate: float,
                       recovery_window_s: float,
                       newest_version: Optional[int],
                       min_per_pool: int) -> dict:
    """The AUTOSCALE verdict: the serve invariants (zero silent drops,
    answered-once, sheds carry retry hints, SLO outside recovery
    windows) plus the scaling-loop invariants:

    * **scaled_up / scaled_down** — EVERY pool (prefill and decode)
      grew at least once under the burst and shrank at least once in
      the cool phase: a soak where one pool never moved proves nothing
      about that pool's loop.
    * **scale_actions_ok** — no applied action failed: a crash-faulted
      scale-up must end admitted (the spawn retry), a drop-faulted
      scale-down must end removed (the hard-kill path with its
      requeue discipline).
    * **newcomers_on_newest** — every admitted newcomer entered on the
      newest published weight version (the respawn gate, generalized).
    * **faults_all_fired** — when a chaos plan was installed, every
      scheduled ``autoscale.scale`` fault actually landed.
    * **capacity_restored** — the fleet ends scaled back down: every
      pool at its floor with every survivor on the newest weights.

    Recovery windows open around every chaos fault AND every applied
    scale event (a spawn or drain is a planned disruption: the SLO is
    asserted on traffic that does not overlap one).
    """
    v: Dict[str, Any] = {
        "submitted": len(records), "statuses": {},
        "no_silent_drops": None, "answered_once": None,
        "shed_carry_retry_after": None,
        "scaled_up": None, "scaled_down": None,
        "scale_actions_ok": None, "newcomers_on_newest": None,
        "faults_all_fired": None, "slo_held": None,
        "p99_outside_ms": None, "error_rate_outside": None,
        "clean_ok_samples": None, "capacity_restored": None,
        "duplicates_suppressed":
            fleet_stats.get("duplicates_suppressed", 0),
    }
    statuses: Dict[str, int] = {}
    for r in records:
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    v["statuses"] = statuses
    v["no_silent_drops"] = (
        len(records) > 0
        and all(r["status"] != "pending" for r in records)
        and fleet_stats.get("inflight", 0) == 0)
    v["answered_once"] = all(r.get("resolutions", 1) <= 1
                             for r in records)
    shed = [r for r in records if r["status"] in ("shed", "rejected")]
    v["shed_carry_retry_after"] = all(
        (r.get("retry_after_ms") or 0) > 0 for r in shed)

    # -- the scaling loop actually closed, in BOTH directions, per pool
    scale = [e for e in events if e.get("kind") == "scale"]
    counts: Dict[str, Dict[str, int]] = {}
    for e in scale:
        if e.get("ok"):
            c = counts.setdefault(e.get("pool"), {"up": 0, "down": 0})
            c[e.get("direction")] = c.get(e.get("direction"), 0) + 1
    v["scale_events"] = {p: dict(c) for p, c in sorted(counts.items())}
    pools = ("prefill", "decode")
    v["scaled_up"] = all(counts.get(p, {}).get("up", 0) > 0
                         for p in pools)
    v["scaled_down"] = all(counts.get(p, {}).get("down", 0) > 0
                           for p in pools)
    v["scale_actions_ok"] = (len(scale) > 0
                             and all(e.get("ok") for e in scale))

    ups = [e for e in scale if e.get("direction") == "up"
           and e.get("ok")]
    v["newcomers_on_newest"] = (
        len(ups) > 0 and newest_version is not None
        and all(e.get("weights_version") == newest_version
                for e in ups))

    if plan is not None and plan.faults:
        want = {(f.site, f.kind) for f in plan.faults}
        got = {(e.get("site"), e.get("fault")) for e in events
               if e.get("kind") == "chaos"}
        v["faults_all_fired"] = want <= got

    # -- SLO outside recovery windows: chaos faults AND scale events
    # are both planned disruptions
    windows = [(e["t"], e["t"] + recovery_window_s) for e in events
               if (e.get("kind") == "chaos"
                   and e.get("fault") in _DISRUPTIVE)
               or e.get("kind") == "scale"]

    def outside(r):
        return not any(r["t0"] < hi and r["t1"] > lo
                       for lo, hi in windows)

    clean = [r for r in records if outside(r)]
    oks = sorted(r["latency_ms"] for r in clean
                 if r["status"] == "ok"
                 and r.get("latency_ms") is not None)
    v["clean_ok_samples"] = len(oks)
    served = [r for r in clean
              if r["status"] not in ("shed", "rejected")]
    errs = [r for r in served if r["status"] in ("error", "expired")]
    if len(oks) >= 20:
        v["p99_outside_ms"] = round(
            oks[min(len(oks) - 1, int(0.99 * len(oks)))], 1)
        v["error_rate_outside"] = round(
            len(errs) / max(len(served), 1), 4)
        v["slo_held"] = (v["p99_outside_ms"] <= slo_p99_ms
                         and v["error_rate_outside"] <= slo_error_rate)
    else:
        v["slo_held"] = False   # too few clean samples to claim an SLO

    # -- ends scaled back to the floor, everyone on newest weights
    p_stats = fleet_stats.get("prefill", {})
    d_stats = fleet_stats.get("decode", {})
    versions = [r.get("weights_version")
                for r in fleet_stats.get("replicas", {}).values()]
    v["capacity_restored"] = (
        p_stats.get("replicas_up") == min_per_pool
        and d_stats.get("replicas_up") == min_per_pool
        and newest_version is not None
        and all(ver == newest_version for ver in versions))

    v["ok"] = all(v[k] is not False for k in (
        "no_silent_drops", "answered_once", "shed_carry_retry_after",
        "scaled_up", "scaled_down", "scale_actions_ok",
        "newcomers_on_newest", "faults_all_fired", "slo_held",
        "capacity_restored"))
    return v


def run_autoscale_soak(out_dir: Optional[str] = None, *,
                       clients: int = 4,
                       seed: int = 0, plan=None,
                       scale_horizon: int = 8,
                       suspect_s: float = FLEET_SUSPECT_S,
                       interval_s: float = DEFAULT_INTERVAL_S,
                       slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                       slo_error_rate: float = DEFAULT_SLO_ERROR_RATE,
                       recovery_window_s: float = 8.0,
                       max_duration_s: float = 240.0,
                       max_new_tokens: int = 8,
                       deadline_ms: float = 20000.0,
                       max_replicas: int = 2,
                       spawn_timeout_s: float = 120.0) -> dict:
    """The AUTOSCALE soak (acceptance for the autoscale tentpole): a
    1+1 disaggregated fleet behind a live :class:`Autoscaler`, driven
    with PHASED closed-loop traffic — a light warmup, then a
    long-prompt burst that must grow both pools to ``max_replicas``,
    then a cool-down that must drain them back to the floor with no
    sequence dropped — cycling until every pool has scaled BOTH
    directions (and, under a chaos plan, every ``autoscale.scale``
    fault has landed). A fresh weight version is published before the
    first burst so every newcomer must admit on it. Returns the
    :func:`evaluate_autoscale` verdict; never raises on a failed
    invariant.

    ``plan`` follows the other soaks: None for no chaos, ``"random"``
    for the seeded autoscale profile (newcomer killed mid-warmup, the
    actuator stalled past the weight stream, a drain turned into a
    hard kill), or an explicit :class:`ChaosPlan`/JSON.
    """
    import tempfile

    from ..autoscale import Autoscaler, PolicyConfig, SignalSource
    from ..chaos import inject
    from ..chaos.plan import ChaosPlan, random_plan
    from ..native.store import StoreServer
    from ..redist.stream import WeightPublisher
    from .disagg import DisaggRouter
    from .worker import tiny_gpt_builder

    resolved = None
    if plan == "random":
        resolved = random_plan(seed, 2, scale_horizon,
                               profile="autoscale")
    elif isinstance(plan, ChaosPlan):
        resolved = plan
    elif plan is not None:
        resolved = ChaosPlan.parse(str(plan))

    work_dir = out_dir or tempfile.mkdtemp(prefix="hvd_autoscale_soak.")
    os.makedirs(work_dir, exist_ok=True)
    channel = f"assoak{seed}"

    events: List[dict] = []
    records: List[dict] = []
    ev_lock = threading.Lock()

    def log_event(kind: str, ev: dict) -> None:
        with ev_lock:
            events.append(dict(ev, kind=kind))

    srv = StoreServer()
    built = tiny_gpt_builder(seed=seed, paged=True)
    pub = WeightPublisher(channel, kv_addr="127.0.0.1",
                          kv_port=srv.port, resume_timeout=0.05)
    pub.publish(built["params"])              # version 1, pre-burst

    stop = threading.Event()
    torn_down = []
    router = None
    scaler = None

    def _teardown() -> None:
        # idempotent and reached on EVERY exit path, so the poll
        # thread, worker processes, store server, publisher and global
        # injector never leak into the caller's process
        if torn_down:
            return
        torn_down.append(True)
        stop.set()
        if scaler is not None:
            try:
                scaler.stop()
            except Exception:  # noqa: BLE001
                pass
        if router is not None:
            try:
                router.close()
            except Exception:  # noqa: BLE001
                pass
        inject.uninstall()
        try:
            pub.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            srv.close()
        except Exception:  # noqa: BLE001
            pass

    try:
        worker = {
            "builder": "horovod_tpu.serve.worker:tiny_gpt_builder",
            "builder_kwargs": {"seed": seed, "paged": True},
            "buckets": [32], "max_queue": 8,
            "deadline_ms": deadline_ms, "kv_crc": True}
        router = DisaggRouter(
            1, 1, kv_addr="127.0.0.1", kv_port=srv.port,
            prefill_worker=worker, decode_worker=worker,
            channel=channel, ns=f"asoak{seed}", interval_s=interval_s,
            suspect_s=suspect_s, chaos_plan=resolved,
            events_dir=os.path.join(work_dir, "worker_events"),
            log_dir=os.path.join(work_dir, "logs"),
            spawn_timeout_s=spawn_timeout_s)
        router.add_listener(lambda ev: log_event("fleet", ev))

        if resolved is not None:
            inj = inject.install(resolved, rank=0)
            inj.add_listener(lambda ev: log_event(
                "chaos", {"fault": ev["kind"],
                          **{k: x for k, x in ev.items()
                             if k != "kind"}}))

        # aggressive thresholds so the tiny fleet's burst crosses the
        # bands within seconds: the POLICY is what the tier-1 replay
        # tests pin down; the soak proves the LOOP end to end
        cfg = PolicyConfig(
            up_util=0.3, down_util=0.1,
            cooldown_up_s=1.0, cooldown_down_s=3.0,
            min_replicas=1, max_replicas=max_replicas,
            long_prompt_tokens=24, long_prompt_frac=0.5,
            ttft_slo_ms=5.0)
        scaler = Autoscaler(
            router, policy_config=cfg,
            source=SignalSource(router, long_prompt_tokens=24),
            interval_s=0.25,
            trace_path=os.path.join(work_dir, "trace.jsonl"),
            graceful_timeout_s=30.0,
            spawn_timeout_s=spawn_timeout_s)
        scaler.add_listener(lambda ev: log_event("scale", ev))

        return _autoscale_soak_body(
            router, scaler, resolved, events, records, ev_lock,
            work_dir, pub, built, stop, _teardown,
            clients=clients, slo_p99_ms=slo_p99_ms,
            slo_error_rate=slo_error_rate,
            recovery_window_s=recovery_window_s,
            max_duration_s=max_duration_s,
            max_new_tokens=max_new_tokens, deadline_ms=deadline_ms,
            max_replicas=max_replicas, seed=seed)
    finally:
        _teardown()


def _autoscale_soak_body(router, scaler, resolved, events, records,
                         ev_lock, work_dir, pub, built, stop,
                         teardown, *, clients, slo_p99_ms,
                         slo_error_rate, recovery_window_s,
                         max_duration_s, max_new_tokens, deadline_ms,
                         max_replicas, seed) -> dict:
    """The guarded body of :func:`run_autoscale_soak` — every exit
    path runs the caller's teardown."""
    from .queue import Rejected

    router.start()
    burst = threading.Event()   # clients read this: burst vs light load
    rec_lock = threading.Lock()

    def client(cid: int) -> None:
        import numpy as np
        rng = np.random.RandomState(40_000 + cid)
        while not stop.is_set():
            if burst.is_set():
                # long-prompt burst: every prompt over the 24-token
                # bar (and under the 32-token bucket / 48 context),
                # no pacing — the mix shift the policy must see
                n = int(rng.randint(25, 33))
                pace = 0.0
            else:
                n = int(rng.randint(2, 8))
                pace = 0.1
            prompt = list(rng.randint(1, 64, n))
            t0 = time.time()
            rec = {"fid": None, "t0": t0, "t1": None,
                   "status": "pending", "latency_ms": None,
                   "retry_after_ms": None, "resolutions": 0,
                   "replica": None, "client": cid}
            try:
                h = router.submit(prompt,
                                  max_new_tokens=max_new_tokens)
            except Rejected as e:
                rec.update(status="shed",
                           retry_after_ms=e.retry_after_ms,
                           t1=time.time())
                with rec_lock:
                    records.append(rec)
                time.sleep(min((e.retry_after_ms or 100.0), 500.0)
                           / 1000.0)
                continue
            h.wait(timeout=deadline_ms / 1000.0 + 60.0)
            rec.update(fid=h.fid, t1=time.time(),
                       status=h.status, latency_ms=h.latency_ms,
                       retry_after_ms=h.retry_after_ms,
                       resolutions=h.resolutions, replica=h.replica)
            with rec_lock:
                records.append(rec)
            if h.status == "rejected" and h.retry_after_ms:
                time.sleep(min(h.retry_after_ms, 500.0) / 1000.0)
            time.sleep(pace)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    # fresh weights BEFORE any scale-up: every newcomer must stream
    # and admit on v2 while the founding replicas re-admit onto it
    time.sleep(1.0)
    pub.publish(built["params"])              # version 2

    scaler.start()

    def scale_counts() -> Dict[str, Dict[str, int]]:
        with ev_lock:
            out: Dict[str, Dict[str, int]] = {}
            for e in events:
                if e.get("kind") == "scale" and e.get("ok"):
                    c = out.setdefault(e.get("pool"),
                                       {"up": 0, "down": 0})
                    c[e.get("direction")] += 1
            return out

    def goals_met() -> bool:
        c = scale_counts()
        both = all(c.get(p, {}).get("up", 0) > 0
                   and c.get(p, {}).get("down", 0) > 0
                   for p in ("prefill", "decode"))
        if not both:
            return False
        if resolved is not None:
            want = {(f.site, f.kind) for f in resolved.faults}
            with ev_lock:
                got = {(e.get("site"), e.get("fault")) for e in events
                       if e.get("kind") == "chaos"}
            if not want <= got:
                return False
        return True

    def at_floor() -> bool:
        s = router.stats()
        return (s["prefill"]["replicas_up"] == 1
                and s["decode"]["replicas_up"] == 1)

    def at_ceiling() -> bool:
        s = router.stats()
        return (s["prefill"]["replicas_up"] >= max_replicas
                and s["decode"]["replicas_up"] >= max_replicas)

    def wait_until(pred, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if stop.is_set() or pred():
                return True
            time.sleep(0.25)
        return pred()

    deadline = t_start + max_duration_s
    while time.monotonic() < deadline and not goals_met():
        burst.set()
        wait_until(at_ceiling, min(60.0, deadline - time.monotonic()))
        burst.clear()
        wait_until(at_floor, min(60.0, deadline - time.monotonic()))
    # final cool: end at the floor for capacity_restored
    burst.clear()
    wait_until(at_floor, max(deadline - time.monotonic(), 10.0))
    scaler.stop()
    stop.set()
    for t in threads:
        t.join(timeout=deadline_ms / 1000.0 + 65.0)

    fleet_stats = router.stats()
    newest_version = pub._version
    with ev_lock:
        all_events = sorted(events, key=lambda e: e.get("t", 0.0))
    teardown()

    verdict = evaluate_autoscale(
        records, all_events, resolved, fleet_stats,
        slo_p99_ms=slo_p99_ms, slo_error_rate=slo_error_rate,
        recovery_window_s=recovery_window_s,
        newest_version=newest_version, min_per_pool=1)
    verdict.update({
        "seed": seed, "clients": clients, "processes": True,
        "disagg": True, "autoscale": True,
        "max_replicas": max_replicas,
        "wall_s": round(time.monotonic() - t_start, 2),
        "plan": (json.loads(resolved.to_json())
                 if resolved is not None else None),
        "fleet": fleet_stats,
        "out_dir": work_dir,
    })
    with open(os.path.join(work_dir, "events.jsonl"), "w") as f:
        for e in all_events:
            f.write(json.dumps(e, default=str) + "\n")
    with open(os.path.join(work_dir, "requests.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    with open(os.path.join(work_dir, "verdict.json"), "w") as f:
        json.dump(verdict, f, indent=2, sort_keys=True)
    return verdict


def evaluate_kvtier(records: List[dict], events: List[dict], plan,
                    fleet_stats: dict, tier: dict) -> dict:
    """The FLEET-KV-TIER verdict: the serve hygiene invariants (zero
    silent drops, answered-once, sheds carry retry hints) plus the
    tier's own contract —

    * the ladder actually moved: demotions AND promotions > 0 (a soak
      whose pool never pressured the prefix cache proves nothing);
    * **cross-replica hits**: the fleet index steered > 0 dispatches at
      the replica holding the request's longest cached run
      (``hvd_serve_kvtier_routed_total``);
    * **bit-identical tokens**: every repeat of the same (prompt,
      max_new_tokens) — cold, promoted, or re-prefilled after a drop —
      produced the same token sequence;
    * **corrupt caught before install**: every ``kvtier.promote``
      corrupt that fired was caught by the per-leaf crc gate
      (``corrupt_detected`` >= fired), and no request errored;
    * **drop degrades to re-prefill**: the scheduled drops fired, the
      drop counters moved, and still zero ``error`` statuses — a lost
      tier move is a cache miss, never a failure.
    """
    v: Dict[str, Any] = {
        "submitted": len(records), "statuses": {},
        "no_silent_drops": None, "answered_once": None,
        "shed_carry_retry_after": None,
        "ladder_exercised": None, "cross_replica_hit": None,
        "tokens_bit_identical": None, "corrupt_caught": None,
        "drops_degraded": None, "no_errors": None,
        "faults_fired": None, "tier": tier,
    }
    statuses: Dict[str, int] = {}
    for r in records:
        statuses[r["status"]] = statuses.get(r["status"], 0) + 1
    v["statuses"] = statuses
    v["no_silent_drops"] = (
        len(records) > 0
        and all(r["status"] != "pending" for r in records)
        and fleet_stats.get("inflight", 0) == 0)
    v["answered_once"] = all(r.get("resolutions", 1) <= 1
                             for r in records)
    shed = [r for r in records if r["status"] in ("shed", "rejected")]
    v["shed_carry_retry_after"] = all(
        (r.get("retry_after_ms") or 0) > 0 for r in shed)
    v["no_errors"] = statuses.get("error", 0) == 0

    v["ladder_exercised"] = (tier.get("demoted_blocks", 0) > 0
                             and tier.get("promoted_blocks", 0) > 0)
    v["cross_replica_hit"] = tier.get("routed", 0) > 0

    # bit-identity across every repeat of the same prompt
    by_prompt: Dict[str, set] = {}
    for r in records:
        if r["status"] == "ok" and r.get("pkey"):
            by_prompt.setdefault(r["pkey"], set()).add(
                tuple(r.get("tokens") or ()))
    v["prompt_repeats"] = sum(1 for _ in by_prompt)
    v["tokens_bit_identical"] = (
        len(by_prompt) > 0
        and all(len(s) == 1 for s in by_prompt.values()))

    fired = [e for e in events if e.get("kind") == "chaos"]
    want = {(f.site, f.kind, f.peer) for f in plan.faults}
    got = {(e.get("site"), e.get("fault"), e.get("peer"))
           for e in fired}
    v["faults_fired"] = want <= got
    promote_corrupts = sum(
        1 for e in fired if e.get("site") == "kvtier.promote"
        and e.get("fault") == "corrupt")
    v["corrupt_caught"] = (
        promote_corrupts > 0
        and tier.get("corrupt_detected", 0) >= promote_corrupts
        and v["no_errors"])
    drops_fired = sum(1 for e in fired if e.get("fault") == "drop"
                      and str(e.get("site", "")).startswith("kvtier."))
    v["drops_degraded"] = (
        drops_fired > 0
        and (tier.get("demote_drops", 0)
             + tier.get("promote_drops", 0)) > 0
        and v["no_errors"])

    v["ok"] = all(v[k] is not False for k in (
        "no_silent_drops", "answered_once", "shed_carry_retry_after",
        "ladder_exercised", "cross_replica_hit",
        "tokens_bit_identical", "corrupt_caught", "drops_degraded",
        "no_errors", "faults_fired"))
    return v


def run_kvtier_soak(out_dir: Optional[str] = None, *,
                    replicas: int = 2, clients: int = 4,
                    seed: int = 0, plan=None, steps: int = 8,
                    suspect_s: float = DEFAULT_SUSPECT_S,
                    interval_s: float = DEFAULT_INTERVAL_S,
                    min_duration_s: float = 6.0,
                    max_duration_s: float = 60.0,
                    max_new_tokens: int = 4,
                    deadline_ms: float = 20000.0) -> dict:
    """The fleet-KV-tier soak: multi-turn conversations with a shared
    system prefix over an in-process fleet running the full tier —
    small pool + tiny host rings so prefix evictions DEMOTE down the
    ladder (one replica rings at 1 MiB for the host rung, one at 0 so
    every demotion spills to disk), returning turns PROMOTE back, the
    fleet index steers follow-ups at the holder — under the seeded
    ``kvtier`` chaos profile (corrupt demote + corrupt promote + drop
    both). Conversations replay deterministically (greedy decode,
    derived follow-up tokens), so every prompt repeats and the verdict
    can assert bit-identical tokens across cold/promoted/re-prefilled
    serves. Returns the :func:`evaluate_kvtier` verdict; never raises
    on a failed invariant."""
    import shutil
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..chaos import inject
    from ..chaos.plan import ChaosPlan, random_plan
    from ..models.gpt import GPT, GPTConfig
    from .executor import ShardedExecutor
    from .fleet import FleetRouter, Replica
    from .queue import Rejected

    if plan is None or plan == "random":
        resolved = random_plan(seed, replicas, steps, profile="kvtier")
    elif isinstance(plan, ChaosPlan):
        resolved = plan
    else:
        resolved = ChaosPlan.parse(str(plan))

    work = out_dir or tempfile.mkdtemp(prefix="hvd-kvtier-soak-")
    os.makedirs(work, exist_ok=True)

    kw = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
              max_seq_len=48, dtype=jnp.float32,
              attention_impl="reference")
    # 16 blocks is UNDER one deep conversation pair (two 32-token
    # prompts need 18) — the admission gate must evict prefix runs
    # every wave, which is exactly the demotion pressure the ladder
    # soak exists to exercise
    model = GPT(GPTConfig(decode=True, **kw, kv_block_size=4,
                          kv_pool_blocks=16))
    params = GPT(GPTConfig(**kw)).init(
        jax.random.PRNGKey(seed), jnp.zeros((2, 8), jnp.int32))["params"]

    events: List[dict] = []
    records: List[dict] = []
    ev_lock = threading.Lock()
    rec_lock = threading.Lock()

    def log_event(kind: str, ev: dict) -> None:
        with ev_lock:
            events.append(dict(ev, kind=kind))

    reps = [
        Replica(i,
                ShardedExecutor(model, params, max_batch=4, max_len=48,
                                replica_id=i),
                # conversations grow to 32 prompt tokens — the bucket
                # set must cover the deepest turn
                buckets=(16, 32), max_queue=max(32, 4 * clients),
                deadline_ms=deadline_ms, kv_crc=True,
                prefix_cache=True, kv_tier=True,
                # replica 0 spills straight to disk (0 MiB ring);
                # the others keep the host rung — both ladder rungs
                # are exercised in one soak
                kvtier_host_mb=(0 if i == 0 else 1),
                kvtier_dir=os.path.join(work, "spill", f"r{i}"))
        for i in range(replicas)]
    router = FleetRouter(reps, interval_s=interval_s,
                         suspect_s=suspect_s)
    router.add_listener(lambda ev: log_event("fleet", ev))

    inj = inject.install(resolved, rank=0)
    inj.add_listener(lambda ev: log_event(
        "chaos", {"fault": ev["kind"],
                  **{k: x for k, x in ev.items() if k != "kind"}}))

    router.start()
    stop = threading.Event()

    # one shared system prefix (2 full blocks) across EVERY client —
    # the cross-replica routing signal
    grng = np.random.RandomState(seed + 777)
    sys_prefix = [int(t) for t in grng.randint(1, 64, 8)]

    def client(cid: int) -> None:
        rng = np.random.RandomState(10_000 + cid)
        openers = [[int(t) for t in rng.randint(1, 64, 4)]
                   for _ in range(2)]
        conv = 0
        while not stop.is_set():
            prompt = list(sys_prefix) + openers[conv % 2]
            conv += 1
            while len(prompt) <= 32 and not stop.is_set():
                t0 = time.time()
                rec = {"fid": None, "t0": t0, "t1": None,
                       "status": "pending", "latency_ms": None,
                       "retry_after_ms": None, "resolutions": 0,
                       "replica": None, "client": cid,
                       "pkey": ",".join(map(str, prompt)),
                       "tokens": None}
                try:
                    h = router.submit(prompt,
                                      max_new_tokens=max_new_tokens)
                except Rejected as e:
                    rec.update(status="shed",
                               retry_after_ms=e.retry_after_ms,
                               t1=time.time())
                    with rec_lock:
                        records.append(rec)
                    time.sleep(min((e.retry_after_ms or 100.0), 500.0)
                               / 1000.0)
                    continue
                h.wait(timeout=deadline_ms / 1000.0 + 30.0)
                rec.update(fid=h.fid, t1=time.time(),
                           status=h.status, latency_ms=h.latency_ms,
                           retry_after_ms=h.retry_after_ms,
                           resolutions=h.resolutions,
                           replica=h.replica,
                           tokens=[int(t) for t in (h.tokens or ())])
                with rec_lock:
                    records.append(rec)
                if h.status != "ok":
                    break
                # the follow-up turn: generated tokens plus ONE derived
                # user token — deterministic, so conversation replays
                # repeat the exact prompts (the bit-identity probe)
                prompt = prompt + [int(t) for t in h.tokens] + [
                    (cid * 7 + len(prompt)) % 63 + 1]
                time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    t_start = time.monotonic()
    for t in threads:
        t.start()

    want = {(f.site, f.kind, f.peer) for f in resolved.faults}

    def faults_all_fired() -> bool:
        with ev_lock:
            got = {(e.get("site"), e.get("fault"), e.get("peer"))
                   for e in events if e.get("kind") == "chaos"}
        return want <= got

    def tier_exercised() -> bool:
        promoted = sum(r.batcher.kvtier.promoted_blocks for r in reps
                       if r.batcher is not None
                       and r.batcher.kvtier is not None)
        return (promoted > 0
                and int(router._m_kvtier_routed.value) > 0)

    while time.monotonic() - t_start < max_duration_s:
        if faults_all_fired() and tier_exercised() \
                and time.monotonic() - t_start >= min_duration_s:
            break
        time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=deadline_ms / 1000.0 + 35.0)

    fleet_stats = router.stats()
    tier: Dict[str, int] = {
        "demoted_blocks": 0, "promoted_blocks": 0, "demote_drops": 0,
        "promote_drops": 0, "corrupt_detected": 0, "pulls_in": 0,
        "host_runs": 0, "disk_runs": 0,
    }
    for r in reps:
        if r.batcher is None or r.batcher.kvtier is None:
            continue
        for k, val in r.batcher.kvtier.stats().items():
            if k in tier:
                tier[k] += int(val)
    tier["routed"] = int(router._m_kvtier_routed.value)
    tier["pulls"] = int(router._m_kvtier_pulls.value)
    tier["pull_corrupt"] = int(router.kvtier_pull_corrupt)
    if router.kvtier_index is not None:
        tier["index"] = router.kvtier_index.stats()
    router.close()
    inject.uninstall()

    verdict = evaluate_kvtier(
        records, sorted(events, key=lambda e: e.get("t", 0.0)),
        resolved, fleet_stats, tier)
    verdict.update({
        "seed": resolved.seed, "replicas": replicas,
        "clients": clients,
        "wall_s": round(time.monotonic() - t_start, 2),
        "plan": json.loads(resolved.to_json()),
        "fleet": fleet_stats,
    })
    if out_dir:
        with open(os.path.join(out_dir, "events.jsonl"), "w") as f:
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        with open(os.path.join(out_dir, "requests.jsonl"), "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        with open(os.path.join(out_dir, "verdict.json"), "w") as f:
            json.dump(verdict, f, indent=2, sort_keys=True)
    else:
        shutil.rmtree(work, ignore_errors=True)
    return verdict
