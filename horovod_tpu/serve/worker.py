"""Replica worker process: one OS process, one serving replica.

The unit the multi-process fleet (serve/proc_fleet.py) is made of.
Each worker hosts the full PR 8/10 serving stack — ``ShardedExecutor``
(+ optional draft executor), ``AdmissionQueue``, ``ContinuousBatcher``
with paged KV / prefix cache / speculative decoding — plus the three
things that make it a FLEET citizen across a process boundary:

* **A request endpoint** (:class:`ReplicaEndpoint`): a threading TCP
  server speaking the framed protocol of serve/wire.py. Every
  ``submit`` carries a router-generated request id (``fid``); the
  worker keeps a bounded resolution cache and an in-flight table keyed
  by it, so a REPLAYED dispatch — the retry ladder re-dialing after a
  ``conn_reset`` ate the reply — is served its cached (or still
  cooking) result instead of being executed twice. This mirrors the
  csrc/store.cc nonce dedupe and is what makes answered-exactly-once
  hold across the process boundary.
* **Heartbeats over the native KV** — ``serve.hb.<ns>.g<gen>.<rid>``
  posted by a chaos-exempt ``StoreClient`` on its own thread. The
  SEQUENCE only advances when the scheduler actually iterates (the
  batcher's heartbeat hook), so a wedged scheduler goes stale at the
  router's accrual sweep even while the poster thread lives — the same
  liveness-vs-reachability split the PR 5 detector enforces.
* **A weight gate at startup** — before taking traffic the worker
  adopts the NEWEST published version from the redist/stream.py
  channel (``WeightSubscriber.peek_version()`` names the target), so a
  respawned replica re-enters the fleet on the weights its siblings
  already serve, never the stale params it was built with.

Chaos: the worker installs the fleet's plan and fires ``serve.proc``
once per scheduler iteration — ``crash`` there is a REAL
``os.kill(getpid(), SIGKILL)`` (the injector's listener ledger is
flushed first), the genuine host-loss the soak's accrual-detection
bound is measured against. ``serve.step``/``serve.kv``/``serve.admit``
faults keep their PR 8 in-replica semantics, now per process.

Spawned via the runner machinery (runner/exec.py ``spawn_local``);
configuration travels as inline JSON in ``HOROVOD_SERVE_WORKER_CFG``
(see :func:`build_worker` for the schema).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import socketserver
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

from ..trace.spans import configure_recorder as _trace_configure
from ..trace.spans import get_recorder as _trace_recorder
from . import wire
from .queue import AdmitDropped, Rejected

logger = logging.getLogger("horovod_tpu")

#: resolved results retained for replay dedupe (the store.cc DoneRound
#: TTL cache analog, bounded by count instead of time)
DEDUPE_CAP = 4096

#: extra wait past a request's own deadline before the endpoint calls
#: it stalled — the batcher resolves expiry itself within one
#: iteration, so this only fires when the scheduler is wedged
REPLY_GRACE_S = 30.0


def tiny_gpt_builder(seed: int = 0, paged: bool = True,
                     vocab_size: int = 64, num_layers: int = 2,
                     num_heads: int = 2, head_dim: int = 8,
                     max_seq_len: int = 48, max_batch: int = 4,
                     kv_block_size: int = 4, kv_pool_blocks: int = 32,
                     draft: bool = False) -> Dict[str, Any]:
    """The built-in model builder the fleet soak and bench use: a tiny
    decode-mode GPT with params DETERMINISTIC per seed, so every
    replica process (and the soak's publisher) derives bit-identical
    weights without shipping arrays over the spawn boundary."""
    import jax
    import jax.numpy as jnp

    from ..models.gpt import GPT, GPTConfig

    kw = dict(vocab_size=vocab_size, num_layers=num_layers,
              num_heads=num_heads, head_dim=head_dim,
              max_seq_len=max_seq_len, dtype=jnp.float32,
              attention_impl="reference")
    paged_kw = dict(kv_block_size=kv_block_size,
                    kv_pool_blocks=kv_pool_blocks) if paged else {}
    model = GPT(GPTConfig(decode=True, **kw, **paged_kw))
    params = GPT(GPTConfig(**kw)).init(
        jax.random.PRNGKey(seed), jnp.zeros((2, 8), jnp.int32))["params"]
    draft_model = GPT(GPTConfig(decode=True, **kw)) if draft else None
    return {"model": model, "params": params,
            "draft_model": draft_model, "eos_id": None,
            "max_batch": max_batch, "max_len": max_seq_len}


def _resolve_builder(spec: str):
    """'module:function' -> callable, fail-fast."""
    import importlib
    mod, _, fn = spec.partition(":")
    if not mod or not fn:
        raise ValueError(
            f"worker builder must be 'module:function'; got {spec!r}")
    return getattr(importlib.import_module(mod), fn)


class ReplicaEndpoint:
    """The worker's request endpoint: framed submit/healthz over TCP
    with fid-keyed replay dedupe. Usable in-thread (tier-1 tests run it
    against a local batcher without any subprocess)."""

    def __init__(self, batcher, *, rid: int,
                 host: str = "127.0.0.1", port: int = 0,
                 dedupe_cap: int = DEDUPE_CAP):
        self.batcher = batcher
        self.rid = int(rid)
        self._lock = threading.Lock()
        self._inflight: Dict[str, Any] = {}
        self._done: "OrderedDict[str, dict]" = OrderedDict()
        self._dedupe_cap = int(dedupe_cap)
        #: replayed dispatches served from the cache or the in-flight
        #: table instead of being executed twice — the soak's evidence
        #: that a lost reply never becomes a duplicate execution
        self.dedupe_hits = 0
        self.submits = 0
        #: in-progress kv_install entries keyed by fid: a replayed
        #: install arriving while the original is still installing
        #: joins its outcome instead of double-installing
        self._installing: Dict[str, dict] = {}
        ep = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    msg, payload = wire.recv_any(self.request,
                                                 timeout=30.0)
                    ep._handle(self.request, msg, payload)
                except (wire.DispatchConnError, wire.DispatchError,
                        OSError):
                    # resilience: exempt (the client vanished or spoke
                    # garbage — the retry ladder lives ROUTER-side; any
                    # computed result is already in the dedupe cache
                    # for the replay)
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"hvd-replica-ep-{rid}")

    def start(self) -> "ReplicaEndpoint":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- request handling ---------------------------------------------------
    def _handle(self, sock, msg: dict,
                payload: Optional[bytes] = None) -> None:
        op = msg.get("op")
        if op == "healthz":
            wire.send_msg(sock, self.healthz())
            return
        if op == "metrics":
            # the fleet /metrics?fleet=1 scrape leg: the worker's whole
            # registry snapshot rides one JSON reply (serve/http.py
            # merges it with its siblings' via merge_snapshots)
            from ..obs import metrics as obs_metrics
            wire.send_msg(sock, {
                "ack": "metrics",
                "snapshot": obs_metrics.get_registry().snapshot()})
            return
        if op == "kv_install":
            self._handle_kv_install(sock, msg, payload or b"")
            return
        if op in ("migrate", "release", "result"):
            self._handle_disagg(sock, op, msg)
            return
        if op != "submit":
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": f"unknown op {op!r}"})
            return
        if msg.get("fid") in (None, ""):
            # a missing fid must not collapse onto one shared dedupe
            # key (str(None) == "None" would serve one caller another
            # request's cached tokens)
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": "submit requires a fid"})
            return
        fid = str(msg["fid"])
        with self._lock:
            self.submits += 1
            self._sweep_orphans_locked()
            cached = self._done.get(fid)
            handle = None if cached is not None \
                else self._inflight.get(fid)
            if cached is not None or handle is not None:
                # the replay-dedupe core: a re-dispatched request whose
                # reply was lost is served its existing result (or
                # joins the in-flight wait) — never executed twice
                self.dedupe_hits += 1
            elif self.batcher.draining:
                wire.send_msg(sock, {"ack": "rejected",
                                     "reason": "replica draining",
                                     "retry_after_ms": 1000.0})
                return
            else:
                try:
                    handle = self.batcher.queue.submit(
                        msg["prompt"],
                        max_new_tokens=int(msg.get("max_new_tokens", 16)),
                        deadline_ms=msg.get("deadline_ms"),
                        temperature=float(msg.get("temperature", 0.0)),
                        top_p=float(msg.get("top_p", 1.0)),
                        seed=int(msg.get("seed", 0)),
                        hold_kv=bool(msg.get("hold_kv", False)),
                        trace=msg.get("trace"))
                except AdmitDropped as e:
                    wire.send_msg(sock, {
                        "ack": "admit_dropped",
                        "retry_after_ms": e.retry_after_ms})
                    return
                except Rejected as e:
                    wire.send_msg(sock, {
                        "ack": "rejected", "reason": e.reason,
                        "retry_after_ms": e.retry_after_ms})
                    return
                except (KeyError, ValueError, TypeError) as e:
                    wire.send_msg(sock, {"ack": "bad_request",
                                         "error": str(e)})
                    return
                self._inflight[fid] = handle
        # accepted (fresh or replayed): ack now, result when it lands
        wire.send_msg(sock, {"ack": "accepted"})
        deadline_ms = msg.get("deadline_ms") \
            or self.batcher.queue.default_deadline_ms
        self._await_and_reply(sock, fid, handle, cached, deadline_ms,
                              trace=msg.get("trace"))

    def _record(self, handle) -> dict:
        """The cached (replay-servable) rendering of a resolved
        handle. ``rid`` rides along so the disagg ``migrate`` op can
        find the parked sequence a hold_kv prefill left behind."""
        return {"status": handle.status, "tokens": list(handle.tokens),
                "error": handle.error, "latency_ms": handle.latency_ms,
                "rid": handle.rid}

    def _sweep_orphans_locked(self) -> None:
        """Lazily migrate resolved orphans (a client that vanished
        before the ack leaves its entry here) into the bounded done
        cache, so the in-flight table cannot grow past the queue's own
        bounds. Caller holds ``self._lock``."""
        for k in [k for k, h in self._inflight.items() if h.done()]:
            h = self._inflight.pop(k)
            self._done[k] = self._record(h)
            while len(self._done) > self._dedupe_cap:
                self._done.popitem(last=False)

    def _await_and_reply(self, sock, fid: str, handle,
                         cached: Optional[dict],
                         deadline_ms: float,
                         trace: Optional[dict] = None) -> None:
        """The shared result tail of ``submit`` and ``result``: wait
        out the handle (unless a cached record already answers the
        replay), cache BEFORE sending — if the send dies with the
        reply, the replay finds the result here. When the request was
        traced, the recorder's completed spans for it piggyback on the
        reply as ``spans`` (drained at send time, NOT cached: a replay
        re-reads the result, not the telemetry)."""
        if cached is None:
            handle.wait(timeout=float(deadline_ms) / 1000.0
                        + REPLY_GRACE_S)
            if handle.done():
                cached = self._record(handle)
            else:
                # scheduler wedged past deadline + grace: a structured
                # error, not a dropped socket (NOT cached — a replay
                # after the replica recovers may still resolve it)
                wire.send_msg(sock, {"status": "error",
                                     "error": "replica stalled",
                                     "tokens": [], "latency_ms": None})
                return
            with self._lock:
                self._done[fid] = cached
                self._inflight.pop(fid, None)
                while len(self._done) > self._dedupe_cap:
                    self._done.popitem(last=False)
        reply = cached
        if isinstance(trace, dict) and trace.get("trace"):
            spans = _trace_recorder().drain(str(trace["trace"]))
            if spans:
                reply = dict(cached, spans=spans)
        wire.send_msg(sock, reply)

    # -- disaggregated serving ops (serve/disagg.py orchestration) ----------
    def _handle_disagg(self, sock, op: str, msg: dict) -> None:
        """``migrate`` / ``release`` / ``result``: the decode-pool and
        prefill-pool halves of KV-block migration, addressed by the
        SAME fid namespace (and dedupe discipline) as ``submit``."""
        from . import kv_migrate
        fid = str(msg.get("fid") or "")
        if not fid:
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": f"{op} requires a fid"})
            return
        with self._lock:
            self._sweep_orphans_locked()
            cached = self._done.get(fid)
            handle = self._inflight.get(fid)
            if op == "result" and cached is not None:
                self.dedupe_hits += 1
        if op == "result":
            # the decode-side completion wait: same contract as a
            # submit's reply leg (ack, block, cached-replay dedupe)
            if cached is None and handle is None:
                wire.send_msg(sock, {"ack": "unknown_fid"})
                return
            wire.send_msg(sock, {"ack": "accepted"})
            deadline_ms = msg.get("deadline_ms") \
                or self.batcher.queue.default_deadline_ms
            self._await_and_reply(sock, fid, handle, cached,
                                  deadline_ms,
                                  trace=msg.get("trace"))
            return
        rid = cached.get("rid") if cached is not None else \
            (handle.rid if handle is not None else None)
        if rid is None:
            wire.send_msg(sock, {"ack": "migrate_failed",
                                 "reason": "unknown_fid"})
            return
        if op == "release":
            self.batcher.release_parked(int(rid))
            wire.send_msg(sock, {"ack": "released"})
            return
        # op == "migrate": pack the parked sequence and PUSH it to the
        # decode endpoint the router chose (serve.migrate chaos +
        # retry ladder live inside kv_migrate.push)
        t0 = time.monotonic()
        try:
            packet = kv_migrate.pack_parked(
                self.batcher, int(rid), fid=str(msg["dfid"]),
                max_new_tokens=int(msg["max_new_tokens"]),
                deadline_ms=float(msg.get("deadline_ms") or 30000.0))
        except kv_migrate.MigrateCorrupt as e:
            # the SOURCE blocks are untrusted: release them so the
            # inevitable re-prefill runs on clean capacity
            self.batcher.release_parked(int(rid))
            wire.send_msg(sock, {"ack": "migrate_failed",
                                 "reason": "source_corrupt",
                                 "detail": str(e)[:200]})
            return
        except (KeyError, ValueError, TypeError) as e:
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": str(e)[:200]})
            return
        if packet is None:
            wire.send_msg(sock, {"ack": "migrate_failed",
                                 "reason": "not_parked"})
            return
        header, payload = packet
        try:
            target = (str(msg["target"][0]), int(msg["target"][1]))
            ack = kv_migrate.push(target, header, payload,
                                  peer=msg.get("peer"))
        except (wire.DispatchConnError, wire.DispatchError) as e:
            wire.send_msg(sock, {"ack": "migrate_failed",
                                 "reason": "unreachable",
                                 "detail": str(e)[:200]})
            return
        if ack.get("ack") == "installed":
            # the blocks live on the decode replica now — free the
            # parked row (scheduler-thread free, endpoint-safe)
            self.batcher.release_parked(int(rid))
            reply = {
                "ack": "migrated", "bytes": len(payload),
                "blocks": len(header["blocks"]),
                "ms": round((time.monotonic() - t0) * 1000.0, 3),
                "dedupe": bool(ack.get("dedupe", False))}
            tr = header.get("trace")
            if isinstance(tr, dict) and tr.get("trace"):
                base = time.time() - time.monotonic()
                _trace_recorder().record(
                    tr, "migrate_push", t0 + base, time.time(),
                    fid=str(msg.get("dfid")), bytes=len(payload))
                spans = _trace_recorder().drain(str(tr["trace"]))
                if spans:
                    reply["spans"] = spans
            wire.send_msg(sock, reply)
            return
        wire.send_msg(sock, {
            "ack": "migrate_failed",
            "reason": str(ack.get("ack", "unknown")),
            "detail": ack.get("detail") or ack.get("error"),
            "retry_after_ms": ack.get("retry_after_ms")})

    def _handle_kv_install(self, sock, msg: dict,
                           payload: bytes) -> None:
        """Receive a migrated sequence (the decode-pool side): crc
        verification + reservation-gated install ride
        kv_migrate.install; the fid dedupe (done cache, in-flight
        table, in-progress installs) makes a ladder REPLAY of a
        severed push converge on one install and one ack."""
        from . import kv_migrate
        fid = str(msg.get("fid") or "")
        if not fid:
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": "kv_install requires a fid"})
            return
        mine = False
        with self._lock:
            self._sweep_orphans_locked()
            if fid in self._done or fid in self._inflight:
                self.dedupe_hits += 1
                ent = None
            else:
                ent = self._installing.get(fid)
                if ent is None:
                    mine = True
                    ent = {"evt": threading.Event(), "outcome": None,
                           "handle": None}
                    self._installing[fid] = ent
                else:
                    self.dedupe_hits += 1
        if ent is None:
            # already installed (or even resolved): the replay of a
            # severed push is served the same ack, never a second copy
            wire.send_msg(sock, {"ack": "installed", "dedupe": True})
            return
        if mine:
            t_i0 = time.time()
            try:
                blocks = kv_migrate.unpack_blocks(msg, payload)
            except kv_migrate.MigrateCorrupt as e:
                self.batcher.note_migrate_corrupt()
                self._finalize_install(fid, ent, ("corrupt", str(e)),
                                       None)
            else:
                pending = self.batcher.submit_migrated(msg, blocks)
                if pending["evt"].wait(
                        kv_migrate.INSTALL_ACK_TIMEOUT_S):
                    out = pending["outcome"]
                    tr = msg.get("trace")
                    if isinstance(tr, dict) and tr.get("trace"):
                        # decode-side receive span; drained later with
                        # the result op's reply
                        _trace_recorder().record(
                            tr, "migrate_install", t_i0, time.time(),
                            fid=fid, outcome=str(out[0]))
                    self._finalize_install(
                        fid, ent, out,
                        pending["handle"] if out[0] == "installed"
                        else None)
                else:
                    # the decode scheduler has not picked the entry up
                    # yet: the install is still PENDING, not dead. The
                    # _installing entry stays registered so a ladder
                    # replay JOINS this install instead of starting a
                    # second one (the double-install the fid dedupe
                    # exists to prevent), and a finisher thread
                    # completes the bookkeeping — registering the
                    # handle for the result op — whenever it lands.
                    def finish():
                        pending["evt"].wait(REPLY_GRACE_S * 10)
                        out = pending["outcome"] or ("stalled", None)
                        self._finalize_install(
                            fid, ent, out,
                            pending["handle"] if out[0] == "installed"
                            else None)
                    threading.Thread(
                        target=finish, daemon=True,
                        name=f"hvd-install-finish-{self.rid}").start()
        else:
            ent["evt"].wait(kv_migrate.INSTALL_ACK_TIMEOUT_S + 5.0)
        outcome, detail = ent["outcome"] or ("stalled", None)
        if outcome == "installed":
            wire.send_msg(sock, {"ack": "installed",
                                 "dedupe": not mine})
        elif outcome == "corrupt":
            wire.send_msg(sock, {"ack": "migrate_corrupt",
                                 "detail": detail})
        elif outcome == "version_mismatch":
            wire.send_msg(sock, {"ack": "version_mismatch",
                                 "detail": detail})
        elif outcome == "rejected":
            wire.send_msg(sock, {"ack": "rejected",
                                 "retry_after_ms": detail})
        else:
            wire.send_msg(sock, {"ack": "bad_request",
                                 "error": f"{outcome}: {detail}"})

    def _finalize_install(self, fid: str, ent: dict, outcome: tuple,
                          handle) -> None:
        """Complete a kv_install's endpoint bookkeeping exactly once:
        record the outcome, register the handle for the result op,
        release the in-progress entry, wake every waiter (the original
        requester and any replays that joined it)."""
        with self._lock:
            ent["outcome"] = outcome
            ent["handle"] = handle
            if handle is not None:
                self._inflight[fid] = handle
            self._installing.pop(fid, None)
        ent["evt"].set()

    def healthz(self) -> dict:
        b = self.batcher
        info = {"replica": self.rid,
                "replica_up": b.alive(),
                "draining": bool(getattr(b, "draining", False)),
                "load": b.load(),
                "iterations": b.iterations,
                "weights_version": b.executor.params_version,
                "dedupe_hits": self.dedupe_hits,
                "kv_corruptions_injected": b.kv_corruptions_injected,
                "kv_corruptions_detected": b.kv_corruptions_detected}
        if getattr(b, "paged", False):
            info["kv_blocks_in_use"] = b.kv.pool.in_use()
            info["kv_blocks_total"] = b.kv.pool.num_blocks
            info["kv_block_size"] = b.kv.pool.block_size
            # blocks held ONLY by the prefix cache (refcount-zero
            # runs): resident but reclaimable on demand — load signals
            # must not read cache residency as capacity pressure
            info["kv_blocks_evictable"] = (
                b.prefix.evictable_blocks()
                if getattr(b, "prefix", None) is not None else 0)
            if getattr(b, "prefix", None) is not None:
                # TOKEN counts — the fleet-wide cacheable-capacity
                # definition the index and autoscale signals share
                info["prefix_tokens_resident"] = \
                    b.prefix.resident_tokens()
                info["prefix_tokens_evictable"] = \
                    b.prefix.evictable_tokens()
            if getattr(b, "kvtier", None) is not None:
                # fleet-index event feed piggybacks the healthz reply
                # (the heartbeat channel the router already polls)
                info["kvtier_events"] = b.kvtier.drain_events()
                info["kvtier"] = b.kvtier.stats()
        # disaggregated-serving evidence (serve/disagg.py healthz +
        # the disagg soak verdict read these per pool)
        info["migrations_in"] = b.migrations_in
        info["migrate_rejects"] = b.migrate_rejects
        info["migrate_corrupt_detected"] = b.migrate_corrupt_detected
        with b._parked_lock:
            info["parked"] = len(b.parked)
        from ..native.resilience import RETRIES_HELP
        from ..obs import metrics as obs_metrics
        info["migrate_absorbed"] = int(obs_metrics.get_registry().counter(
            "hvd_net_retries_total", RETRIES_HELP,
            {"site": "serve.migrate", "outcome": "absorbed"}).value)
        info.update(b.queue.counters())
        return info


class ReplicaWorker:
    """The whole worker process, assembled from a config dict (see
    :func:`build_worker`). In-process usable for tests; ``main()``
    wraps it for the real spawned process."""

    def __init__(self, cfg: dict):
        from .batcher import ContinuousBatcher
        from .executor import ShardedExecutor
        from .queue import AdmissionQueue

        self.cfg = dict(cfg)
        self.rid = int(cfg["rid"])
        self.gen = int(cfg.get("gen", 0))
        self.ns = str(cfg.get("ns", "fleet"))
        # stamp this process's span recorder with its fleet identity
        # (pool/replica/generation name the Chrome-trace pid row)
        _trace_configure(pool=str(cfg.get("pool") or self.ns),
                         replica=self.rid, gen=self.gen)
        self.hb_interval_s = float(cfg.get("hb_interval_s", 0.125))
        self._events_f = None
        events_path = cfg.get("events_path")
        if events_path:
            self._events_f = open(events_path, "a", buffering=1)
        self._install_chaos(cfg.get("chaos_plan"))

        built = _resolve_builder(
            cfg.get("builder",
                    "horovod_tpu.serve.worker:tiny_gpt_builder"))(
            **(cfg.get("builder_kwargs") or {}))
        self.executor = ShardedExecutor(
            built["model"], built["params"],
            max_batch=int(built.get("max_batch", 4)),
            max_len=int(built.get("max_len", 48)),
            replica_id=self.rid)
        draft = built.get("draft_model")
        self.draft_executor = None if draft is None else ShardedExecutor(
            draft, built["params"],
            max_batch=int(built.get("max_batch", 4)),
            max_len=int(built.get("max_len", 48)),
            replica_id=self.rid, role="draft")
        self.queue = AdmissionQueue(
            max_queue=int(cfg.get("max_queue", 64)),
            default_deadline_ms=float(cfg.get("deadline_ms", 30000.0)),
            replica_id=self.rid)
        self.batcher = ContinuousBatcher(
            self.executor, self.queue,
            buckets=tuple(cfg.get("buckets")
                          or built.get("buckets") or (8,)),
            eos_id=built.get("eos_id"), replica_id=self.rid,
            kv_crc=cfg.get("kv_crc"),
            draft_executor=self.draft_executor,
            spec_k=cfg.get("spec_k"),
            prefix_cache=cfg.get("prefix_cache"),
            kv_tier=cfg.get("kv_tier"),
            kvtier_host_mb=cfg.get("kvtier_host_mb"),
            # a shared spill root is partitioned per replica: two
            # workers scanning one directory would double-count runs
            kvtier_dir=(os.path.join(str(cfg["kvtier_dir"]),
                                     f"r{self.rid}")
                        if cfg.get("kvtier_dir") else None))
        # scheduler-iteration pulse: advances the heartbeat seq AND
        # crosses the serve.proc chaos gate (crash there = SIGKILL of
        # THIS process — the real host loss, see module docstring)
        self.seq = 0
        self.batcher.heartbeat = self._pulse
        # chaos-exempt KV client: the observer plane (heartbeats +
        # endpoint registration) must be neither faulted nor allowed to
        # skew site counters (the PR 5 detector's rule)
        self._kv = None
        kv_addr, kv_port = cfg.get("kv_addr"), cfg.get("kv_port")
        if kv_addr and kv_port:
            from ..native.store import StoreClient
            self._kv = StoreClient(str(kv_addr), int(kv_port),
                                   rank=self.rid, chaos_exempt=True)
        self.subscriber = None
        channel = cfg.get("channel")
        if channel and kv_addr and kv_port:
            from ..native.store import StoreClient
            from ..redist.stream import WeightSubscriber
            self.subscriber = WeightSubscriber(
                str(channel),
                client=StoreClient(str(kv_addr), int(kv_port),
                                   rank=self.rid, chaos_exempt=True),
                template=built["params"])
        self.endpoint = ReplicaEndpoint(
            self.batcher, rid=self.rid,
            host=str(cfg.get("host", "127.0.0.1")))
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.draining = False
        self._drained = threading.Event()

    # -- chaos wiring --------------------------------------------------------
    def _install_chaos(self, plan_obj) -> None:
        if not plan_obj:
            return
        from ..chaos import inject
        from ..chaos.plan import ChaosPlan
        plan = plan_obj if isinstance(plan_obj, ChaosPlan) \
            else ChaosPlan.from_dict(plan_obj)
        # epoch = the worker's GENERATION: a respawned worker's fresh
        # iteration/submit counters re-cross every exact-'at' address,
        # so epoch-pinned faults (the plan composer pins the kill to
        # epoch 0) fire in exactly one incarnation — the same rule the
        # elastic relaunch path uses (HOROVOD_CKPT_RESET_EPOCH)
        inj = inject.install(plan, rank=0, epoch=self.gen)
        if self._events_f is not None:
            f = self._events_f

            def log_event(ev: dict) -> None:
                f.write(json.dumps(ev, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())

            inj.add_listener(log_event)

    def _pulse(self) -> None:
        self.seq += 1
        from ..chaos import inject as _chaos
        if _chaos._INJ is None:
            return
        f = _chaos.fire("serve.proc", peer=self.rid,
                        step=self.batcher.iterations)
        if f is not None and f.kind == "crash":
            # the REAL host loss: no cleanup, no flushes beyond the
            # listener ledger (already fsync'd above), no goodbye on
            # the heartbeat key — exactly what a dead machine looks
            # like to the router's accrual sweep
            os.kill(os.getpid(), signal.SIGKILL)

    # -- lifecycle -----------------------------------------------------------
    def hb_key(self) -> str:
        return f"serve.hb.{self.ns}.g{self.gen}.{self.rid}"

    def ep_key(self) -> str:
        return f"serve.ep.{self.ns}.g{self.gen}.{self.rid}"

    def _hb_value(self) -> bytes:
        """``<seq>:<wall>`` — the sequence the accrual sweep reads plus
        this process's wall clock, the free round-trip clock sample the
        router's trace assembler estimates per-worker offsets from
        (trace/clock.py). Readers that predate the stamp parse the int
        prefix and ignore the rest."""
        return f"{self.seq}:{time.time():.6f}".encode()

    def _post_heartbeats(self) -> None:
        while not self._hb_stop.wait(self.hb_interval_s):
            try:
                self._kv.set(self.hb_key(), self._hb_value())
            except Exception as e:  # noqa: BLE001 — a KV blip must not
                logger.warning(     # kill the poster; stale age is the
                    "replica %d heartbeat post failed: %s",  # signal
                    self.rid, e)

    def _weight_gate(self, timeout_s: float = 30.0) -> None:
        """Adopt the channel's newest PUBLISHED version before taking
        traffic — the respawn re-admission gate, enforced where the
        weights actually land."""
        if self.subscriber is None:
            return
        target = self.subscriber.peek_version()
        if target is None:
            return                    # nothing published yet
        deadline = time.monotonic() + timeout_s
        while (self.executor.params_version or 0) < target:
            try:
                got = self.subscriber.poll()
                if got is not None:
                    self.executor.swap_params(got[1], version=got[0])
            except Exception as e:  # noqa: BLE001
                logger.warning("replica %d weight gate poll failed "
                               "(%s); retrying", self.rid, e)
            if (self.executor.params_version or 0) >= target:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"replica {self.rid} could not adopt weight "
                    f"version {target} within {timeout_s:.0f}s")
            time.sleep(0.05)

    def start(self) -> "ReplicaWorker":
        """Warm up, pass the weight gate, open the endpoint, start
        heartbeating, REGISTER (the registration key doubles as the
        ready signal the router waits on)."""
        self.batcher.warmup()
        self._weight_gate()
        if self.subscriber is not None:
            self.batcher.attach_weights(self.subscriber)
        self.endpoint.start()
        self.batcher.start()
        if self._kv is not None:
            self._kv.set(self.hb_key(), self._hb_value())
            self._hb_thread = threading.Thread(
                target=self._post_heartbeats, daemon=True,
                name=f"hvd-replica-hb-{self.rid}")
            self._hb_thread.start()
            self._kv.set(self.ep_key(), json.dumps({
                "host": self.endpoint.address[0],
                "port": self.endpoint.address[1],
                "pid": os.getpid(),
                "weights_version": self.executor.params_version,
                "t": time.time()}).encode())
        return self

    def drain(self, timeout_s: float = 10.0) -> None:
        """Stop admitting, finish the in-flight tail, stop. New submits
        are rejected with retry-after at the endpoint (never silently
        dropped) while the tail resolves."""
        self.draining = True
        self.batcher.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.queue.depth() == 0 and not self.batcher._active:
                break
            time.sleep(0.05)
        self.close()
        self._drained.set()

    def close(self) -> None:
        self._hb_stop.set()
        self.batcher.stop()
        self.endpoint.close()
        if self.subscriber is not None:
            self.subscriber.close()
        if self._kv is not None:
            self._kv.close()
        if self._events_f is not None:
            self._events_f.close()

    def run_forever(self) -> int:
        """Block until the scheduler dies (rc 1 — the supervisor
        respawns) or a drain COMPLETES (rc 0 — exiting on the mere
        start of a drain would kill the in-flight tail the drain
        exists to finish)."""
        while True:
            if self._drained.is_set():
                return 0
            if self.draining:
                time.sleep(0.1)
                continue
            if not self.batcher.alive():
                logger.error("replica %d scheduler died — exiting so "
                             "the router can respawn a fresh process",
                             self.rid)
                return 1
            time.sleep(0.2)


def main(argv=None) -> int:
    cfg_raw = os.environ.get("HOROVOD_SERVE_WORKER_CFG")
    if not cfg_raw:
        print("serve worker: HOROVOD_SERVE_WORKER_CFG is not set",
              file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO)
    cfg = json.loads(cfg_raw)
    worker = ReplicaWorker(cfg)

    def _sigterm(signum, frame):
        logger.info("replica %d: SIGTERM — draining", worker.rid)
        threading.Thread(target=worker.drain, daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    worker.start()
    logger.info("replica %d ready on %s:%d (gen %d, weights v%s)",
                worker.rid, worker.endpoint.address[0],
                worker.endpoint.address[1], worker.gen,
                worker.executor.params_version)
    return worker.run_forever()


if __name__ == "__main__":
    sys.exit(main())
