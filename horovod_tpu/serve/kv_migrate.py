"""Live paged-KV block migration: prefill computes, decode continues.

The transport half of disaggregated serving (serve/disagg.py): after a
prefill replica computes a prompt's KV into paged blocks (and emits
the first token for TTFT), the sequence's blocks + metadata move to a
decode replica and decode continues BIT-IDENTICAL to colocated
prefill+decode. The design composes three existing disciplines:

* **Plan/transport split** (PAPERS.md, "Memory-efficient array
  redistribution"): :func:`pack_parked` is the pure plan — which
  bytes, which crcs, which metadata — and :func:`push` /
  :func:`install` are the transport, interchangeable (the tier-1
  parity suite drives pack->install fully in-process, no sockets).
* **crc-framed transport** (redist/transport.py): blocks ride a
  BINARY wire frame (serve/wire.py ``send_bin`` — raw bytes after a
  JSON header, never base64 inside JSON) with a frame-level crc32,
  and each block additionally carries its per-leaf crc32 ledger so
  corruption is caught on arrival — before any token could be
  generated from the migrated cache — whether it happened on the wire
  (frame crc) or before framing (block crcs, the chaos
  ``serve.migrate corrupt`` scenario).
* **Replay-safe retries** (PR 9 ladder + the store.cc nonce pattern):
  a ``conn_reset`` that eats the install ack is absorbed by replaying
  the push under the resilience ladder; the decode endpoint dedupes
  on the migration ``fid`` and serves the replay its existing install
  ack, so a severed wire never double-installs.

Fencing: the header carries the prefill executor's ``weights_version``
and the decode batcher refuses to install under any other version
(checked again after the device writes — a hot swap landing mid-install
tears the install down, never the token stream). A fenced-off
migration re-prefills cleanly on the sender side; stale-KV tokens are
unreachable by construction.

What travels, per sequence: the block table's byte content (every
cache leaf's ``[0, filled)`` positions per block), the per-block
per-leaf crc32 ledger, the prompt + emitted-token prefix, the
sampling state (temperature/top-p/seed + the rng draw counter, so a
seeded stream continues exactly where prefill left it), and the
weight version.
"""
from __future__ import annotations

import time
import zlib
from typing import List, Optional, Tuple

from ..chaos import inject as _chaos
from ..native import resilience
from ..trace.spans import get_recorder as _trace_recorder
from . import wire

#: how long the pushing side waits for the decode endpoint's install
#: ack (covers the decode scheduler picking the entry up at its next
#: iteration plus the device writes)
INSTALL_ACK_TIMEOUT_S = 20.0


class MigrateCorrupt(RuntimeError):
    """A migration payload failed a crc check — on the source re-read
    (pre-flight, the sender's own ledger) or on arrival (the
    per-block crcs in the header). Never retried blindly: the sender
    re-packs from the source of truth or re-prefills."""


def pack_parked(batcher, rid: int, *, fid: str,
                max_new_tokens: int,
                deadline_ms: float) -> Optional[Tuple[dict, bytes]]:
    """Build the migration packet for parked request ``rid``:
    ``(header, payload)`` where ``payload`` is the raw concatenated
    block bytes (block-major, cache-leaf-minor) and ``header`` is the
    JSON-able metadata incl. the per-block per-leaf crc32 ledger and
    ``payload_crc`` for the wire frame. Returns None when ``rid`` is
    not parked (already released / reaped / never held).

    ``max_new_tokens`` is the ORIGINAL generation budget (the parked
    prefill request ran with budget 1 — its first token is already in
    the packet's ``out``); ``deadline_ms`` the remaining client
    deadline the decode side enforces.

    Pre-flight integrity: when the source batcher runs its crc ledger
    (kv_crc), every block's re-read is verified against it before the
    bytes can travel — a corruption that happened at rest on the
    prefill replica raises :class:`MigrateCorrupt` here instead of
    migrating garbage.
    """
    # PIN the parked row for the whole read: the scheduler's TTL
    # reaper (or a racing release) must not free — and the pool
    # re-issue — these blocks mid-pack, or the crcs would be stamped
    # over another sequence's bytes with every check green
    seq = batcher.pin_parked(rid)
    if seq is None:
        return None
    try:
        ex = batcher.executor
        kv = batcher.kv
        pool = kv.pool
        bs = kv.block_size
        cache_len = int(seq.cache_len)
        blocks = list(kv.blocks[seq.slot])
        n_blocks = -(-cache_len // bs)
        metas: List[dict] = []
        chunks: List[bytes] = []
        for bi in range(n_blocks):
            blk = blocks[bi]
            filled = min(cache_len - bi * bs, bs)
            ledger_hi = pool.crc_filled(blk)
            if batcher.kv_crc and ledger_hi >= filled > 0:
                # verify the full ledgered span against the
                # write-side crcs, then slice the migrated prefix out
                # of the same read (one readback, no re-read race)
                full = ex.kv_block_bytes(blk, 0, ledger_hi)
                if not pool.crc_check(blk, full):
                    raise MigrateCorrupt(
                        f"block {blk} failed its source crc ledger "
                        f"on the pre-flight re-read (request {rid})")
                leaf_bytes = [raw[:(len(raw) // ledger_hi) * filled]
                              for raw in full]
            else:
                leaf_bytes = ex.kv_block_bytes(blk, 0, filled)
            metas.append({
                "filled": filled,
                "crcs": [zlib.crc32(raw) for raw in leaf_bytes],
                "nbytes": [len(raw) for raw in leaf_bytes],
            })
            chunks.extend(leaf_bytes)
        payload = b"".join(chunks)
        req = seq.req
        header = {
            "op": "kv_install", "fid": str(fid), "rid": int(rid),
            "prompt": [int(t) for t in req.prompt],
            "out": [int(t) for t in seq.out],
            "cache_len": cache_len,
            "max_new_tokens": int(max_new_tokens),
            "deadline_ms": float(deadline_ms),
            "temperature": float(req.temperature),
            "top_p": float(req.top_p),
            "seed": int(req.seed),
            "rng_ctr": int(seq.rng_ctr),
            # the version the PREFILL actually ran under (stamped by
            # the batcher at the prefill step; None = no version
            # published yet) — pack-time params_version would relabel
            # stale KV as current across a hot swap
            "weights_version": seq.params_version,
            "block_size": bs,
            "blocks": metas,
            "payload_crc": zlib.crc32(payload),
        }
        if req.trace is not None:
            # the trace context rides the migration header so the
            # decode side's spans join the same tree; the park span
            # covers parked-in-_retire -> packed-here
            header["trace"] = req.trace
            if seq.parked_at is not None:
                base = time.time() - time.monotonic()
                _trace_recorder().record(
                    req.trace, "park",
                    seq.parked_at + base, time.time(), rid=int(rid))
        return header, payload
    finally:
        batcher.unpin_parked(rid)


def unpack_blocks(header: dict, payload: bytes) -> List[dict]:
    """Slice ``payload`` back into per-block per-leaf byte strings and
    VERIFY each against the header's crc ledger — the arrival-side
    integrity gate. Raises :class:`MigrateCorrupt` on any mismatch
    (the caller counts it and acks ``migrate_corrupt``; no byte
    reaches a device pool)."""
    blocks: List[dict] = []
    off = 0
    for bi, m in enumerate(header.get("blocks", [])):
        leaf_bytes = []
        for want_n, want_crc in zip(m["nbytes"], m["crcs"]):
            raw = payload[off:off + int(want_n)]
            if len(raw) != int(want_n):
                raise MigrateCorrupt(
                    f"payload truncated at block {bi} "
                    f"({len(raw)}/{want_n} bytes)")
            if zlib.crc32(raw) != int(want_crc):
                raise MigrateCorrupt(
                    f"block {bi} failed its crc32 on arrival "
                    f"(corrupted in flight)")
            leaf_bytes.append(raw)
            off += int(want_n)
        blocks.append({"filled": int(m["filled"]),
                       "leaf_bytes": leaf_bytes,
                       "crcs": [int(c) for c in m["crcs"]]})
    if off != len(payload):
        raise MigrateCorrupt(
            f"payload carries {len(payload) - off} unclaimed trailing "
            f"bytes")
    return blocks


def install(batcher, header: dict, payload: bytes, *,
            timeout_s: float = INSTALL_ACK_TIMEOUT_S
            ) -> Tuple[str, Optional[object], Optional[object]]:
    """The decode-side receive path (endpoint thread): crc-verify the
    payload, hand the sequence to the scheduler thread
    (``submit_migrated``) and wait for the install outcome. Returns
    ``(outcome, detail, handle)`` where outcome is ``"installed"`` |
    ``"corrupt"`` | ``"version_mismatch"`` | ``"rejected"`` |
    ``"incompatible"`` | ``"error"`` | ``"stalled"``; the handle (set
    on "installed") resolves when decode finishes the sequence."""
    try:
        blocks = unpack_blocks(header, payload)
    except MigrateCorrupt as e:
        batcher.note_migrate_corrupt()
        return "corrupt", str(e), None
    ent = batcher.submit_migrated(header, blocks)
    if not ent["evt"].wait(timeout_s):
        return "stalled", "decode scheduler did not install in time", \
            None
    outcome, detail = ent["outcome"]
    return outcome, detail, (ent["handle"]
                             if outcome == "installed" else None)


def push(addr: Tuple[str, int], header: dict, payload: bytes, *,
         peer: Optional[int] = None,
         ladder: Optional[resilience.RetryPolicy] = None,
         timeout_s: float = INSTALL_ACK_TIMEOUT_S) -> dict:
    """The prefill-side network push: dial the decode endpoint, send
    the binary kv_install frame, await the install ack — under the
    resilience ladder, so transport blips replay the push and the
    decode endpoint's fid dedupe keeps replay-after-install safe.

    The ``serve.migrate`` chaos site fires here, once per attempt
    (``peer`` = the decode replica id): ``drop`` loses the push before
    the dial (retryable — the ladder replays), ``conn_reset`` severs
    the socket AFTER the frame landed (the ack is lost; the replay
    must be served the deduped install ack), ``corrupt`` flips one
    payload bit BEFORE framing — the frame crc is recomputed over the
    corrupted bytes, so only the per-block ledger can catch it on
    arrival (exactly the "corrupt at source" case the block crcs
    exist for), ``delay`` sleeps inside the injector."""
    if ladder is None:
        ladder = resilience.policy()

    def attempt() -> dict:
        body, head = payload, header
        if _chaos._INJ is not None:
            f = _chaos.fire("serve.migrate", peer=peer)
            if f is not None and f.kind == "drop":
                raise wire.DispatchConnError(
                    f"chaos: migration push dropped (peer {peer})")
            if f is not None and f.kind == "corrupt":
                # pre-framing corruption: the frame crc is stamped
                # over the CORRUPTED bytes so it passes — detection
                # must come from the per-block crc ledger on arrival
                body = _chaos.corrupt_copy(payload)
                head = dict(header, payload_crc=zlib.crc32(body))
            if f is not None and f.kind in ("conn_reset", "flaky"):
                s = wire.connect(addr, timeout=5.0)
                try:
                    wire.send_bin(s, head, body)
                    time.sleep(0.01)   # let the frame land
                finally:
                    s.close()
                raise wire.DispatchConnError(
                    f"chaos: injected {f.kind} at serve.migrate "
                    f"(peer {peer})")
        sock = wire.connect(addr, timeout=5.0)
        try:
            wire.send_bin(sock, head, body)
            return wire.recv_msg(sock, timeout=timeout_s)
        finally:
            sock.close()

    return ladder.run(attempt,
                      what=f"migrate(fid {header.get('fid')})",
                      site="serve.migrate", plane="serve")
