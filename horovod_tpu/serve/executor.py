"""Sharded model executor: the single jitted entry point of the server.

Drives a decode-mode model (models/gpt.py or models/llama.py with
``cfg.decode=True``) at **fixed shapes**: every call is
``[max_batch, T]`` tokens with per-row positions, an update mask and a
per-row last-token index, where T is 1 (decode) or one of the configured
prefill buckets. Because batch membership is carried in *data* (mask,
positions) rather than *shape*, sequences can join and leave at
iteration granularity without ever invalidating the jit cache — the
no-recompile contract the continuous batcher (serve/batcher.py) is
built on.

Two decisions are frozen at build time so the jit cache stays flat:

* **Kernel**: paged executors resolve the decode-attention kernel ONCE
  (``HOROVOD_SERVE_KERNEL`` via `ops.pallas_paged.resolve_kernel` —
  fused Pallas on TPU by default, the XLA gather oracle as CPU
  fallback) and stamp it into the model config before the first trace.
  The resolved path is named by a one-shot **KERNEL** timeline instant
  and the ``kernel`` label on ``hvd_serve_step_ms``, so a silent
  fallback to XLA on TPU is visible in the trace and in /metrics.
* **Sampling**: token selection runs ON DEVICE inside the jitted step
  — temperature / top-p with per-request seeds threaded as row data
  (``sample=`` arrays), greedy being the ``temperature == 0`` special
  case (an all-greedy batch takes a sort-free `lax.cond` branch of the
  same program). Only the per-row EMITTING position's logits are
  computed (``logits_idx`` gathers before the lm_head), and the
  speculative verify step applies the rejection-sampling accept rule
  on device (`ops.pallas_paged.speculative_accept`), returning the
  emitted tokens instead of raw argmaxes.

Sharding rides the training stack unchanged: pass `mesh` plus the
model's `PartitionRules` (parallel/tp.py) and parameters are placed with
`shard_params`; jit/GSPMD then emits the same ICI collectives the
training step uses. The KV cache and token buffers default to
replicated, which is correct for TP (activations replicated, weights
sharded) — the Megatron serving layout.

Observability: each step lands on the timeline's **SERVE** row
(`timeline.instant("SERVE", {...})`) with step latency, step kind,
queue depth / batch occupancy / shed count (supplied by the batcher) and
a rolling tokens/s, next to the engine's WIRE_BYTES row in the same
trace.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics

logger = logging.getLogger("horovod_tpu")


class ShardedExecutor:
    """Owns the params, the device KV cache and the one jitted step."""

    def __init__(self, model: Any, params: Any, *, max_batch: int,
                 max_len: int, mesh=None, partition_rules=None,
                 timeline=None, replica_id: Optional[int] = None,
                 role: str = "target"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1; got {max_batch}")
        if role not in ("target", "draft"):
            raise ValueError(f"role must be 'target'|'draft'; got {role!r}")
        model_max = getattr(getattr(model, "cfg", None), "max_seq_len",
                            None)
        if model_max is not None and max_len > model_max:
            # the cache arrays are shaped by the model's max_seq_len; a
            # larger executor max_len would silently clamp cache writes
            # and position lookups instead of erroring
            raise ValueError(
                f"max_len {max_len} exceeds the model's max_seq_len "
                f"{model_max}")
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.timeline = timeline
        #: "draft" executors (speculative decoding proposers) share the
        #: process with a target executor: they must neither reclaim
        #: the serve metric families nor blend into the target's series
        self.role = role
        # -- paged layout (model-config driven): the device cache is a
        # block pool and every step takes per-row block tables
        cfg = getattr(model, "cfg", None)
        self.kv_block_size = int(getattr(cfg, "kv_block_size", 0) or 0)
        self.kv_pool_blocks = int(getattr(cfg, "kv_pool_blocks", 0) or 0)
        self.paged = self.kv_block_size > 0
        #: fixed block-table width: enough entries to address max_len
        self.blocks_per_seq = (
            -(-max_len // self.kv_block_size) if self.paged else 0)
        if self.paged and \
                self.kv_pool_blocks < self.blocks_per_seq:
            raise ValueError(
                f"kv_pool_blocks {self.kv_pool_blocks} cannot cover one "
                f"max_len sequence ({self.blocks_per_seq} blocks of "
                f"{self.kv_block_size})")
        #: vocab width — the verify step's draft-probs row shape
        self.vocab_size = int(getattr(cfg, "vocab_size", 0) or 0)
        # -- decode kernel, resolved ONCE before the first trace: the
        # model reads cfg.decode_kernel at trace time, so stamping the
        # resolution here keeps every compiled program on one path and
        # the jit cache flat. Slotted executors (draft models included)
        # always run the XLA path — the fused kernel is block-table
        # shaped; HOROVOD_SERVE_KERNEL names the PAGED hot path.
        from ..ops.pallas_paged import resolve_kernel
        if self.paged:
            self.kernel = resolve_kernel(
                getattr(cfg, "decode_kernel", None))
            if cfg is not None:
                cfg.decode_kernel = self.kernel
        else:
            self.kernel = "xla"
        # kept for hot weight swaps (redist/stream.py): replacement
        # params are placed exactly like the originals
        self._mesh = mesh
        self._rules = partition_rules
        if mesh is not None and partition_rules is not None:
            from ..parallel.tp import shard_params
            params = shard_params(params, mesh, partition_rules)
        self.params = params
        # the swap/version fence: step() holds this lock for the whole
        # forward, swap_params() takes it to replace self.params — a
        # swap can therefore land only BETWEEN decode iterations, never
        # mid-step, and no step ever mixes two param versions
        self._swap_lock = threading.Lock()
        self.params_version: Optional[int] = None
        self.swaps = 0
        # -- metrics --
        self.steps = 0
        self.tokens_out = 0
        self.step_latencies_ms: "deque[float]" = deque(maxlen=1024)
        self._tok_window: "deque[Tuple[float, int]]" = deque(maxlen=1024)
        #: distinct (kind, T) entry points actually executed — the
        #: jit-signature ledger the no-recompile tests assert on
        self.signatures: Set[Tuple[str, int]] = set()
        # registry series: per-kind step latency histogram + generated
        # tokens. Claimed fresh per executor when standalone (one
        # serving stack per process); a FLEET replica instead passes
        # replica_id and gets get-or-create labeled children, so one
        # replica's (re)construction never clobbers its siblings'
        # series and a restarted replica keeps counting where it left
        # off (serve/fleet.py).
        self.replica_id = replica_id
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        if role == "draft":
            rl = dict(rl, role="draft")
        R = obs_metrics.get_registry()
        if replica_id is None and role == "target":
            # only the TARGET standalone executor claims the families
            # fresh: a draft executor is constructed NEXT TO a target in
            # the same process and must not clobber its series
            R.unregister("hvd_serve_step_ms")
            R.unregister("hvd_serve_tokens_total")
        # get-or-create, NOT claimed fresh: a multi-replica fleet runs
        # several executors in one process and the swap series is
        # fleet-shared (redist/stream.py)
        self._m_swap_ms = R.histogram(
            "hvd_weight_swap_ms",
            "hot weight swap: new params placed + adopted (ms)")
        self._m_step_ms = {
            k: R.histogram("hvd_serve_step_ms",
                           "executor step latency by kind (ms)",
                           dict(rl, kind=k, kernel=self.kernel))
            for k in ("prefill", "decode", "verify")}
        self._m_tokens = R.counter(
            "hvd_serve_tokens_total", "tokens generated", rl or None)

        # -- the jitted steps. Token selection runs ON DEVICE
        # (ops/pallas_paged.py sampling): per-row temperature / top-p /
        # seed / draw-counter ride as data through the fixed shapes.
        #
        #   _fwd_token   prefill + decode: only the per-row EMITTING
        #                position's logits are computed (logits_idx
        #                gathers hidden states before the lm_head — the
        #                step's largest GEMM runs [B, 1, V], never
        #                [B, bucket, V]); returns the sampled token
        #                [B], plus the filtered sampling distribution
        #                [B, V] on DRAFT executors (what the verify
        #                step consumes as q).
        #   _fwd_verify  the fused speculative verify: full [B, T, V]
        #                logits (every draft position emits), the
        #                rejection-sampling accept rule applied on
        #                device -> (emitted [B, T], n_accept [B]).
        from ..ops.pallas_paged import (STREAM_DRAFT, STREAM_SAMPLE,
                                        sample_with_probs,
                                        speculative_accept)
        stream = STREAM_DRAFT if role == "draft" else STREAM_SAMPLE
        emit_probs = role == "draft"

        def apply_model(params, cache, tokens, positions, mask, tables,
                        logits_idx):
            kw = {"block_tables": tables} if self.paged else {}
            return self.model.apply(
                {"params": params, "cache": cache}, tokens,
                positions=positions, update_mask=mask,
                logits_idx=logits_idx, mutable=["cache"], **kw)

        if self.paged:
            def fwd_token(params, cache, tokens, positions, mask,
                          last_idx, temp, top_p, seed, ctr, tables):
                logits, vout = apply_model(params, cache, tokens,
                                           positions, mask, tables,
                                           last_idx)
                tok, probs = sample_with_probs(
                    logits[:, 0], temp, top_p, seed, ctr, stream=stream)
                if emit_probs:
                    return tok, probs, vout["cache"]
                return tok, vout["cache"]

            def fwd_verify(params, cache, tokens, positions, mask,
                           temp, top_p, seed, ctr, dprobs, n_draft,
                           tables):
                logits, vout = apply_model(params, cache, tokens,
                                           positions, mask, tables,
                                           None)
                emitted, n_acc = speculative_accept(
                    tokens, dprobs, logits, n_draft, temp, top_p, seed,
                    ctr)
                return emitted, n_acc, vout["cache"]
        else:
            def fwd_token(params, cache, tokens, positions, mask,
                          last_idx, temp, top_p, seed, ctr):
                logits, vout = apply_model(params, cache, tokens,
                                           positions, mask, None,
                                           last_idx)
                tok, probs = sample_with_probs(
                    logits[:, 0], temp, top_p, seed, ctr, stream=stream)
                if emit_probs:
                    return tok, probs, vout["cache"]
                return tok, vout["cache"]

            def fwd_verify(params, cache, tokens, positions, mask,
                           temp, top_p, seed, ctr, dprobs, n_draft):
                logits, vout = apply_model(params, cache, tokens,
                                           positions, mask, None, None)
                emitted, n_acc = speculative_accept(
                    tokens, dprobs, logits, n_draft, temp, top_p, seed,
                    ctr)
                return emitted, n_acc, vout["cache"]

        # donating the cache lets XLA update it in place on TPU; CPU
        # does not support donation and would only warn
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._fwd_token = jax.jit(fwd_token, donate_argnums=donate)
        self._fwd_verify = jax.jit(fwd_verify, donate_argnums=donate)

        # materialize the zero cache once (a separate cache-creating
        # trace; steady-state steps all go through _fwd_token/_fwd_verify)
        def make_cache(params, tokens, positions, mask, tables):
            kw = {"block_tables": tables} if self.paged else {}
            _, v = self.model.apply(
                {"params": params}, tokens, positions=positions,
                update_mask=mask, mutable=["cache"], **kw)
            return v["cache"]

        z = jnp.zeros((max_batch, 1), jnp.int32)
        zt = jnp.full((max_batch, max(self.blocks_per_seq, 1)), -1,
                      jnp.int32)
        self.cache = jax.jit(make_cache, static_argnums=())(
            params, z, jnp.zeros((max_batch,), jnp.int32),
            jnp.zeros((max_batch,), bool), zt)

        if self.paged:
            # CoW block copy, jitted once (shapes are static): donation
            # makes it an in-place pool write on TPU instead of a full
            # pool copy per CoW
            NB, BS = self.kv_pool_blocks, self.kv_block_size

            def copy_block(cache, src, dst):
                def cp(leaf):
                    if getattr(leaf, "ndim", 0) == 4 and \
                            leaf.shape[0] == NB and leaf.shape[1] == BS:
                        return leaf.at[dst].set(leaf[src])
                    return leaf
                return jax.tree_util.tree_map(cp, cache)

            self._copy_block = jax.jit(
                copy_block, donate_argnums=() if
                jax.default_backend() == "cpu" else (0,))
        #: params_version the most recent step actually ran under (set
        #: inside the step lock) — what lets the batcher detect a swap
        #: landing between its prefix-cache lookup and the prefill
        self.last_step_version: Optional[int] = None
        # one-shot KERNEL instant: names the RESOLVED decode kernel so
        # a silent fallback to XLA on TPU is visible in the trace
        logger.info(
            "serve executor (replica=%s role=%s): decode kernel=%s "
            "paged=%s backend=%s", replica_id, role, self.kernel,
            self.paged, jax.default_backend())
        if self.timeline is not None:
            self.timeline.instant("KERNEL", {
                "kernel": self.kernel, "paged": self.paged,
                "role": role, "backend": jax.default_backend()})

    # -- the one step --------------------------------------------------------
    def _default_sample(self) -> Dict[str, np.ndarray]:
        """Greedy row data: temperature 0 everywhere (the all-greedy
        `lax.cond` fast path inside the jitted step)."""
        B = self.max_batch
        return {"temperature": np.zeros(B, np.float32),
                "top_p": np.ones(B, np.float32),
                "seed": np.zeros(B, np.uint32),
                "ctr": np.zeros(B, np.int32)}

    def step(self, tokens: np.ndarray, positions: np.ndarray,
             mask: np.ndarray, last_idx: np.ndarray, *,
             kind: str = "decode",
             stats: Optional[Dict[str, Any]] = None,
             block_tables: Optional[np.ndarray] = None,
             sample: Optional[Dict[str, np.ndarray]] = None,
             draft_probs=None, n_draft: Optional[np.ndarray] = None):
        """Run one fixed-shape forward step.

        tokens [max_batch, T] int32; positions/last_idx [max_batch]
        int32; mask [max_batch] bool; block_tables
        [max_batch, blocks_per_seq] int32 (paged executors only).
        ``sample`` carries the per-row sampling data (temperature /
        top_p / seed / ctr arrays, [max_batch] each); None is greedy.
        `stats` (queue depth, occupancy, shed count — batcher-supplied)
        is folded into the SERVE event.

        Returns, valid where `mask` is set:

        * ``kind="prefill"`` / ``"decode"``: the sampled next token per
          row, ``[max_batch]`` int32 (the emitting position is
          ``last_idx`` — its logits are the only ones computed). A
          DRAFT executor returns ``(tokens, probs)`` where ``probs``
          [max_batch, V] is the on-device filtered distribution each
          token was drawn from.
        * ``kind="verify"``: ``(emitted [max_batch, T] int32,
          n_accept [max_batch] int32)`` — the rejection-sampling (or,
          at temperature 0, bit-identical greedy) accept rule applied
          on device against ``draft_probs`` [max_batch, T-1, V] with
          per-row real proposal counts ``n_draft``.
        """
        t0 = time.perf_counter()
        self.signatures.add((kind, int(tokens.shape[1])))
        if self.paged:
            if block_tables is None:
                raise ValueError("a paged executor step needs "
                                 "block_tables")
            extra = (jnp.asarray(block_tables, jnp.int32),)
        else:
            extra = ()
        s = sample if sample is not None else self._default_sample()
        sargs = (jnp.asarray(s["temperature"], jnp.float32),
                 jnp.asarray(s["top_p"], jnp.float32),
                 jnp.asarray(s["seed"], jnp.uint32),
                 jnp.asarray(s["ctr"], jnp.int32))
        probs = None
        with self._swap_lock:   # the weight-swap version fence
            self.last_step_version = self.params_version
            if kind == "verify":
                B, T = self.max_batch, int(tokens.shape[1])
                if draft_probs is None:
                    draft_probs = jnp.zeros((B, T - 1, self.vocab_size),
                                            jnp.float32)
                nd = jnp.asarray(
                    n_draft if n_draft is not None
                    else np.zeros(B, np.int32), jnp.int32)
                emitted, n_acc, self.cache = self._fwd_verify(
                    self.params, self.cache,
                    jnp.asarray(tokens, jnp.int32),
                    jnp.asarray(positions, jnp.int32),
                    jnp.asarray(mask, bool), *sargs, draft_probs, nd,
                    *extra)
                # host readback doubles as completion fence — inside
                # the lock so a swap never lands mid-step
                nxt = (np.asarray(emitted), np.asarray(n_acc))
            else:
                out = self._fwd_token(
                    self.params, self.cache,
                    jnp.asarray(tokens, jnp.int32),
                    jnp.asarray(positions, jnp.int32),
                    jnp.asarray(mask, bool),
                    jnp.asarray(last_idx, jnp.int32), *sargs, *extra)
                if self.role == "draft":
                    tok, probs, self.cache = out
                else:
                    tok, self.cache = out
                nxt = np.asarray(tok)
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self.steps += 1
        self.step_latencies_ms.append(dt_ms)
        self._m_step_ms.get(kind, self._m_step_ms["decode"]).observe(dt_ms)
        n_tok = int(np.sum(mask))
        self.tokens_out += n_tok
        self._m_tokens.inc(n_tok)
        self._tok_window.append((time.perf_counter(), n_tok))
        if self.timeline is not None:
            ev = {"kind": kind, "step_ms": round(dt_ms, 3),
                  "tokens": n_tok, "tokens_per_s": round(self.tokens_per_s(), 1)}
            if stats:
                ev.update(stats)
            self.timeline.instant("SERVE", ev)
        if self.role == "draft" and kind != "verify":
            # the filtered proposal distribution stays ON DEVICE — the
            # batcher hands it straight to the target's verify step
            return nxt, probs
        return nxt

    # -- hot weight swap (redist/stream.py consumer) -------------------------
    def swap_params(self, new_params: Any, *,
                    version: Optional[int] = None) -> bool:
        """Adopt ``new_params`` between decode iterations.

        The version fence: the step lock guarantees no swap lands while
        a forward is in flight (no torn step — every launched program
        sees exactly one param version), and adoption is MONOTONE —
        a ``version`` at or below the current one is refused (returns
        False) so out-of-order polls across replicas can never roll
        weights backwards. The structure must match the serving params
        exactly (same treedef/shapes); placement (mesh + partition
        rules) mirrors the constructor.

        Returns True on adoption; observes ``hvd_weight_swap_ms`` and
        emits a SWAP timeline instant."""
        import jax

        t0 = time.perf_counter()
        if version is not None and self.params_version is not None \
                and version <= self.params_version:
            return False
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def or any(
                np.shape(a) != np.shape(b)
                # .dtype without np.asarray: materializing device
                # arrays to host just to read their dtype would cost an
                # O(model) transfer per swap (and raise on multi-host
                # GSPMD leaves)
                or getattr(a, "dtype", None) != getattr(b, "dtype",
                                                        None)
                for a, b in zip(old_leaves, new_leaves)):
            # dtype is part of the jitted step's signature: adopting
            # fp32 master weights into a bf16 executor would not error
            # — it would recompile EVERY bucket mid-traffic. Fail fast
            # instead; the publisher must cast to the serving dtype.
            raise ValueError(
                "swap_params: replacement tree does not match the "
                "serving params (treedef/shape/dtype mismatch) — "
                "refusing a structurally torn swap (a dtype change "
                "would recompile every serving bucket mid-traffic)")
        if self._mesh is not None and self._rules is not None:
            from ..parallel.tp import shard_params
            new_params = shard_params(new_params, self._mesh,
                                      self._rules)
        else:
            new_params = jax.tree_util.tree_map(jnp.asarray, new_params)
        with self._swap_lock:
            # re-check under the lock: another subscriber thread may
            # have adopted a newer version while we placed this one
            if version is not None and self.params_version is not None \
                    and version <= self.params_version:
                return False
            self.params = new_params
            self.params_version = version if version is not None else \
                (self.params_version or 0) + 1
            self.swaps += 1
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self._m_swap_ms.observe(dt_ms)
        if self.timeline is not None:
            self.timeline.instant("SWAP", {
                "version": self.params_version,
                "swap_ms": round(dt_ms, 3)})
        return True

    # -- KV integrity hooks (serve.kv chaos + crc option) --------------------
    def _cache_leaves(self) -> list:
        """The device KV arrays inside the flax cache collection, in
        flatten order: every ``[max_batch, L, H_kv, D]`` slotted leaf —
        or, for a paged executor, every ``[pool_blocks, block_size,
        H_kv, D]`` pool leaf — (cache_k and cache_v of each layer)."""
        leaves = jax.tree_util.tree_leaves(self.cache)
        if self.paged:
            return [l for l in leaves
                    if getattr(l, "ndim", 0) == 4
                    and l.shape[0] == self.kv_pool_blocks
                    and l.shape[1] == self.kv_block_size]
        return [l for l in leaves
                if getattr(l, "ndim", 0) == 4
                and l.shape[0] == self.max_batch]

    def kv_slot_bytes(self, slot: int, start: int,
                      stop: int) -> list:
        """Host bytes of positions ``[start, stop)`` of ``slot``'s row
        in each cache leaf (leaf order) — what the per-slot crc ledger
        (SlotKVCache.crc_update/crc_check) streams over. Decode reads
        one position; the verify-on-read pass re-reads the whole valid
        prefix once per retiring request."""
        return [np.asarray(l[slot, start:stop]).tobytes()
                for l in self._cache_leaves()]

    def kv_block_bytes(self, block: int, start: int,
                       stop: int) -> list:
        """Paged sibling of :meth:`kv_slot_bytes`: host bytes of
        positions ``[start, stop)`` of pool block ``block`` in each
        cache leaf — what the per-BLOCK crc ledger
        (BlockPool.crc_stream/crc_check) runs over."""
        return [np.asarray(l[block, start:stop]).tobytes()
                for l in self._cache_leaves()]

    def copy_kv_block(self, src: int, dst: int) -> None:
        """Device-side copy of pool block ``src`` onto ``dst`` in every
        cache leaf — the copy-on-write body behind partial prefix-block
        sharing (serve/prefix.py). One precompiled program; call once
        from warmup so the first divergent prompt never meets a
        compile."""
        if not self.paged:
            raise RuntimeError("copy_kv_block is paged-only")
        with self._swap_lock:   # never tear a step in flight
            self.cache = self._copy_block(
                self.cache, jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32))

    def install_kv_blocks(self, blocks: "list[int]",
                          block_leaf_bytes: "list[list[bytes]]",
                          lengths: "list[int]") -> None:
        """Write migrated KV bytes into pool blocks ``blocks``:
        ``block_leaf_bytes[j]`` carries one bytes object per cache
        leaf (leaf order, the order :meth:`kv_block_bytes` reads) for
        block ``blocks[j]``, covering positions ``[0, lengths[j])`` —
        the receive half of paged KV-block migration
        (serve/kv_migrate.py). BATCHED: one scatter per cache leaf
        for the whole sequence (positions past ``lengths[j]`` land as
        zeros — unreachable by the positional mask, and overwritten
        by the first decode write that needs them), not a full-pool
        functional update per (block, leaf). Byte counts are
        validated against the leaf dtype/shape before anything lands,
        and the write runs under the swap lock so it can never tear a
        step in flight."""
        if not self.paged:
            raise RuntimeError("install_kv_blocks is paged-only")
        if not blocks:
            return
        bs = self.kv_block_size
        with self._swap_lock:
            leaves, treedef = jax.tree_util.tree_flatten(self.cache)
            idxs = [i for i, l in enumerate(leaves)
                    if getattr(l, "ndim", 0) == 4
                    and l.shape[0] == self.kv_pool_blocks
                    and l.shape[1] == bs]
            if any(len(lb) != len(idxs) for lb in block_leaf_bytes):
                raise ValueError(
                    f"install_kv_blocks: payload leaf counts "
                    f"{[len(lb) for lb in block_leaf_bytes]} do not "
                    f"match the {len(idxs)} cache leaves — the "
                    f"sender's model layout does not match")
            ids = jnp.asarray(blocks, jnp.int32)
            for li, i in enumerate(idxs):
                leaf = leaves[i]
                tail = leaf.shape[2:]
                row = int(np.prod(tail)) * leaf.dtype.itemsize
                stacked = np.zeros((len(blocks), bs) + tail,
                                   leaf.dtype)
                for j, (lb, length) in enumerate(
                        zip(block_leaf_bytes, lengths)):
                    raw = lb[li]
                    if len(raw) != int(length) * row:
                        raise ValueError(
                            f"install_kv_blocks: leaf payload of "
                            f"{len(raw)} bytes != expected "
                            f"{int(length) * row} for {length} "
                            f"positions of {tail} {leaf.dtype} — "
                            f"incompatible pool layouts")
                    stacked[j, :int(length)] = np.frombuffer(
                        raw, dtype=leaf.dtype).reshape(
                        (int(length),) + tail)
                leaves[i] = leaf.at[ids].set(jnp.asarray(stacked))
            self.cache = jax.tree_util.tree_unflatten(treedef, leaves)

    def corrupt_kv_slot(self, slot: int, length: int) -> None:
        """Flip one deterministically chosen bit inside ``slot``'s
        valid cache prefix — the chaos ``serve.kv`` fault body. Real
        device bytes change, so detection must come from the crc
        ledger, not from bookkeeping."""
        from ..chaos import inject as _chaos
        with self._swap_lock:   # never tear a step in flight
            leaves, treedef = jax.tree_util.tree_flatten(self.cache)
            idx = next(i for i, l in enumerate(leaves)
                       if getattr(l, "ndim", 0) == 4
                       and l.shape[0] == self.max_batch)
            row = np.array(leaves[idx][slot, :length])
            flipped = np.frombuffer(
                _chaos.corrupt_copy(row.tobytes()),
                dtype=row.dtype).reshape(row.shape)
            leaves[idx] = leaves[idx].at[slot, :length].set(
                jnp.asarray(flipped))
            self.cache = jax.tree_util.tree_unflatten(treedef, leaves)

    def corrupt_kv_block(self, block: int, length: int) -> None:
        """Paged ``serve.kv`` fault body: flip one bit inside the first
        ``length`` positions of pool block ``block`` — real device
        bytes, caught only by the per-block crc ledger."""
        from ..chaos import inject as _chaos
        if not self.paged:
            raise RuntimeError("corrupt_kv_block is paged-only")
        with self._swap_lock:
            leaves, treedef = jax.tree_util.tree_flatten(self.cache)
            idx = next(i for i, l in enumerate(leaves)
                       if getattr(l, "ndim", 0) == 4
                       and l.shape[0] == self.kv_pool_blocks
                       and l.shape[1] == self.kv_block_size)
            row = np.array(leaves[idx][block, :length])
            flipped = np.frombuffer(
                _chaos.corrupt_copy(row.tobytes()),
                dtype=row.dtype).reshape(row.shape)
            leaves[idx] = leaves[idx].at[block, :length].set(
                jnp.asarray(flipped))
            self.cache = jax.tree_util.tree_unflatten(treedef, leaves)

    # -- metrics -------------------------------------------------------------
    def tokens_per_s(self) -> float:
        """Rolling throughput over the retained step window."""
        if len(self._tok_window) < 2:
            return 0.0
        t_first = self._tok_window[0][0]
        t_last = self._tok_window[-1][0]
        if t_last <= t_first:
            return 0.0
        toks = sum(n for _, n in self._tok_window) - self._tok_window[0][1]
        return toks / (t_last - t_first)

    def p50_step_ms(self) -> Optional[float]:
        if not self.step_latencies_ms:
            return None
        return float(np.median(self.step_latencies_ms))

    def jit_cache_size(self) -> int:
        """Compiled-program count across the step functions (falls back
        to the executed-signature count on jax versions without the
        introspection hook) — the churn tests assert this is flat."""
        try:
            return int(self._fwd_token._cache_size()
                       + self._fwd_verify._cache_size())
        except Exception:  # noqa: BLE001 — private API across jax versions
            return len(self.signatures)
