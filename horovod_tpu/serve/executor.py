"""Sharded model executor: the single jitted entry point of the server.

Drives a decode-mode model (models/gpt.py or models/llama.py with
``cfg.decode=True``) at **fixed shapes**: every call is
``[max_batch, T]`` tokens with per-row positions, an update mask and a
per-row last-token index, where T is 1 (decode) or one of the configured
prefill buckets. Because batch membership is carried in *data* (mask,
positions) rather than *shape*, sequences can join and leave at
iteration granularity without ever invalidating the jit cache — the
no-recompile contract the continuous batcher (serve/batcher.py) is
built on.

Sharding rides the training stack unchanged: pass `mesh` plus the
model's `PartitionRules` (parallel/tp.py) and parameters are placed with
`shard_params`; jit/GSPMD then emits the same ICI collectives the
training step uses. The KV cache and token buffers default to
replicated, which is correct for TP (activations replicated, weights
sharded) — the Megatron serving layout.

Observability: each step lands on the timeline's **SERVE** row
(`timeline.instant("SERVE", {...})`) with step latency, step kind,
queue depth / batch occupancy / shed count (supplied by the batcher) and
a rolling tokens/s, next to the engine's WIRE_BYTES row in the same
trace.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics


class ShardedExecutor:
    """Owns the params, the device KV cache and the one jitted step."""

    def __init__(self, model: Any, params: Any, *, max_batch: int,
                 max_len: int, mesh=None, partition_rules=None,
                 timeline=None, replica_id: Optional[int] = None,
                 role: str = "target"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1; got {max_batch}")
        if role not in ("target", "draft"):
            raise ValueError(f"role must be 'target'|'draft'; got {role!r}")
        model_max = getattr(getattr(model, "cfg", None), "max_seq_len",
                            None)
        if model_max is not None and max_len > model_max:
            # the cache arrays are shaped by the model's max_seq_len; a
            # larger executor max_len would silently clamp cache writes
            # and position lookups instead of erroring
            raise ValueError(
                f"max_len {max_len} exceeds the model's max_seq_len "
                f"{model_max}")
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.timeline = timeline
        #: "draft" executors (speculative decoding proposers) share the
        #: process with a target executor: they must neither reclaim
        #: the serve metric families nor blend into the target's series
        self.role = role
        # -- paged layout (model-config driven): the device cache is a
        # block pool and every step takes per-row block tables
        cfg = getattr(model, "cfg", None)
        self.kv_block_size = int(getattr(cfg, "kv_block_size", 0) or 0)
        self.kv_pool_blocks = int(getattr(cfg, "kv_pool_blocks", 0) or 0)
        self.paged = self.kv_block_size > 0
        #: fixed block-table width: enough entries to address max_len
        self.blocks_per_seq = (
            -(-max_len // self.kv_block_size) if self.paged else 0)
        if self.paged and \
                self.kv_pool_blocks < self.blocks_per_seq:
            raise ValueError(
                f"kv_pool_blocks {self.kv_pool_blocks} cannot cover one "
                f"max_len sequence ({self.blocks_per_seq} blocks of "
                f"{self.kv_block_size})")
        # kept for hot weight swaps (redist/stream.py): replacement
        # params are placed exactly like the originals
        self._mesh = mesh
        self._rules = partition_rules
        if mesh is not None and partition_rules is not None:
            from ..parallel.tp import shard_params
            params = shard_params(params, mesh, partition_rules)
        self.params = params
        # the swap/version fence: step() holds this lock for the whole
        # forward, swap_params() takes it to replace self.params — a
        # swap can therefore land only BETWEEN decode iterations, never
        # mid-step, and no step ever mixes two param versions
        self._swap_lock = threading.Lock()
        self.params_version: Optional[int] = None
        self.swaps = 0
        # -- metrics --
        self.steps = 0
        self.tokens_out = 0
        self.step_latencies_ms: "deque[float]" = deque(maxlen=1024)
        self._tok_window: "deque[Tuple[float, int]]" = deque(maxlen=1024)
        #: distinct (kind, T) entry points actually executed — the
        #: jit-signature ledger the no-recompile tests assert on
        self.signatures: Set[Tuple[str, int]] = set()
        # registry series: per-kind step latency histogram + generated
        # tokens. Claimed fresh per executor when standalone (one
        # serving stack per process); a FLEET replica instead passes
        # replica_id and gets get-or-create labeled children, so one
        # replica's (re)construction never clobbers its siblings'
        # series and a restarted replica keeps counting where it left
        # off (serve/fleet.py).
        self.replica_id = replica_id
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        if role == "draft":
            rl = dict(rl, role="draft")
        R = obs_metrics.get_registry()
        if replica_id is None and role == "target":
            # only the TARGET standalone executor claims the families
            # fresh: a draft executor is constructed NEXT TO a target in
            # the same process and must not clobber its series
            R.unregister("hvd_serve_step_ms")
            R.unregister("hvd_serve_tokens_total")
        # get-or-create, NOT claimed fresh: a multi-replica fleet runs
        # several executors in one process and the swap series is
        # fleet-shared (redist/stream.py)
        self._m_swap_ms = R.histogram(
            "hvd_weight_swap_ms",
            "hot weight swap: new params placed + adopted (ms)")
        self._m_step_ms = {
            k: R.histogram("hvd_serve_step_ms",
                           "executor step latency by kind (ms)",
                           dict(rl, kind=k))
            for k in ("prefill", "decode", "verify")}
        self._m_tokens = R.counter(
            "hvd_serve_tokens_total", "tokens generated", rl or None)

        # the jitted step returns the greedy argmax at EVERY position
        # ([B, T] int32): prefill picks each row's last real token on
        # the host, decode reads column 0, and speculative VERIFY needs
        # the whole row (one batched step scores all k draft positions)
        if self.paged:
            def fwd(params, cache, tokens, positions, mask, tables):
                logits, vout = self.model.apply(
                    {"params": params, "cache": cache}, tokens,
                    positions=positions, update_mask=mask,
                    block_tables=tables, mutable=["cache"])
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, vout["cache"]
        else:
            def fwd(params, cache, tokens, positions, mask):
                logits, vout = self.model.apply(
                    {"params": params, "cache": cache}, tokens,
                    positions=positions, update_mask=mask,
                    mutable=["cache"])
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return nxt, vout["cache"]

        # donating the cache lets XLA update it in place on TPU; CPU
        # does not support donation and would only warn
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._fwd = jax.jit(fwd, donate_argnums=donate)

        # materialize the zero cache once (a separate cache-creating
        # trace; steady-state steps all go through self._fwd)
        def make_cache(params, tokens, positions, mask, tables):
            kw = {"block_tables": tables} if self.paged else {}
            _, v = self.model.apply(
                {"params": params}, tokens, positions=positions,
                update_mask=mask, mutable=["cache"], **kw)
            return v["cache"]

        z = jnp.zeros((max_batch, 1), jnp.int32)
        zt = jnp.full((max_batch, max(self.blocks_per_seq, 1)), -1,
                      jnp.int32)
        self.cache = jax.jit(make_cache, static_argnums=())(
            params, z, jnp.zeros((max_batch,), jnp.int32),
            jnp.zeros((max_batch,), bool), zt)

        if self.paged:
            # CoW block copy, jitted once (shapes are static): donation
            # makes it an in-place pool write on TPU instead of a full
            # pool copy per CoW
            NB, BS = self.kv_pool_blocks, self.kv_block_size

            def copy_block(cache, src, dst):
                def cp(leaf):
                    if getattr(leaf, "ndim", 0) == 4 and \
                            leaf.shape[0] == NB and leaf.shape[1] == BS:
                        return leaf.at[dst].set(leaf[src])
                    return leaf
                return jax.tree_util.tree_map(cp, cache)

            self._copy_block = jax.jit(
                copy_block, donate_argnums=() if
                jax.default_backend() == "cpu" else (0,))
        #: params_version the most recent step actually ran under (set
        #: inside the step lock) — what lets the batcher detect a swap
        #: landing between its prefix-cache lookup and the prefill
        self.last_step_version: Optional[int] = None

    # -- the one step --------------------------------------------------------
    def step(self, tokens: np.ndarray, positions: np.ndarray,
             mask: np.ndarray, last_idx: np.ndarray, *,
             kind: str = "decode",
             stats: Optional[Dict[str, Any]] = None,
             block_tables: Optional[np.ndarray] = None) -> np.ndarray:
        """Run one fixed-shape forward step; returns the sampled
        (greedy) next token per row, valid where `mask` is set —
        ``[max_batch]`` for prefill (each row's last real token) and
        decode (T=1), ``[max_batch, T]`` for ``kind="verify"`` (the
        speculative scoring step needs the argmax at every draft
        position).

        tokens [max_batch, T] int32; positions/last_idx [max_batch]
        int32; mask [max_batch] bool; block_tables
        [max_batch, blocks_per_seq] int32 (paged executors only).
        `stats` (queue depth, occupancy, shed count — batcher-supplied)
        is folded into the SERVE event.
        """
        t0 = time.perf_counter()
        self.signatures.add((kind, int(tokens.shape[1])))
        if self.paged:
            if block_tables is None:
                raise ValueError("a paged executor step needs "
                                 "block_tables")
            extra = (jnp.asarray(block_tables, jnp.int32),)
        else:
            extra = ()
        with self._swap_lock:   # the weight-swap version fence
            self.last_step_version = self.params_version
            nxt, self.cache = self._fwd(
                self.params, self.cache, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(mask, bool), *extra)
            # host readback doubles as completion fence — inside the
            # lock so a swap never lands while this step is in flight
            nxt = np.asarray(nxt)
        if kind == "prefill":
            nxt = nxt[np.arange(self.max_batch), np.asarray(last_idx)]
        elif kind != "verify":
            nxt = nxt[:, 0]
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self.steps += 1
        self.step_latencies_ms.append(dt_ms)
        self._m_step_ms.get(kind, self._m_step_ms["decode"]).observe(dt_ms)
        n_tok = int(np.sum(mask))
        self.tokens_out += n_tok
        self._m_tokens.inc(n_tok)
        self._tok_window.append((time.perf_counter(), n_tok))
        if self.timeline is not None:
            ev = {"kind": kind, "step_ms": round(dt_ms, 3),
                  "tokens": n_tok, "tokens_per_s": round(self.tokens_per_s(), 1)}
            if stats:
                ev.update(stats)
            self.timeline.instant("SERVE", ev)
        return nxt

    # -- hot weight swap (redist/stream.py consumer) -------------------------
    def swap_params(self, new_params: Any, *,
                    version: Optional[int] = None) -> bool:
        """Adopt ``new_params`` between decode iterations.

        The version fence: the step lock guarantees no swap lands while
        a forward is in flight (no torn step — every launched program
        sees exactly one param version), and adoption is MONOTONE —
        a ``version`` at or below the current one is refused (returns
        False) so out-of-order polls across replicas can never roll
        weights backwards. The structure must match the serving params
        exactly (same treedef/shapes); placement (mesh + partition
        rules) mirrors the constructor.

        Returns True on adoption; observes ``hvd_weight_swap_ms`` and
        emits a SWAP timeline instant."""
        import jax

        t0 = time.perf_counter()
        if version is not None and self.params_version is not None \
                and version <= self.params_version:
            return False
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new_params)
        if old_def != new_def or any(
                np.shape(a) != np.shape(b)
                # .dtype without np.asarray: materializing device
                # arrays to host just to read their dtype would cost an
                # O(model) transfer per swap (and raise on multi-host
                # GSPMD leaves)
                or getattr(a, "dtype", None) != getattr(b, "dtype",
                                                        None)
                for a, b in zip(old_leaves, new_leaves)):
            # dtype is part of the jitted step's signature: adopting
            # fp32 master weights into a bf16 executor would not error
            # — it would recompile EVERY bucket mid-traffic. Fail fast
            # instead; the publisher must cast to the serving dtype.
            raise ValueError(
                "swap_params: replacement tree does not match the "
                "serving params (treedef/shape/dtype mismatch) — "
                "refusing a structurally torn swap (a dtype change "
                "would recompile every serving bucket mid-traffic)")
        if self._mesh is not None and self._rules is not None:
            from ..parallel.tp import shard_params
            new_params = shard_params(new_params, self._mesh,
                                      self._rules)
        else:
            new_params = jax.tree_util.tree_map(jnp.asarray, new_params)
        with self._swap_lock:
            # re-check under the lock: another subscriber thread may
            # have adopted a newer version while we placed this one
            if version is not None and self.params_version is not None \
                    and version <= self.params_version:
                return False
            self.params = new_params
            self.params_version = version if version is not None else \
                (self.params_version or 0) + 1
            self.swaps += 1
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self._m_swap_ms.observe(dt_ms)
        if self.timeline is not None:
            self.timeline.instant("SWAP", {
                "version": self.params_version,
                "swap_ms": round(dt_ms, 3)})
        return True

    # -- KV integrity hooks (serve.kv chaos + crc option) --------------------
    def _cache_leaves(self) -> list:
        """The device KV arrays inside the flax cache collection, in
        flatten order: every ``[max_batch, L, H_kv, D]`` slotted leaf —
        or, for a paged executor, every ``[pool_blocks, block_size,
        H_kv, D]`` pool leaf — (cache_k and cache_v of each layer)."""
        leaves = jax.tree_util.tree_leaves(self.cache)
        if self.paged:
            return [l for l in leaves
                    if getattr(l, "ndim", 0) == 4
                    and l.shape[0] == self.kv_pool_blocks
                    and l.shape[1] == self.kv_block_size]
        return [l for l in leaves
                if getattr(l, "ndim", 0) == 4
                and l.shape[0] == self.max_batch]

    def kv_slot_bytes(self, slot: int, start: int,
                      stop: int) -> list:
        """Host bytes of positions ``[start, stop)`` of ``slot``'s row
        in each cache leaf (leaf order) — what the per-slot crc ledger
        (SlotKVCache.crc_update/crc_check) streams over. Decode reads
        one position; the verify-on-read pass re-reads the whole valid
        prefix once per retiring request."""
        return [np.asarray(l[slot, start:stop]).tobytes()
                for l in self._cache_leaves()]

    def kv_block_bytes(self, block: int, start: int,
                       stop: int) -> list:
        """Paged sibling of :meth:`kv_slot_bytes`: host bytes of
        positions ``[start, stop)`` of pool block ``block`` in each
        cache leaf — what the per-BLOCK crc ledger
        (BlockPool.crc_stream/crc_check) runs over."""
        return [np.asarray(l[block, start:stop]).tobytes()
                for l in self._cache_leaves()]

    def copy_kv_block(self, src: int, dst: int) -> None:
        """Device-side copy of pool block ``src`` onto ``dst`` in every
        cache leaf — the copy-on-write body behind partial prefix-block
        sharing (serve/prefix.py). One precompiled program; call once
        from warmup so the first divergent prompt never meets a
        compile."""
        if not self.paged:
            raise RuntimeError("copy_kv_block is paged-only")
        with self._swap_lock:   # never tear a step in flight
            self.cache = self._copy_block(
                self.cache, jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32))

    def corrupt_kv_slot(self, slot: int, length: int) -> None:
        """Flip one deterministically chosen bit inside ``slot``'s
        valid cache prefix — the chaos ``serve.kv`` fault body. Real
        device bytes change, so detection must come from the crc
        ledger, not from bookkeeping."""
        from ..chaos import inject as _chaos
        with self._swap_lock:   # never tear a step in flight
            leaves, treedef = jax.tree_util.tree_flatten(self.cache)
            idx = next(i for i, l in enumerate(leaves)
                       if getattr(l, "ndim", 0) == 4
                       and l.shape[0] == self.max_batch)
            row = np.array(leaves[idx][slot, :length])
            flipped = np.frombuffer(
                _chaos.corrupt_copy(row.tobytes()),
                dtype=row.dtype).reshape(row.shape)
            leaves[idx] = leaves[idx].at[slot, :length].set(
                jnp.asarray(flipped))
            self.cache = jax.tree_util.tree_unflatten(treedef, leaves)

    def corrupt_kv_block(self, block: int, length: int) -> None:
        """Paged ``serve.kv`` fault body: flip one bit inside the first
        ``length`` positions of pool block ``block`` — real device
        bytes, caught only by the per-block crc ledger."""
        from ..chaos import inject as _chaos
        if not self.paged:
            raise RuntimeError("corrupt_kv_block is paged-only")
        with self._swap_lock:
            leaves, treedef = jax.tree_util.tree_flatten(self.cache)
            idx = next(i for i, l in enumerate(leaves)
                       if getattr(l, "ndim", 0) == 4
                       and l.shape[0] == self.kv_pool_blocks
                       and l.shape[1] == self.kv_block_size)
            row = np.array(leaves[idx][block, :length])
            flipped = np.frombuffer(
                _chaos.corrupt_copy(row.tobytes()),
                dtype=row.dtype).reshape(row.shape)
            leaves[idx] = leaves[idx].at[block, :length].set(
                jnp.asarray(flipped))
            self.cache = jax.tree_util.tree_unflatten(treedef, leaves)

    # -- metrics -------------------------------------------------------------
    def tokens_per_s(self) -> float:
        """Rolling throughput over the retained step window."""
        if len(self._tok_window) < 2:
            return 0.0
        t_first = self._tok_window[0][0]
        t_last = self._tok_window[-1][0]
        if t_last <= t_first:
            return 0.0
        toks = sum(n for _, n in self._tok_window) - self._tok_window[0][1]
        return toks / (t_last - t_first)

    def p50_step_ms(self) -> Optional[float]:
        if not self.step_latencies_ms:
            return None
        return float(np.median(self.step_latencies_ms))

    def jit_cache_size(self) -> int:
        """Compiled-program count of the step function (falls back to
        the executed-signature count on jax versions without the
        introspection hook) — the churn tests assert this is flat."""
        try:
            return int(self._fwd._cache_size())
        except Exception:  # noqa: BLE001 — private API across jax versions
            return len(self.signatures)
