"""Radix prefix cache: shared system prompts are computed once.

A host-side radix tree over prompt TOKEN IDS at block granularity —
each node is one full KV block (``block_size`` consecutive tokens) plus
the pool index holding that block's K/V. A prefill whose prompt walks
down an existing path COPIES BLOCK REFERENCES instead of recomputing
attention: the matched run joins the new sequence's block table with a
refcount each (serve/kv_cache.py ``BlockPool``), and only the suffix
past the match is fed to the model. Correctness rests on causality —
a block's K/V depends only on the tokens at and before it, and both
model families cache position-absolute values (learned positions /
post-RoPE keys), so a shared block is valid verbatim for every sequence
sharing that token prefix.

Three policies the serving contract needs:

* **Copy-on-write at the divergence block.** When the match ends
  MID-block (the prompt diverges inside a cached block, or simply ends
  there), the partially matching block is CoW'd: a fresh block is
  allocated, the cached one is device-copied onto it
  (``executor.copy_kv_block``), and the sequence writes its divergent
  tokens into the copy. The cached original is never written by a
  non-owner — a refcount > 1 block is read-only by construction.
* **LRU eviction of refcount-zero runs.** The tree holds one refcount
  per node; a node whose block's ONLY reference is the tree itself
  (pool refcount == 1) is evictable, leaves first, least-recently
  matched first. `evictable_blocks()` feeds the paged admission gate,
  so cached-but-unreferenced runs count as free capacity.
* **Version fencing.** Cached K/V is only valid for the params that
  computed it: the batcher flushes this cache whenever
  ``swap_params`` adopts a new version (and the fleet router flushes a
  recovering replica before re-admission) — stale-weight KV can never
  serve a new model version (docs/serving.md).

Single-threaded by design: every method runs on the batcher's
scheduling thread, the same one-writer discipline as the block
allocator.
"""
from __future__ import annotations

import logging
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from .kv_cache import BlockPool

logger = logging.getLogger("horovod_tpu")


class _Node:
    __slots__ = ("tokens", "block", "children", "parent", "last_used")

    def __init__(self, tokens: Tuple[int, ...], block: int,
                 parent: Optional["_Node"]):
        self.tokens = tokens
        self.block = block
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.last_used = 0


class RadixPrefixCache:
    """Block-granularity radix tree over prompt token ids."""

    def __init__(self, pool: BlockPool,
                 replica_id: Optional[int] = None):
        self.pool = pool
        self.block_size = pool.block_size
        self._children: Dict[Tuple[int, ...], _Node] = {}   # root level
        self._nodes = 0
        self._tick = 0
        #: eviction hook (the KV tier's demotion trigger,
        #: serve/kvtier/): called with a structured event dict — run id,
        #: block index, block count (depth of the run), token length and
        #: the run's root->node token path — BEFORE the tree reference
        #: is dropped, so the subscriber can still read the block's
        #: device bytes and crc ledger. Runs on the scheduler thread
        #: (eviction is an admission-wave step); a raising hook is
        #: logged and dropped, never the scheduler's problem.
        self.on_evict: Optional[Callable[[dict], None]] = None
        # -- counters (obs): standalone stacks claim fresh, fleet
        # replicas get labeled children (the serve-wide discipline)
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        R = obs_metrics.get_registry()
        if replica_id is None:
            for fam in ("hvd_serve_prefix_hits_total",
                        "hvd_serve_prefix_misses_total",
                        "hvd_serve_prefix_tokens_saved_total",
                        "hvd_serve_prefix_evictions_total"):
                R.unregister(fam)
        self._m_hits = R.counter(
            "hvd_serve_prefix_hits_total",
            "prefills that reused at least one cached prefix block",
            rl or None)
        self._m_misses = R.counter(
            "hvd_serve_prefix_misses_total",
            "prefills that matched no cached prefix", rl or None)
        self._m_saved = R.counter(
            "hvd_serve_prefix_tokens_saved_total",
            "prompt tokens served from cached KV instead of recompute",
            rl or None)
        self._m_evict = R.counter(
            "hvd_serve_prefix_evictions_total",
            "prefix blocks evicted (LRU, refcount-zero runs)", rl or None)

    # -- introspection -------------------------------------------------------
    def __len__(self) -> int:
        return self._nodes

    @property
    def hits(self) -> int:
        return int(self._m_hits.value)

    @property
    def misses(self) -> int:
        return int(self._m_misses.value)

    @property
    def tokens_saved(self) -> int:
        return int(self._m_saved.value)

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_used = self._tick

    # -- capacity in TOKENS (the fleet index / autoscale definition) ---------
    def resident_tokens(self) -> int:
        """Prompt tokens whose KV is resident in the tree — every node
        is one full block, so this is nodes x block_size. The
        fleet-wide definition of cacheable capacity (``aggregate_
        healthz`` reports it per replica; docs/serving.md)."""
        return self._nodes * self.block_size

    def evictable_tokens(self) -> int:
        """Tokens releasable on demand (the token-granular view of
        :meth:`evictable_blocks` — same subtree walk, same refcount
        rule)."""
        return self.evictable_blocks() * self.block_size

    def run_tokens(self, node: _Node) -> Tuple[int, ...]:
        """The root->node token path — the run identity the eviction
        event and the fleet KV tier key on."""
        segs: List[Tuple[int, ...]] = []
        cur: Optional[_Node] = node
        while cur is not None:
            segs.append(cur.tokens)
            cur = cur.parent
        out: List[int] = []
        for seg in reversed(segs):
            out.extend(seg)
        return tuple(out)

    # -- lookup --------------------------------------------------------------
    def match(self, prompt) -> Tuple[List[int], Optional[Tuple[int, int]],
                                     int]:
        """Longest cached prefix of ``prompt``, capped at
        ``len(prompt) - 1`` tokens (at least one prompt token must be
        prefilled so the request has a last-logit to sample from).

        Returns ``(full_blocks, partial, matched_tokens)`` where
        ``full_blocks`` are pool indices whose refcount was BUMPED for
        the caller (they become the sequence's references), and
        ``partial`` is ``(block, tokens_matched_in_block)`` for a
        mid-block match — also bumped, but as a TEMPORARY pin the
        caller must drop after the copy-on-write copy (the pin
        guarantees eviction cannot free the source mid-wave).

        Hit/miss accounting is the caller's (`note_lookup`): a match
        whose admission falls through must not count as a hit.
        """
        bs = self.block_size
        cap = len(prompt) - 1
        full: List[int] = []
        children = self._children
        pos = 0
        node = None
        while pos + bs <= cap:
            seg = tuple(int(t) for t in prompt[pos:pos + bs])
            child = children.get(seg)
            if child is None:
                break
            self.pool.incref(child.block)
            self._touch(child)
            full.append(child.block)
            node = child
            children = child.children
            pos += bs
        # partial (copy-on-write) match inside the next block
        partial: Optional[Tuple[int, int]] = None
        want = [int(t) for t in prompt[pos:cap]]
        if want:
            best, best_j = None, 0
            for child in children.values():
                j = 0
                for a, b in zip(child.tokens, want):
                    if a != b:
                        break
                    j += 1
                if j > best_j:
                    best, best_j = child, j
            if best is not None:
                self.pool.incref(best.block)      # temp pin, see above
                self._touch(best)
                partial = (best.block, best_j)
                pos += best_j
        return full, partial, pos

    def note_lookup(self, matched_tokens: int) -> None:
        """Fold one ADMITTED prefill into the hit/miss/tokens-saved
        counters (docs/metrics.md)."""
        if matched_tokens > 0:
            self._m_hits.inc()
            self._m_saved.inc(matched_tokens)
        else:
            self._m_misses.inc()

    def release(self, blocks) -> None:
        """Drop references handed out by :meth:`match` (an admission
        that fell through, or the CoW temp pin after the copy)."""
        for blk in blocks:
            self.pool.decref(blk)

    # -- insertion -----------------------------------------------------------
    def insert(self, prompt, seq_blocks: List[int]) -> int:
        """Record ``prompt``'s full blocks (computed KV now resident in
        ``seq_blocks``, the sequence's table) into the tree; each newly
        created node takes its own refcount on the block. Existing
        nodes win (first writer of a prefix keeps it — contents are
        identical by construction). Returns nodes created."""
        bs = self.block_size
        children = self._children
        parent: Optional[_Node] = None
        created = 0
        pos = 0
        while pos + bs <= len(prompt) and (pos // bs) < len(seq_blocks):
            seg = tuple(int(t) for t in prompt[pos:pos + bs])
            child = children.get(seg)
            if child is None:
                blk = seq_blocks[pos // bs]
                self.pool.incref(blk)
                child = _Node(seg, blk, parent)
                children[seg] = child
                self._nodes += 1
                created += 1
            self._touch(child)
            parent = child
            children = child.children
            pos += bs
        return created

    def attach(self, tokens, block: int) -> bool:
        """Graft ONE block back onto the tree (the KV tier's promotion
        path, serve/kvtier/): ``tokens`` is the full root->node token
        path (a multiple of ``block_size``; the last ``block_size``
        tokens are the new node's segment) and ``block`` a pool index
        whose bytes already hold that segment's KV (installed through
        the verified path). Takes its OWN refcount on success — the
        caller keeps/releases whatever reference it held. Returns False
        without touching anything when the parent path is missing (the
        caller promotes shallower blocks first) or the node already
        exists (someone recomputed it; the existing node wins, exactly
        like :meth:`insert`)."""
        bs = self.block_size
        toks = tuple(int(t) for t in tokens)
        if not toks or len(toks) % bs != 0:
            return False
        children = self._children
        parent: Optional[_Node] = None
        for pos in range(0, len(toks) - bs, bs):
            parent = children.get(toks[pos:pos + bs])
            if parent is None:
                return False
            children = parent.children
        seg = toks[-bs:]
        if seg in children:
            return False
        self.pool.incref(block)
        node = _Node(seg, block, parent)
        children[seg] = node
        self._nodes += 1
        self._touch(node)
        return True

    # -- eviction ------------------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out: List[_Node] = []
        stack = list(self._children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evictable_blocks(self) -> int:
        """Blocks releasable on demand: nodes whose subtree holds no
        externally referenced block (pool refcount > 1 anywhere below
        pins the whole path — leaf-first eviction cannot reach it).
        Iterative post-order: the tree is a chain of prompt_len /
        block_size nodes per cached prompt, deep enough to blow the
        recursion limit on a long system prompt."""
        count = 0
        ok: Dict[int, bool] = {}            # id(node) -> subtree clear
        stack = [(n, False) for n in self._children.values()]
        while stack:
            node, visited = stack.pop()
            if not visited:
                stack.append((node, True))
                stack.extend((ch, False)
                             for ch in node.children.values())
                continue
            good = self.pool.refcount[node.block] == 1 and all(
                ok[id(ch)] for ch in node.children.values())
            ok[id(node)] = good
            if good:
                count += 1
        return count

    def evict(self, n_blocks: int) -> int:
        """Release at least ``n_blocks`` back to the pool if possible:
        LRU leaves first, cascading up as parents become leaves.
        Returns blocks actually freed."""
        freed = 0
        while freed < n_blocks:
            cands = [lf for lf in self._leaves()
                     if self.pool.refcount[lf.block] == 1]
            if not cands:
                break
            victim = min(cands, key=lambda lf: lf.last_used)
            hook = self.on_evict
            if hook is not None:
                # structured eviction event, emitted BEFORE the decref:
                # the run's block is still owned by the tree here, so a
                # demotion subscriber (serve/kvtier/) can read its
                # device bytes and crc ledger. "run" is a stable id of
                # the root->node token path; "blocks" its depth.
                tokens = self.run_tokens(victim)
                depth = len(tokens) // self.block_size
                ev = {"run": "%08x" % zlib.crc32(
                          b"".join(int(t).to_bytes(4, "little")
                                   for t in tokens)),
                      "tokens": tokens,
                      "block": victim.block,
                      "blocks": depth,
                      "token_len": len(tokens)}
                try:
                    hook(ev)
                except Exception as e:  # noqa: BLE001 — a demotion
                    # failure must degrade to plain eviction (the run
                    # re-prefills later), never kill the scheduler
                    logger.warning(
                        "prefix eviction hook failed (run dropped, "
                        "will re-prefill on next use): %s", e)
            self._remove(victim)
            freed += 1
            self._m_evict.inc()
        return freed

    def _remove(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._children)
        siblings.pop(node.tokens, None)
        self.pool.decref(node.block)
        self._nodes -= 1

    def flush(self) -> int:
        """Drop EVERY cached run (weight-swap invalidation): all tree
        references return to the pool; blocks still shared by live
        sequences survive under their owners' refcounts and die with
        them. Returns nodes dropped."""
        dropped = 0
        stack = list(self._children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.pool.decref(n.block)
            dropped += 1
        self._children = {}
        self._nodes = 0
        return dropped
