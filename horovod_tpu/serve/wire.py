"""Framed JSON wire protocol for the multi-process serve fleet.

The dispatch channel between a :class:`~horovod_tpu.serve.proc_fleet.
ProcessFleetRouter` and its replica worker processes
(serve/worker.py): length-prefixed JSON frames over TCP, small enough
to audit and stdlib-only, because the payloads are token id lists and
counters — the heavy bytes (weights, KV) ride the redist planes.

Failure classification is the whole point of this module existing
separately: every socket fault crossing these helpers is routed
through ``native/resilience.is_retryable`` and re-raised as
:class:`DispatchConnError` — a ``Retryable`` — when it is a
connection-class blip (reset, refused dial, EOF mid-frame), so the
router's retry ladder absorbs it in milliseconds; timeouts and
protocol garbage stay fatal and escalate exactly like every other
wire plane (docs/chaos.md).

Frame: 4-byte big-endian length + UTF-8 JSON object. One request per
connection for the submit path (the reply can be seconds away — a
generation — and a one-shot socket keeps replay-after-reconnect
trivially safe: the worker dedupes on the request ``fid``, mirroring
the csrc/store.cc nonce pattern).
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

from ..native import resilience

#: a healthz/ack reply must fit here; submit replies carry at most
#: max_new_tokens ints — far below this
MAX_FRAME_BYTES = 4 << 20


class DispatchConnError(RuntimeError, resilience.Retryable):
    """The dispatch TRANSPORT failed (reset, refused dial, EOF
    mid-frame) — the request may never have arrived, or its reply may
    be lost. Retryable: replaying the dispatch is safe because the
    worker dedupes on the request id (serve/worker.py) and serves a
    replayed request its cached (or still-in-flight) result."""


class DispatchError(RuntimeError):
    """A NON-retryable dispatch failure: protocol garbage, an oversized
    frame, a stall past the reply timeout. Escalates to failover."""


def _classify(e: OSError, what: str) -> Exception:
    # route through the resilience classifier: connection-class blips
    # become the Retryable DispatchConnError the ladder absorbs;
    # timeouts and the rest stay fatal (the stall bound elapsed)
    if resilience.is_retryable(e):
        return DispatchConnError(f"{what}: {e}")
    if isinstance(e, socket.timeout):
        return DispatchError(f"{what}: timed out ({e})")
    return e


def connect(addr: Tuple[str, int], timeout: float) -> socket.socket:
    """Dial a replica endpoint; refused/reset dials raise the
    Retryable :class:`DispatchConnError` (the ladder re-dials)."""
    try:
        s = socket.create_connection(addr, timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s
    except OSError as e:
        # resilience classifier decides retryable vs fatal
        raise _classify(e, f"dial {addr[0]}:{addr[1]}") from None


def send_msg(sock: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj).encode()
    if len(raw) > MAX_FRAME_BYTES:
        raise DispatchError(
            f"frame of {len(raw)} bytes exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES})")
    try:
        sock.sendall(struct.pack(">I", len(raw)) + raw)
    except OSError as e:
        # resilience classifier decides retryable vs fatal
        raise _classify(e, "send") from None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            got = sock.recv(n - len(buf))
        except OSError as e:
            # resilience classifier decides retryable vs fatal
            raise _classify(e, "recv") from None
        if not got:
            raise DispatchConnError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += got
    return bytes(buf)


def recv_msg(sock: socket.socket,
             timeout: Optional[float] = None) -> dict:
    """Read one frame; EOF/reset raise the Retryable
    :class:`DispatchConnError`, a timeout raises the fatal
    :class:`DispatchError` (the reply bound elapsed — retrying would
    mask a stalled replica the router should fail over instead)."""
    if timeout is not None:
        sock.settimeout(timeout)
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > MAX_FRAME_BYTES:
        raise DispatchError(
            f"peer announced a {n}-byte frame (> {MAX_FRAME_BYTES}) — "
            f"protocol garbage, not retryable")
    raw = _recv_exact(sock, n)
    try:
        obj = json.loads(raw.decode())
    except ValueError as e:
        raise DispatchError(f"undecodable frame: {e}") from None
    if not isinstance(obj, dict):
        raise DispatchError(
            f"frame must be a JSON object; got {type(obj).__name__}")
    return obj
