"""Framed wire protocol for the multi-process serve fleet.

The dispatch channel between a :class:`~horovod_tpu.serve.proc_fleet.
ProcessFleetRouter` and its replica worker processes
(serve/worker.py): length-prefixed JSON frames over TCP, small enough
to audit and stdlib-only, because the payloads are token id lists and
counters — the heavy bytes (weights, KV) ride the redist planes.

Failure classification is the whole point of this module existing
separately: every socket fault crossing these helpers is routed
through ``native/resilience.is_retryable`` and re-raised as
:class:`DispatchConnError` — a ``Retryable`` — when it is a
connection-class blip (reset, refused dial, EOF mid-frame), so the
router's retry ladder absorbs it in milliseconds; timeouts and
protocol garbage stay fatal and escalate exactly like every other
wire plane (docs/chaos.md).

Two frame types share one length-prefixed framing:

* **JSON frame**: 4-byte big-endian length + UTF-8 JSON object. One
  request per connection for the submit path (the reply can be seconds
  away — a generation — and a one-shot socket keeps
  replay-after-reconnect trivially safe: the worker dedupes on the
  request ``fid``, mirroring the csrc/store.cc nonce pattern).
* **BINARY frame** (KV-block migration, serve/kv_migrate.py): the high
  bit of the length word marks a frame carrying a JSON header PLUS a
  raw byte payload — ``[len|BIN][4B header len][header JSON][payload]``
  — so migrated KV blocks ride the wire as bytes with a crc32 in the
  header (the redist framing discipline), never base64 inside JSON.

The frame ceiling is the declared knob ``HOROVOD_SERVE_WIRE_MAX_FRAME``
(docs/knobs.md): dispatch frames never approach it, but migration
frames carry whole sequences' KV blocks and deployments with big
models/pools raise it. Resolved once per process through
``core/config.py`` (strict parse, validated range).
"""
from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Optional, Tuple

from ..native import resilience

#: default frame ceiling (bytes) — the HOROVOD_SERVE_WIRE_MAX_FRAME
#: knob's default, kept importable for back-compat and the config
#: dataclass default (core/config.py serve_wire_max_frame)
MAX_FRAME_BYTES = 4 << 20

#: high bit of the length word: this frame is binary (header + payload)
_BIN_FLAG = 0x80000000

_max_frame_cached: Optional[int] = None


def max_frame_bytes() -> int:
    """The live frame ceiling: ``HOROVOD_SERVE_WIRE_MAX_FRAME``
    strict-parsed through ``Config.from_env`` once per process (every
    endpoint and router shares one resolution; a malformed value fails
    the first wire call loudly instead of silently shrinking frames)."""
    global _max_frame_cached
    if _max_frame_cached is None:
        from ..core.config import Config
        _max_frame_cached = int(Config.from_env().serve_wire_max_frame)
    return _max_frame_cached


def _reset_max_frame_cache() -> None:
    """Test hook: re-resolve the ceiling from the environment."""
    global _max_frame_cached
    _max_frame_cached = None


class DispatchConnError(RuntimeError, resilience.Retryable):
    """The dispatch TRANSPORT failed (reset, refused dial, EOF
    mid-frame) — the request may never have arrived, or its reply may
    be lost. Retryable: replaying the dispatch is safe because the
    worker dedupes on the request id (serve/worker.py) and serves a
    replayed request its cached (or still-in-flight) result."""


class DispatchError(RuntimeError):
    """A NON-retryable dispatch failure: protocol garbage, an oversized
    frame, a stall past the reply timeout. Escalates to failover."""


def _classify(e: OSError, what: str) -> Exception:
    # route through the resilience classifier: connection-class blips
    # become the Retryable DispatchConnError the ladder absorbs;
    # timeouts and the rest stay fatal (the stall bound elapsed)
    if resilience.is_retryable(e):
        return DispatchConnError(f"{what}: {e}")
    if isinstance(e, socket.timeout):
        return DispatchError(f"{what}: timed out ({e})")
    return e


def connect(addr: Tuple[str, int], timeout: float) -> socket.socket:
    """Dial a replica endpoint; refused/reset dials raise the
    Retryable :class:`DispatchConnError` (the ladder re-dials)."""
    try:
        s = socket.create_connection(addr, timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s
    except OSError as e:
        # resilience classifier decides retryable vs fatal
        raise _classify(e, f"dial {addr[0]}:{addr[1]}") from None


def send_msg(sock: socket.socket, obj: dict) -> None:
    raw = json.dumps(obj).encode()
    limit = max_frame_bytes()
    if len(raw) > limit:
        raise DispatchError(
            f"frame of {len(raw)} bytes exceeds "
            f"HOROVOD_SERVE_WIRE_MAX_FRAME ({limit})")
    try:
        sock.sendall(struct.pack(">I", len(raw)) + raw)
    except OSError as e:
        # resilience classifier decides retryable vs fatal
        raise _classify(e, "send") from None


def send_bin(sock: socket.socket, obj: dict, payload: bytes) -> None:
    """Send a BINARY frame: JSON header ``obj`` plus raw ``payload``
    bytes. The header should carry a crc32 of the payload (the
    migration layer stamps ``payload_crc``); :func:`recv_any` verifies
    it on the far side so in-flight corruption is caught at the frame
    boundary, same discipline as redist/transport.py."""
    head = json.dumps(obj).encode()
    total = 4 + len(head) + len(payload)
    limit = max_frame_bytes()
    if total > limit:
        raise DispatchError(
            f"binary frame of {total} bytes exceeds "
            f"HOROVOD_SERVE_WIRE_MAX_FRAME ({limit}) — raise the knob "
            f"for KV-migration payloads this large")
    try:
        sock.sendall(struct.pack(">II", total | _BIN_FLAG, len(head))
                     + head + payload)
    except OSError as e:
        # resilience classifier decides retryable vs fatal
        raise _classify(e, "send") from None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            got = sock.recv(min(n - len(buf), 1 << 20))
        except OSError as e:
            # resilience classifier decides retryable vs fatal
            raise _classify(e, "recv") from None
        if not got:
            raise DispatchConnError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf += got
    return bytes(buf)


def recv_any(sock: socket.socket,
             timeout: Optional[float] = None
             ) -> Tuple[dict, Optional[bytes]]:
    """Read one frame of either type; returns ``(obj, payload)`` where
    ``payload`` is None for plain JSON frames. EOF/reset raise the
    Retryable :class:`DispatchConnError`, a timeout raises the fatal
    :class:`DispatchError` (the reply bound elapsed — retrying would
    mask a stalled replica the router should fail over instead). A
    binary frame whose ``payload_crc`` header does not match the
    received bytes raises :class:`DispatchError` — corruption on this
    wire is NOT retryable blindly; the migration layer re-packs from
    the source ledger instead."""
    if timeout is not None:
        sock.settimeout(timeout)
    (word,) = struct.unpack(">I", _recv_exact(sock, 4))
    is_bin = bool(word & _BIN_FLAG)
    n = word & ~_BIN_FLAG
    limit = max_frame_bytes()
    if n > limit:
        raise DispatchError(
            f"peer announced a {n}-byte frame "
            f"(> HOROVOD_SERVE_WIRE_MAX_FRAME {limit}) — protocol "
            f"garbage, not retryable")
    if not is_bin:
        raw = _recv_exact(sock, n)
        return _decode_obj(raw), None
    if n < 4:
        raise DispatchError(
            f"binary frame of {n} bytes cannot hold its header length")
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if hlen > n - 4:
        raise DispatchError(
            f"binary frame header length {hlen} exceeds the frame "
            f"({n} bytes)")
    obj = _decode_obj(_recv_exact(sock, hlen))
    payload = _recv_exact(sock, n - 4 - hlen)
    want = obj.get("payload_crc")
    if want is not None and zlib.crc32(payload) != int(want):
        raise DispatchError(
            f"binary frame payload failed crc32 "
            f"({zlib.crc32(payload)} != {want}) — corrupted in flight")
    return obj, payload


def _decode_obj(raw: bytes) -> dict:
    try:
        obj = json.loads(raw.decode())
    except ValueError as e:
        raise DispatchError(f"undecodable frame: {e}") from None
    if not isinstance(obj, dict):
        raise DispatchError(
            f"frame must be a JSON object; got {type(obj).__name__}")
    return obj


def recv_msg(sock: socket.socket,
             timeout: Optional[float] = None) -> dict:
    """Read one frame and return its JSON object (a binary frame's
    payload is dropped — callers that expect KV bytes use
    :func:`recv_any`)."""
    obj, _ = recv_any(sock, timeout)
    return obj


def two_frame_request(addr: Tuple[str, int], msg: dict, *,
                      connect_timeout: float = 2.0,
                      ack_timeout: float = 10.0,
                      reply_timeout: float = 30.0,
                      on_ack=None) -> Tuple[str, dict]:
    """THE dispatch exchange every router leg speaks: dial, send one
    request frame, read the control ack, then block for the (possibly
    seconds-away) reply. Returns ``("ctrl", ack)`` when the peer's
    door answered anything but ``accepted``, else ``("ok", reply)``.
    One shared shape so the submit / result / requeue legs cannot
    drift on timeouts or the ack contract."""
    sock = connect(addr, timeout=connect_timeout)
    try:
        send_msg(sock, msg)
        ack = recv_msg(sock, timeout=ack_timeout)
        if ack.get("ack") != "accepted":
            return ("ctrl", ack)
        if on_ack is not None:
            on_ack()    # the dispatch-leg latency hook
        return ("ok", recv_msg(sock, timeout=reply_timeout))
    finally:
        sock.close()
