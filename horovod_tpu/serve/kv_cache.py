"""Slotted KV-cache: the serving-side memory manager.

Orca/vLLM-style continuous batching needs per-sequence key/value state
that outlives any single forward call and can be handed to a *different*
sequence the moment its owner retires. Two halves live here:

1. **Functional cache math** (`write_kv`, `cached_attention`): pure
   jittable updates of the device-resident cache arrays. The cache
   layout is ``[num_slots, max_len, num_kv_heads, head_dim]`` — one row
   ("slot") per in-flight sequence, written in place at per-row offsets
   with a vmapped dynamic_update_slice and read back under a per-row
   validity mask. Shapes never depend on which slots are live, so jit
   compiles the decode program exactly once (the no-recompile contract,
   docs/serving.md).
2. **Host-side slot accounting** (`SlotKVCache`): a free list with
   per-slot lengths, occupancy and reuse counters. Slots are recycled
   LIFO; stale bytes from the previous owner are never cleared — the
   validity mask (`key position <= row position`) makes them
   unreachable, which is what makes reuse O(1).

The device arrays themselves live in the model's flax ``"cache"``
collection (models/gpt.py, models/llama.py decode paths) and are
threaded through the executor (serve/executor.py); this module holds no
jax arrays of its own.
"""
from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

#: additive mask for invalid key positions — large-negative rather than
#: -inf so fully-masked garbage rows (inactive slots) still softmax to
#: finite numbers instead of NaN
_MASK_VALUE = -1e30


def write_kv(cache_k: jax.Array, cache_v: jax.Array, k_new: jax.Array,
             v_new: jax.Array, positions: jax.Array,
             update_mask: jax.Array):
    """Write `T` new K/V vectors per row at that row's offset.

    cache_k/cache_v: [B, max_len, H_kv, D]; k_new/v_new: [B, T, H_kv, D];
    positions: [B] int32 write offsets; update_mask: [B] bool — rows with
    False keep their cache untouched (slots owned by OTHER sequences
    during a prefill of newly admitted ones, or free slots).
    Returns the updated (cache_k, cache_v).
    """
    def upd(c, u, p):
        return jax.lax.dynamic_update_slice(c, u.astype(c.dtype), (p, 0, 0))

    nk = jax.vmap(upd)(cache_k, k_new, positions)
    nv = jax.vmap(upd)(cache_v, v_new, positions)
    m = update_mask[:, None, None, None]
    return jnp.where(m, nk, cache_k), jnp.where(m, nv, cache_v)


def cached_attention(q: jax.Array, cache_k: jax.Array, cache_v: jax.Array,
                     positions: jax.Array) -> jax.Array:
    """Causal attention of `T` query tokens over each row's cache prefix.

    q: [B, T, H, D]; cache_k/cache_v: [B, max_len, H_kv, D] (GQA: kv
    heads are broadcast locally, H % H_kv == 0); positions: [B] — query
    token t of row i sits at absolute position positions[i] + t and may
    attend cache entries [0, positions[i] + t]. Call AFTER write_kv so a
    token attends to itself. Softmax runs in f32 with a large-negative
    additive mask; stale bytes past the valid prefix (slot-reuse
    leftovers) are unreachable by construction.
    """
    B, T, H, D = q.shape
    L, KV = cache_k.shape[1], cache_k.shape[2]
    if KV != H:
        cache_k = jnp.repeat(cache_k, H // KV, axis=2)
        cache_v = jnp.repeat(cache_v, H // KV, axis=2)
    qf = q.astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    vf = cache_v.astype(jnp.float32)
    scores = jnp.einsum("bthd,bjhd->bhtj", qf, kf) / np.sqrt(D)
    valid = jnp.arange(L)[None, None, None, :] <= (
        positions[:, None, None, None] + jnp.arange(T)[None, None, :, None])
    scores = jnp.where(valid, scores, _MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhtj,bjhd->bthd", probs, vf)
    return out.astype(q.dtype)


class SlotKVCache:
    """Host-side slot manager: free list + per-slot length accounting.

    One instance per batcher; `num_slots` equals the executor's fixed
    decode batch (HOROVOD_SERVE_MAX_BATCH). Occupancy / reuse counters
    feed the SERVE timeline row and the /healthz payload.
    """

    def __init__(self, num_slots: int, max_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1; got {num_slots}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1; got {max_len}")
        self.num_slots = num_slots
        self.max_len = max_len
        # LIFO reuse: the most recently freed slot is re-issued first,
        # keeping the hot rows hot
        self._free: List[int] = list(range(num_slots))[::-1]
        #: tokens written into each slot's cache row (the valid prefix)
        self.lengths = np.zeros(num_slots, dtype=np.int32)
        self.active = np.zeros(num_slots, dtype=bool)
        #: times each slot has been (re)allocated — the reuse ledger
        self.generation = np.zeros(num_slots, dtype=np.int64)
        self.allocs = 0
        self.frees = 0
        self.peak_live = 0
        #: per-slot streamed crc32 of the cache bytes written so far,
        #: one running value PER CACHE LEAF (k/v x layer — write order
        #: within one leaf is positional, so streaming holds per leaf
        #: but not across leaves). Populated only when the batcher runs
        #: with kv_crc enabled; the chaos serve.kv corrupt fault is
        #: what this must catch (docs/serving.md).
        self._crc: Dict[int, List[int]] = {}

    # -- per-slot integrity (crc-on-write / verify-on-read option) ----------
    def crc_update(self, slot: int, leaf_bytes: Sequence[bytes]) -> None:
        """Fold the bytes just written to ``slot`` (one entry per cache
        leaf, in leaf order) into the slot's running crc32s."""
        cur = self._crc.get(slot)
        if cur is None:
            cur = self._crc[slot] = [0] * len(leaf_bytes)
        for i, raw in enumerate(leaf_bytes):
            cur[i] = zlib.crc32(raw, cur[i])

    def crc_check(self, slot: int, leaf_bytes: Sequence[bytes]) -> bool:
        """Verify a full re-read of ``slot``'s valid prefix (one entry
        per cache leaf) against the streamed write-side crc32s. True
        when every leaf matches; a slot never written checks clean."""
        cur = self._crc.get(slot)
        if cur is None:
            return True
        return len(cur) == len(leaf_bytes) and all(
            zlib.crc32(raw) == c for raw, c in zip(leaf_bytes, cur))

    def alloc(self) -> Optional[int]:
        """Claim a free slot (None when all are live). The new owner's
        length starts at 0; stale cache bytes need no clearing (masked
        out by `cached_attention`)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.active[slot] = True
        self.lengths[slot] = 0
        self.generation[slot] += 1
        self.allocs += 1
        self._crc.pop(slot, None)   # the new owner's ledger starts empty
        self.peak_live = max(self.peak_live, self.live())
        return slot

    def free(self, slot: int) -> None:
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not live")
        self.active[slot] = False
        self.lengths[slot] = 0
        self._free.append(slot)
        self.frees += 1

    def live(self) -> int:
        return self.num_slots - len(self._free)

    def occupancy(self) -> float:
        """Live slots / total slots — the batch-occupancy counter."""
        return self.live() / self.num_slots
