"""KV-cache memory managers: slotted rows and paged blocks.

Orca/vLLM-style continuous batching needs per-sequence key/value state
that outlives any single forward call and can be handed to a *different*
sequence the moment its owner retires. Two storage layouts live here:

1. **Slotted** (`write_kv`, `cached_attention`, `SlotKVCache`): the
   original layout — one ``[num_slots, max_len, H_kv, D]`` row per
   in-flight sequence, written in place at per-row offsets and read
   back under a per-row validity mask. Simple, but occupancy is
   ``slots x max_len`` regardless of how many tokens are resident.
2. **Paged** (`write_kv_paged`, `paged_attention`, `BlockPool`,
   `PagedKVCache`): vLLM-style block storage — the device arrays are a
   pool ``[num_blocks, block_size, H_kv, D]`` and each sequence owns an
   ordered *block table* of pool indices. Virtual position ``p`` of a
   sequence lives at ``pool[table[p // bs], p % bs]``; attention
   gathers the table and applies the same positional validity mask, so
   occupancy is bounded by **tokens resident** (blocks actually
   allocated), not ``slots x max_len``. Blocks are refcounted, which is
   what lets the radix prefix cache (serve/prefix.py) share read-only
   prompt-prefix runs across sequences.

Shapes never depend on which rows/blocks are live — liveness is data
(masks, tables, positions), so jit compiles each program exactly once
(the no-recompile contract, docs/serving.md).

The device arrays themselves live in the model's flax ``"cache"``
collection (models/gpt.py, models/llama.py decode paths) and are
threaded through the executor (serve/executor.py); this module holds no
jax arrays of its own.
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: additive mask for invalid key positions — large-negative rather than
#: -inf so fully-masked garbage rows (inactive slots) still softmax to
#: finite numbers instead of NaN
_MASK_VALUE = -1e30


def write_kv(cache_k: jax.Array, cache_v: jax.Array, k_new: jax.Array,
             v_new: jax.Array, positions: jax.Array,
             update_mask: jax.Array):
    """Write `T` new K/V vectors per row at that row's offset.

    cache_k/cache_v: [B, max_len, H_kv, D]; k_new/v_new: [B, T, H_kv, D];
    positions: [B] int32 write offsets; update_mask: [B] bool — rows with
    False keep their cache untouched (slots owned by OTHER sequences
    during a prefill of newly admitted ones, or free slots).
    Returns the updated (cache_k, cache_v).
    """
    def upd(c, u, p):
        return jax.lax.dynamic_update_slice(c, u.astype(c.dtype), (p, 0, 0))

    nk = jax.vmap(upd)(cache_k, k_new, positions)
    nv = jax.vmap(upd)(cache_v, v_new, positions)
    m = update_mask[:, None, None, None]
    return jnp.where(m, nk, cache_k), jnp.where(m, nv, cache_v)


def cached_attention(q: jax.Array, cache_k: jax.Array, cache_v: jax.Array,
                     positions: jax.Array) -> jax.Array:
    """Causal attention of `T` query tokens over each row's cache prefix.

    q: [B, T, H, D]; cache_k/cache_v: [B, max_len, H_kv, D] (GQA: kv
    heads are broadcast locally, H % H_kv == 0); positions: [B] — query
    token t of row i sits at absolute position positions[i] + t and may
    attend cache entries [0, positions[i] + t]. Call AFTER write_kv so a
    token attends to itself. Softmax runs in f32 with a large-negative
    additive mask; stale bytes past the valid prefix (slot-reuse
    leftovers) are unreachable by construction.
    """
    return masked_attention(q, cache_k, cache_v, positions)


def masked_attention(q: jax.Array, keys: jax.Array, vals: jax.Array,
                     positions: jax.Array) -> jax.Array:
    """THE masked-attention contract — the single reference
    implementation shared by every decode read in the tree.

    Causal attention of `T` query tokens over each row's
    ``[B, L, H_kv, D]`` key/value view, valid positions
    ``[0, positions[b] + t]`` only; f32 score math, divide-after-dot
    ``1/sqrt(D)`` scaling, large-negative additive masking, output cast
    back to ``q.dtype``. `cached_attention` (slotted), `paged_attention`
    (the gathered-pool XLA path) and the models' decode attention all
    delegate here, and the fused Pallas kernels
    (ops/pallas_paged.py) mirror this math operation-for-operation —
    it is the bit-exactness ORACLE the interpret-mode parity suite
    asserts against (tests/test_serve_kernels.py).

    The GQA group is folded into the matmul M dimension
    (``[T * G, D] x [L, D]`` per (row, kv head), exactly the kernel's
    slice shapes) rather than repeating K/V to H heads: batched
    `dot_general` over (B, KV) and the kernel's per-program dot then
    hit the same XLA gemm micro-kernels, which is what makes bit-match
    achievable at all (micro-kernel choice is shape-dependent).
    """
    B, T, H, D = q.shape
    L, KV = keys.shape[1], keys.shape[2]
    G = H // KV
    # [B, T, H, D] -> [B, KV, T*G, D]; row order t*G + g matches the
    # kernel's [T, G, D] block flattening
    qf = q.astype(jnp.float32).reshape(B, T, KV, G, D).transpose(
        0, 2, 1, 3, 4).reshape(B, KV, T * G, D)
    kf = keys.astype(jnp.float32).transpose(0, 2, 1, 3)   # [B, KV, L, D]
    vf = vals.astype(jnp.float32).transpose(0, 2, 1, 3)
    scores = jax.lax.dot_general(
        qf, kf, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) / np.sqrt(D)  # [B, KV, TG, L]
    t_of = jnp.arange(T * G) // G
    valid = jnp.arange(L)[None, None, None, :] <= (
        positions[:, None, None, None] + t_of[None, None, :, None])
    scores = jnp.where(valid, scores, _MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jax.lax.dot_general(
        probs, vf, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32)               # [B, KV, TG, D]
    out = out.reshape(B, KV, T, G, D).transpose(
        0, 2, 1, 3, 4).reshape(B, T, H, D)
    return out.astype(q.dtype)


#: back-compat alias (pre-PR-12 private name)
_masked_attention = masked_attention


# -- paged (block) storage ---------------------------------------------------

def write_kv_paged(pool_k: jax.Array, pool_v: jax.Array, k_new: jax.Array,
                   v_new: jax.Array, positions: jax.Array,
                   update_mask: jax.Array, block_tables: jax.Array):
    """Scatter `T` new K/V vectors per row into the block pool.

    pool_k/pool_v: [num_blocks, block_size, H_kv, D]; k_new/v_new:
    [B, T, H_kv, D]; positions: [B] int32 — row b's token t lands at
    virtual position positions[b] + t, i.e. pool slot
    ``(block_tables[b, p // bs], p % bs)``; block_tables:
    [B, blocks_per_seq] int32, -1 for unassigned entries. Writes whose
    row mask is False, whose virtual position runs past the table, or
    whose table entry is -1 are DROPPED (never land anywhere) — the
    paged analog of the slotted update_mask discipline, which is what
    keeps bucket-padding garbage out of other sequences' blocks.
    Returns the updated (pool_k, pool_v).
    """
    NB, BS = pool_k.shape[0], pool_k.shape[1]
    B, T = k_new.shape[0], k_new.shape[1]
    nblk = block_tables.shape[1]
    abs_pos = positions[:, None] + jnp.arange(T, dtype=positions.dtype)[None]
    blk_idx = abs_pos // BS                                   # [B, T]
    off = abs_pos % BS
    safe_idx = jnp.clip(blk_idx, 0, nblk - 1)
    blocks = jnp.take_along_axis(block_tables, safe_idx, axis=1)  # [B, T]
    valid = (update_mask[:, None] & (blk_idx < nblk) & (blocks >= 0))
    flat = blocks * BS + off
    # invalid writes get an out-of-range index and mode="drop" discards
    # them at the scatter (deterministic on every backend)
    flat = jnp.where(valid, flat, NB * BS).reshape(-1)

    def scatter(pool, new):
        out = pool.reshape(NB * BS, *pool.shape[2:]).at[flat].set(
            new.reshape(B * T, *new.shape[2:]).astype(pool.dtype),
            mode="drop")
        return out.reshape(pool.shape)

    return scatter(pool_k, k_new), scatter(pool_v, v_new)


def paged_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                    block_tables: jax.Array,
                    positions: jax.Array) -> jax.Array:
    """Block-table-aware masked attention over the pooled cache.

    Gathers each row's blocks into a contiguous
    ``[B, blocks_per_seq * block_size, H_kv, D]`` view and applies the
    same positional validity mask as the slotted read. Unassigned table
    entries (-1) are sanitized to block 0; whatever they gather is
    unreachable — a sequence's valid prefix never extends past its
    assigned blocks.
    """
    NB, BS = pool_k.shape[0], pool_k.shape[1]
    B, nblk = block_tables.shape
    tbl = jnp.maximum(block_tables, 0)
    keys = pool_k[tbl].reshape(B, nblk * BS, *pool_k.shape[2:])
    vals = pool_v[tbl].reshape(B, nblk * BS, *pool_v.shape[2:])
    return masked_attention(q, keys, vals, positions)


def pool_blocks_for(max_batch: int, max_len: int, block_size: int,
                    fraction: float = 0.5) -> int:
    """A sane device pool size: ``fraction`` of the slotted layout's
    ``max_batch x max_len`` worst case (the whole point of paging is to
    provision for tokens actually resident), floored so every row can
    hold at least one block plus headroom for a shared prefix run."""
    worst = max_batch * -(-max_len // block_size)
    want = int(worst * fraction)
    return max(want, 2 * max_batch, -(-max_len // block_size) + max_batch)


def paged_model_kwargs(max_batch: int, max_len: int, *, config=None,
                       fraction: float = 0.5) -> dict:
    """The HOROVOD_SERVE_KV_BLOCK knob's one consumer: model-config
    kwargs for the serving layout the environment asks for — ``{}``
    when the knob is 0 (slotted), else ``kv_block_size`` plus a
    :func:`pool_blocks_for`-provisioned ``kv_pool_blocks``. The model
    config stays authoritative (the pool shape is static and compiles
    into every serving program); this is the one place the env knob
    becomes device-array shapes::

        cfg = GPTConfig(decode=True, **kw,
                        **paged_model_kwargs(max_batch, max_len))
    """
    if config is None:
        from ..core.config import Config
        config = Config.from_env()
    bs = int(config.serve_kv_block)
    if bs <= 0:
        return {}
    return {"kv_block_size": bs,
            "kv_pool_blocks": pool_blocks_for(max_batch, max_len, bs,
                                              fraction)}


class SlotKVCache:
    """Host-side slot manager: free list + per-slot length accounting.

    One instance per batcher; `num_slots` equals the executor's fixed
    decode batch (HOROVOD_SERVE_MAX_BATCH). Occupancy / reuse counters
    feed the SERVE timeline row and the /healthz payload.
    """

    def __init__(self, num_slots: int, max_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1; got {num_slots}")
        if max_len < 1:
            raise ValueError(f"max_len must be >= 1; got {max_len}")
        self.num_slots = num_slots
        self.max_len = max_len
        # LIFO reuse: the most recently freed slot is re-issued first,
        # keeping the hot rows hot
        self._free: List[int] = list(range(num_slots))[::-1]
        #: tokens written into each slot's cache row (the valid prefix)
        self.lengths = np.zeros(num_slots, dtype=np.int32)
        self.active = np.zeros(num_slots, dtype=bool)
        #: times each slot has been (re)allocated — the reuse ledger
        self.generation = np.zeros(num_slots, dtype=np.int64)
        self.allocs = 0
        self.frees = 0
        self.peak_live = 0
        #: per-slot streamed crc32 of the cache bytes written so far,
        #: one running value PER CACHE LEAF (k/v x layer — write order
        #: within one leaf is positional, so streaming holds per leaf
        #: but not across leaves). Populated only when the batcher runs
        #: with kv_crc enabled; the chaos serve.kv corrupt fault is
        #: what this must catch (docs/serving.md).
        self._crc: Dict[int, List[int]] = {}
        #: per-slot high-water mark of positions the ledger covers —
        #: what lets verify-on-read know how far to re-read when the
        #: speculative verify step wrote past the accepted prefix
        self._crc_filled: Dict[int, int] = {}

    # -- per-slot integrity (crc-on-write / verify-on-read option) ----------
    def crc_filled(self, slot: int) -> int:
        return self._crc_filled.get(slot, 0)

    def crc_update(self, slot: int, leaf_bytes: Sequence[bytes],
                   new_filled: Optional[int] = None) -> None:
        """Fold the bytes just written to ``slot`` (one entry per cache
        leaf, in leaf order) into the slot's running crc32s. The caller
        guarantees the bytes extend the stream contiguously;
        ``new_filled`` records the covered prefix length."""
        cur = self._crc.get(slot)
        if cur is None:
            cur = self._crc[slot] = [0] * len(leaf_bytes)
        for i, raw in enumerate(leaf_bytes):
            cur[i] = zlib.crc32(raw, cur[i])
        if new_filled is not None:
            self._crc_filled[slot] = new_filled

    def crc_reset(self, slot: int, leaf_bytes: Sequence[bytes],
                  filled: int) -> None:
        """Recompute the ledger from a full re-read of positions
        [0, filled) — the speculative-rollback path (an overwrite below
        the high-water mark breaks the append-only stream)."""
        self._crc[slot] = [zlib.crc32(raw) for raw in leaf_bytes]
        self._crc_filled[slot] = filled

    def crc_check(self, slot: int, leaf_bytes: Sequence[bytes]) -> bool:
        """Verify a full re-read of ``slot``'s valid prefix (one entry
        per cache leaf) against the streamed write-side crc32s. True
        when every leaf matches; a slot never written checks clean."""
        cur = self._crc.get(slot)
        if cur is None:
            return True
        return len(cur) == len(leaf_bytes) and all(
            zlib.crc32(raw) == c for raw, c in zip(leaf_bytes, cur))

    def alloc(self) -> Optional[int]:
        """Claim a free slot (None when all are live). The new owner's
        length starts at 0; stale cache bytes need no clearing (masked
        out by `cached_attention`)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.active[slot] = True
        self.lengths[slot] = 0
        self.generation[slot] += 1
        self.allocs += 1
        self._crc.pop(slot, None)   # the new owner's ledger starts empty
        self._crc_filled.pop(slot, None)
        self.peak_live = max(self.peak_live, self.live())
        return slot

    def free(self, slot: int) -> None:
        if not self.active[slot]:
            raise ValueError(f"slot {slot} is not live")
        self.active[slot] = False
        self.lengths[slot] = 0
        self._free.append(slot)
        self.frees += 1

    def live(self) -> int:
        return self.num_slots - len(self._free)

    def occupancy(self) -> float:
        """Live slots / total slots — the batch-occupancy counter."""
        return self.live() / self.num_slots


class BlockPool:
    """Host-side free-list allocator over the device block pool.

    Blocks are REFCOUNTED: a block is held by the sequence that wrote
    it, plus one count per radix-prefix-cache node referencing it, plus
    one per additional sequence sharing it. It returns to the free list
    only when the last reference drops, so a shared system-prompt run
    can never be handed to a new owner while anyone still reads it.

    Also owns the per-BLOCK crc ledger (the PR 8 per-slot ledger moved
    to block granularity): one running crc32 per cache leaf per block
    over the block's written prefix (``filled`` positions). Keyed by
    pool index, so a shared block carries ONE ledger entry no matter
    how many sequences reference it, and verify-on-read of a sequence
    covers its shared prefix for free.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1; got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1; got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # LIFO reuse, same rationale as SlotKVCache
        self._free: List[int] = list(range(num_blocks))[::-1]
        self.refcount = np.zeros(num_blocks, dtype=np.int32)
        self.allocs = 0
        self.frees = 0
        self.peak_in_use = 0
        #: block -> (filled positions, [running crc32 per cache leaf])
        self._crc: Dict[int, Tuple[int, List[int]]] = {}

    # -- allocation ----------------------------------------------------------
    def alloc(self) -> Optional[int]:
        """Claim a free block (None when exhausted); refcount starts at
        1 (the caller's reference). Stale bytes need no clearing —
        positional masking makes them unreachable."""
        if not self._free:
            return None
        blk = self._free.pop()
        assert self.refcount[blk] == 0, \
            f"free list handed out in-use block {blk}"
        self.refcount[blk] = 1
        self.allocs += 1
        self._crc.pop(blk, None)
        self.peak_in_use = max(self.peak_in_use, self.in_use())
        return blk

    def incref(self, blk: int) -> None:
        if self.refcount[blk] < 1:
            raise ValueError(f"block {blk} is not live")
        self.refcount[blk] += 1

    def decref(self, blk: int) -> bool:
        """Drop one reference; returns True when the block was freed."""
        if self.refcount[blk] < 1:
            raise ValueError(f"block {blk} is not live")
        self.refcount[blk] -= 1
        if self.refcount[blk] == 0:
            self._free.append(blk)
            self.frees += 1
            self._crc.pop(blk, None)
            return True
        return False

    def in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def free_count(self) -> int:
        return len(self._free)

    def occupancy(self) -> float:
        return self.in_use() / self.num_blocks

    # -- per-block integrity ledger ------------------------------------------
    def crc_filled(self, blk: int) -> int:
        ent = self._crc.get(blk)
        return 0 if ent is None else ent[0]

    def crc_stream(self, blk: int, leaf_bytes: Sequence[bytes],
                   new_filled: int) -> None:
        """Fold bytes just written at positions [filled, new_filled) of
        ``blk`` (one entry per cache leaf, leaf order) into the block's
        running crcs. The caller guarantees the bytes ARE that range."""
        ent = self._crc.get(blk)
        crcs = [0] * len(leaf_bytes) if ent is None else ent[1]
        for i, raw in enumerate(leaf_bytes):
            crcs[i] = zlib.crc32(raw, crcs[i])
        self._crc[blk] = (new_filled, crcs)

    def crc_reset(self, blk: int, leaf_bytes: Sequence[bytes],
                  filled: int) -> None:
        """Recompute the ledger from a full re-read of positions
        [0, filled) — the rollback path (speculative decode overwrites
        rejected positions, which breaks the append-only stream)."""
        self._crc[blk] = (filled, [zlib.crc32(raw) for raw in leaf_bytes])

    def crc_clone(self, src: int, dst: int) -> None:
        """Copy-on-write bookkeeping: ``dst`` now holds byte-identical
        content to ``src``'s written prefix."""
        ent = self._crc.get(src)
        if ent is not None:
            self._crc[dst] = (ent[0], list(ent[1]))
        else:
            self._crc.pop(dst, None)

    def crc_check(self, blk: int, leaf_bytes: Sequence[bytes]) -> bool:
        """Verify a re-read of ``blk``'s written prefix (positions
        [0, crc_filled)) against the ledger. A block never written
        checks clean."""
        ent = self._crc.get(blk)
        if ent is None:
            return True
        return len(ent[1]) == len(leaf_bytes) and all(
            zlib.crc32(raw) == c for raw, c in zip(leaf_bytes, ent[1]))


class PagedKVCache:
    """Per-batcher paged sequence accounting over a :class:`BlockPool`.

    Rows are decode-batch positions (the executor's fixed
    ``max_batch``); each live row owns an ordered block list. Blocks
    are allocated LAZILY as the sequence grows, but admission RESERVES
    the row's worst-case block budget up front
    (``prompt + max_new_tokens [+ speculative margin]``), so a running
    sequence can never hit an empty pool mid-decode: the admission gate
    (`can_admit`) only opens when free + evictable blocks cover every
    outstanding reservation plus the newcomer. Peak bytes resident
    still track blocks actually allocated — tokens, not slots x
    max_len.

    ``evictor`` (set by the batcher) is asked to release prefix-cache
    blocks when the free list runs dry; with the reservation invariant
    it must always be able to satisfy a reserved append.
    """

    def __init__(self, num_rows: int, blocks_per_seq: int,
                 pool: BlockPool):
        if num_rows < 1:
            raise ValueError(f"num_rows must be >= 1; got {num_rows}")
        self.num_rows = num_rows
        self.blocks_per_seq = blocks_per_seq
        self.pool = pool
        self.block_size = pool.block_size
        self._free_rows: List[int] = list(range(num_rows))[::-1]
        self.blocks: Dict[int, List[int]] = {}
        #: per-row outstanding new-block reservation (worst case growth)
        self.reserved: Dict[int, int] = {}
        self.lengths = np.zeros(num_rows, dtype=np.int32)
        self.active = np.zeros(num_rows, dtype=bool)
        self.generation = np.zeros(num_rows, dtype=np.int64)
        self.allocs = 0
        self.frees = 0
        self.peak_live = 0
        #: batcher-installed hook: evict(n) -> blocks actually released
        #: from the prefix cache back to the pool
        self.evictor: Optional[Callable[[int], int]] = None
        #: batcher-installed hook: evictable() -> prefix-cache blocks
        #: releasable on demand (refcount held only by the cache)
        self.evictable: Optional[Callable[[], int]] = None

    # -- admission capacity (the free-BLOCK signal) --------------------------
    def blocks_needed(self, tokens: int) -> int:
        return -(-max(int(tokens), 1) // self.block_size)

    def reserved_total(self) -> int:
        return sum(self.reserved.values())

    def available_blocks(self, evictable: Optional[int] = None) -> int:
        """Free + evictable - reserved. Pass ``evictable`` to reuse a
        snapshot across an admission wave — the live hook walks the
        whole radix tree, and one walk per wave (not per candidate,
        under the queue lock) is plenty; the batcher charges the wave's
        own pins against the snapshot, which only ever under-admits."""
        if evictable is None:
            evictable = (self.evictable()
                         if self.evictable is not None else 0)
        return self.pool.free_count() + evictable - \
            self.reserved_total()

    def can_admit(self, new_blocks: int,
                  evictable: Optional[int] = None) -> bool:
        """True when a newcomer needing ``new_blocks`` fresh blocks fits
        without ever starving an already-admitted sequence."""
        return bool(self._free_rows) and \
            self.available_blocks(evictable) >= new_blocks

    # -- row lifecycle -------------------------------------------------------
    def alloc_row(self, reserve_blocks: int) -> Optional[int]:
        if not self._free_rows:
            return None
        row = self._free_rows.pop()
        self.active[row] = True
        self.lengths[row] = 0
        self.generation[row] += 1
        self.blocks[row] = []
        self.reserved[row] = int(reserve_blocks)
        self.allocs += 1
        self.peak_live = max(self.peak_live, self.live())
        return row

    def attach_shared(self, row: int, blk: int) -> None:
        """Append an already-referenced (shared prefix) block to the
        row's table; the caller transferred one refcount to this row."""
        self.blocks[row].append(blk)

    def append_block(self, row: int) -> int:
        """Allocate the row's next block from the pool, evicting
        prefix-cache runs when the free list is dry. Guaranteed to
        succeed for reserved growth (the admission invariant)."""
        blk = self.pool.alloc()
        if blk is None and self.evictor is not None:
            self.evictor(1)
            blk = self.pool.alloc()
        if blk is None:
            raise RuntimeError(
                "paged KV pool exhausted on a RESERVED append — the "
                "admission gate must make this unreachable")
        self.blocks[row].append(blk)
        if self.reserved.get(row, 0) > 0:
            self.reserved[row] -= 1
        return blk

    def ensure(self, row: int, tokens: int) -> List[int]:
        """Grow the row's table to cover ``tokens`` virtual positions;
        returns the pool indices of any newly allocated blocks."""
        fresh = []
        while len(self.blocks[row]) * self.block_size < tokens:
            fresh.append(self.append_block(row))
        return fresh

    def free_row(self, row: int) -> None:
        """Release the row and every block reference it holds — shared
        prefix blocks survive under the prefix cache's own refcount.
        MUST run in the same scheduling iteration the sequence retires
        (deadline-expired and shed sequences included): a leaked block
        reference is capacity gone forever."""
        if not self.active[row]:
            raise ValueError(f"row {row} is not live")
        for blk in self.blocks.pop(row, []):
            self.pool.decref(blk)
        self.reserved.pop(row, None)
        self.active[row] = False
        self.lengths[row] = 0
        self._free_rows.append(row)
        self.frees += 1

    # -- views ---------------------------------------------------------------
    def table(self) -> np.ndarray:
        """The `[num_rows, blocks_per_seq]` int32 block-table matrix the
        executor step consumes; -1 marks unassigned entries."""
        t = np.full((self.num_rows, self.blocks_per_seq), -1, np.int32)
        for row, blks in self.blocks.items():
            t[row, :len(blks)] = blks
        return t

    def live(self) -> int:
        return self.num_rows - len(self._free_rows)

    def occupancy(self) -> float:
        """Blocks in use / pool size — the token-resident occupancy the
        block-occupancy gauge exports (NOT a row count: rows are free,
        memory is not)."""
        return self.pool.occupancy()

    @property
    def num_slots(self) -> int:   # row-capacity view (fleet/http compat)
        return self.num_rows
