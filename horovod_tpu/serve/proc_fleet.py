"""Multi-process serve fleet: replicas as OS processes, router as
supervisor — the escape from one Python process and one GIL.

PR 8's :class:`~horovod_tpu.serve.fleet.FleetRouter` proved the
failover contract over N *in-process* replicas; this module promotes
it across real process boundaries, composing machinery that already
exists:

* **Replicas are worker processes** (serve/worker.py) spawned through
  the runner machinery (runner/exec.py ``spawn_local``): each hosts
  its own executor/batcher/queue and a framed TCP request endpoint,
  and posts heartbeats to the native KV store from a chaos-exempt
  ``StoreClient`` — `serve.hb.<ns>.g<gen>.<rid>`, sequence advanced
  only by real scheduler iterations.
* **Dispatch rides the PR 9 resilience ladder** (serve/wire.py +
  native/resilience.py): a transient ``conn_reset``/``flaky`` blip on
  the router->replica socket retries in milliseconds —
  ``hvd_net_retries_total{site="serve.dispatch",outcome="absorbed"}``
  — and NEVER triggers a failover. Replays are safe across the
  boundary because every dispatch carries a request id the worker
  dedupes on (the csrc/store.cc nonce pattern): a replayed dispatch
  whose reply was lost is served its cached result, so
  answered-exactly-once holds even when the wire eats replies.
* **Real process death is detected by the PR 5 accrual semantics**
  over the heartbeat keys (:class:`~horovod_tpu.chaos.detector.
  AccrualTracker`): a SIGKILLed worker's key goes stale, the router
  ejects in O(heartbeat) (<= 2x ``suspect_s``), re-enqueues its
  in-flight requests exactly once onto siblings, then **respawns** a
  fresh process which warms, adopts the newest streamed weight version
  (gated on ``WeightSubscriber.peek_version()``), and is only then
  re-admitted.
* **Degradation is never silent**: while capacity is down the router
  sheds with ``retry_after_ms`` SCALED to live capacity (a fleet at
  half strength tells clients to back off twice as long), and
  ``drain()`` resolves every straggler with a structured rejection.

The soak profile for all of this is ``serve/soak.py run_fleet_soak``
(``tools/serve_soak.py --processes``); docs/serving.md has the process
model and knob table, docs/chaos.md the ``serve.proc`` /
``serve.dispatch`` fault rows.

Prefill/decode disaggregation and KV-block migration (ROADMAP item 2's
second half) deliberately stay out of this module — the process-fleet
substrate here is their prerequisite, not their home.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..chaos import inject as _chaos
from ..chaos.detector import AccrualTracker
from ..native import resilience
from ..obs import metrics as obs_metrics
from ..trace import collect as _tr_collect
from . import wire
from .fleet import (FAILOVER_MS_HELP, FAILOVERS_HELP,
                    FLEET_REJECTED_HELP, FleetHandle, REPLICA_UP_HELP,
                    REQUEUED_HELP, ROUTER_MS_HELP, _Tracked)
from .kvtier import FleetRadixIndex, prefer_holders
from .kvtier.tier import ROUTED_HELP
from .queue import Rejected

logger = logging.getLogger("horovod_tpu")

#: base shed hint before capacity scaling (ms)
SHED_BASE_MS = 250.0
#: metric help strings (single-sourced — metric-help lint)
RESPAWNS_HELP = "replica worker processes respawned after ejection"
FLEET_CAPACITY_HELP = \
    "replicas currently admitted (up) in the process fleet"
POOL_QUEUE_FREE_HELP = \
    "free admission-queue slots summed over the pool's admitted replicas"
POOL_KV_FREE_HELP = \
    "free paged-KV blocks summed over the pool's admitted replicas"
POOL_REPLICAS_UP_HELP = \
    "replicas currently admitted (up) in this pool"
#: how long the router waits for a spawned worker to register ready
DEFAULT_SPAWN_TIMEOUT_S = 120.0
#: bounded window of recently admitted prompt lengths (the autoscale
#: signal plane's prompt-mix source)
_PROMPT_WINDOW = 512


class ProcessReplica:
    """Router-side handle for one replica worker process: spawn
    config, the live process, its registered endpoint, and the cached
    health snapshot the routing decision reads."""

    def __init__(self, rid: int, *, python: Optional[str] = None,
                 log_dir: Optional[str] = None):
        self.id = int(rid)
        self.python = python or sys.executable
        self.log_dir = log_dir
        #: "init" | "spawning" | "up" | "down" | "respawning"
        self.state = "init"
        self.gen = -1
        self.proc = None                 # runner WorkerProcess
        self.addr: Optional[Tuple[str, int]] = None
        self.pid: Optional[int] = None
        self.restarts = 0
        #: cached from the last healthz poll / ready registration
        self.load = 0.0
        self.queue_depth = 0
        self.weights_version: Optional[int] = None
        self.dedupe_hits = 0
        self.healthz_cache: dict = {}

    def spawn(self, cfg: dict, env_extra: Dict[str, str]) -> None:
        """Launch a fresh worker process for generation ``cfg['gen']``
        through the runner machinery (process-group isolation, log
        sink)."""
        from ..runner.exec import spawn_local
        self.gen = int(cfg["gen"])
        env = dict(os.environ)
        env.update(env_extra)
        env["HOROVOD_SERVE_WORKER_CFG"] = json.dumps(cfg)
        # the worker must import horovod_tpu regardless of cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + existing if existing else "")
        log_path = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            log_path = os.path.join(
                self.log_dir, f"replica.{self.id}.g{self.gen}.log")
        self.proc = spawn_local(
            [self.python, "-m", "horovod_tpu.serve.worker"], env,
            rank=self.id, output_path=log_path,
            prefix_output=log_path is None)
        self.pid = self.proc.proc.pid

    def kill(self) -> None:
        if self.proc is not None:
            self.proc.kill()

    def terminate(self) -> None:
        if self.proc is not None:
            self.proc.terminate()


class ProcessFleetRouter:
    """Routes requests over N replica worker PROCESSES; ejects the
    dead, respawns and re-admits them on fresh weights. Same external
    contract as the in-process ``FleetRouter`` (submit -> FleetHandle,
    at-most-once, drain, listener events, ``healthz()``), different
    substrate: sockets, KV heartbeats, OS processes."""

    def __init__(self, n_replicas: int, *, kv_addr: str, kv_port: int,
                 worker: Optional[dict] = None,
                 channel: Optional[str] = None, ns: str = "fleet",
                 interval_s: float = 0.25, suspect_s: float = 1.0,
                 auto_respawn: bool = True, max_attempts: int = 2,
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 drain_retry_after_ms: float = 1000.0,
                 chaos_plan=None, events_dir: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 max_inflight: int = 256,
                 python: Optional[str] = None,
                 pool: Optional[str] = None, rid_base: int = 0):
        if n_replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        #: pool identity (disaggregated serving, serve/disagg.py):
        #: names this router's slice of a split fleet. Metric series
        #: get a {pool=...} label INSTEAD of being claimed fresh (two
        #: pools share one router process and must not clobber each
        #: other), and replica ids start at ``rid_base`` so chaos
        #: ``peer`` addressing and labels stay unambiguous fleet-wide.
        self.pool = pool
        if rid_base < 0:
            raise ValueError(f"rid_base must be >= 0; got {rid_base}")
        if suspect_s <= interval_s:
            raise ValueError(
                f"suspect_s ({suspect_s}) must exceed the heartbeat "
                f"interval ({interval_s}) — a threshold under one "
                f"period suspects every healthy replica")
        self.kv_addr, self.kv_port = str(kv_addr), int(kv_port)
        self.worker_cfg = dict(worker or {})
        self.channel = channel
        self.ns = str(ns)
        self.interval_s = float(interval_s)
        self.suspect_s = float(suspect_s)
        self.auto_respawn = bool(auto_respawn)
        self.max_attempts = int(max_attempts)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.drain_retry_after_ms = float(drain_retry_after_ms)
        #: in-flight ceiling: one dispatcher thread + one socket per
        #: in-flight request is the model; past this, submits shed
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1; got {max_inflight}")
        self.max_inflight = int(max_inflight)
        self.events_dir = events_dir
        self.chaos_plan = chaos_plan
        ids = list(range(int(rid_base),
                         int(rid_base) + int(n_replicas)))
        self._python = python
        self._log_dir = log_dir
        self.replicas: Dict[int, ProcessReplica] = {
            r: ProcessReplica(r, python=python, log_dir=log_dir)
            for r in ids}
        self._tracker = AccrualTracker(
            ids, interval_s=interval_s, suspect_s=suspect_s)
        self._lock = threading.Lock()
        # serializes runtime membership changes (autoscale actuator):
        # one add/remove at a time, so rid allocation and the
        # below-one-replica floor stay race-free
        self._scale_lock = threading.Lock()
        self._recent_prompts: deque = deque(maxlen=_PROMPT_WINDOW)
        self._inflight: Dict[int, _Tracked] = {}
        #: submit-time in-flight reservations (released on resolution)
        self._reserved = 0
        # fid namespace unique per router incarnation: a respawned
        # ROUTER must never collide with fids a long-lived worker still
        # caches from the previous incarnation
        self._fid_ns = os.urandom(4).hex()
        self._fids = itertools.count()
        self._dispatches: Dict[int, int] = {r: 0 for r in ids}
        self._respawning: set = set()
        self._listeners: List[Callable[[dict], None]] = []
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self.draining = False
        self.started = False
        self.duplicates_suppressed = 0
        self.last_failover_ms: Optional[float] = None
        # the dispatch ladder: the process policy's knobs, budget
        # capped at the detection window — a dispatch to a dead
        # replica must stop hoping once the accrual sweep has had time
        # to eject and re-dispatch, not burn the full wire budget
        pol = resilience.policy()
        self._ladder = resilience.RetryPolicy(
            retries=pol.retries, backoff_base_ms=pol.backoff_base_ms,
            budget_s=min(pol.budget_s, max(2.0 * suspect_s, 1.0)),
            seed=pol.seed, rank=pol.rank)
        # chaos-exempt KV clients: the heartbeat SWEEP is observer
        # traffic, same rule as the detector's client; per-replica
        # clients (lazily built) let the sweep read heartbeats
        # concurrently — see _hb_client
        from ..native.store import StoreClient
        self._kv = StoreClient(self.kv_addr, self.kv_port,
                               chaos_exempt=True)
        self._hb_clients: Dict[int, object] = {}
        # -- metrics: claimed fresh when this router IS the routing
        # process's one fleet; a POOL router instead get-or-creates
        # {pool=...}-labeled children (two pools share the process and
        # must not clobber each other's series)
        R = obs_metrics.get_registry()
        pl = {} if pool is None else {"pool": str(pool)}
        if pool is None:
            for fam in ("hvd_serve_replica_up",
                        "hvd_serve_failovers_total",
                        "hvd_serve_requeued_total",
                        "hvd_serve_fleet_rejected_total",
                        "hvd_serve_router_ms", "hvd_serve_failover_ms",
                        "hvd_serve_respawns_total",
                        "hvd_serve_fleet_capacity",
                        "hvd_serve_pool_queue_free",
                        "hvd_serve_pool_kv_blocks_free",
                        "hvd_serve_pool_replicas_up",
                        "hvd_serve_kvtier_routed_total"):
                R.unregister(fam)
        self._pl = pl
        self._m_up = {
            r: R.gauge("hvd_serve_replica_up", REPLICA_UP_HELP,
                       dict(pl, replica=str(r))) for r in ids}
        self._m_failovers = R.counter(
            "hvd_serve_failovers_total", FAILOVERS_HELP, pl or None)
        self._m_requeued = R.counter(
            "hvd_serve_requeued_total", REQUEUED_HELP, pl or None)
        self._m_rejected = R.counter(
            "hvd_serve_fleet_rejected_total", FLEET_REJECTED_HELP,
            pl or None)
        self._m_router = {
            leg: R.histogram(
                "hvd_serve_router_ms", ROUTER_MS_HELP,
                dict(pl, leg=leg))
            for leg in ("dispatch", "e2e")}
        self._m_failover_ms = R.histogram(
            "hvd_serve_failover_ms", FAILOVER_MS_HELP, pl or None)
        self._m_respawns = R.counter(
            "hvd_serve_respawns_total", RESPAWNS_HELP, pl or None)
        self._m_kvtier_routed = R.counter(
            "hvd_serve_kvtier_routed_total", ROUTED_HELP, pl or None)
        #: fleet KV-tier radix index, built lazily from the first
        #: healthz reply that carries kvtier events (the worker only
        #: emits them when its batcher runs a ReplicaKVTier)
        self.kvtier_index: Optional[FleetRadixIndex] = None
        self._m_capacity = R.gauge(
            "hvd_serve_fleet_capacity", FLEET_CAPACITY_HELP,
            pl or None)
        # metrics-plane mirror of the /healthz capacity facts: the
        # autoscale signal plane and external monitors read THESE, not
        # the JSON front door. An un-pooled fleet labels itself "fleet"
        # so the family shape is uniform across deployments.
        pool_label = {"pool": str(pool) if pool is not None else "fleet"}
        self._m_pool_qfree = R.gauge(
            "hvd_serve_pool_queue_free", POOL_QUEUE_FREE_HELP,
            pool_label)
        self._m_pool_kvfree = R.gauge(
            "hvd_serve_pool_kv_blocks_free", POOL_KV_FREE_HELP,
            pool_label)
        self._m_pool_up = R.gauge(
            "hvd_serve_pool_replicas_up", POOL_REPLICAS_UP_HELP,
            pool_label)
        #: distributed-tracing assembler (trace/collect.py): armed by
        #: HOROVOD_TRACE when this router IS the front door (pool is
        #: None); a POOL router instead has the owning DisaggRouter's
        #: shared assembler assigned after construction, so clock
        #: samples and fleet events from both pools feed ONE merge
        self.tracer = (_tr_collect.assembler_from_env(self.ns)
                       if pool is None else None)
        self._incident_seq = itertools.count()

    # -- events --------------------------------------------------------------
    def add_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def _emit(self, event: str, rid: int, **kw) -> None:
        ev = dict(kw, event=event, replica=rid, t=time.time())
        if self.tracer is not None:
            # fleet lifecycle events join the flight recorder's ring
            self.tracer.note_event(ev)
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001
                pass

    # -- spawn / lifecycle ---------------------------------------------------
    def _worker_cfg(self, rep: ProcessReplica, gen: int) -> dict:
        cfg = dict(self.worker_cfg)
        plan = self.chaos_plan
        if plan is not None and not isinstance(plan, dict):
            plan = json.loads(plan.to_json())
        events_path = None
        if self.events_dir:
            os.makedirs(self.events_dir, exist_ok=True)
            events_path = os.path.join(
                self.events_dir, f"replica.{rep.id}.events.jsonl")
        cfg.update({
            "rid": rep.id, "gen": gen, "ns": self.ns,
            # the worker stamps its span recorder with this — it MUST
            # match the clock_key the router notes heartbeats under,
            # or spans never clock-align
            "pool": self.pool or self.ns,
            "kv_addr": self.kv_addr, "kv_port": self.kv_port,
            "channel": self.channel,
            "hb_interval_s": self.interval_s / 2.0,
            "chaos_plan": plan, "events_path": events_path,
        })
        return cfg

    def _ep_key(self, rep: ProcessReplica, gen: int) -> str:
        return f"serve.ep.{self.ns}.g{gen}.{rep.id}"

    def _hb_key(self, rep: ProcessReplica) -> str:
        return f"serve.hb.{self.ns}.g{rep.gen}.{rep.id}"

    def _read_ready(self, rep: ProcessReplica,
                    gen: int) -> Optional[dict]:
        from ..native.store import NativeError
        try:
            raw = self._kv.get(self._ep_key(rep, gen), timeout=0.05)
            return json.loads(raw.decode())
        except (NativeError, ValueError):
            return None

    def _spawn(self, rep: ProcessReplica) -> None:
        gen = rep.gen + 1
        rep.state = "spawning" if rep.restarts == 0 else "respawning"
        rep.spawn(self._worker_cfg(rep, gen), {})

    def _wait_ready(self, rep: ProcessReplica,
                    timeout_s: float) -> bool:
        """Poll for the worker's registration key; on ready, cache its
        endpoint + weight version and verify the weight GATE: the
        version it came up on must cover the channel's newest published
        version (the worker enforces this itself at startup — this is
        the router's audit of it)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            info = self._read_ready(rep, rep.gen)
            if info is not None:
                rep.addr = (str(info["host"]), int(info["port"]))
                rep.weights_version = info.get("weights_version")
                target = self._peek_version()
                if target is not None and \
                        (rep.weights_version or 0) < target:
                    # published while the worker was warming: let its
                    # attached subscriber catch up before admission
                    h = self._fetch_healthz(rep)
                    if h is None or (h.get("weights_version") or 0) \
                            < target:
                        time.sleep(self.interval_s / 2.0)
                        continue
                    rep.weights_version = h.get("weights_version")
                return True
            if rep.proc is not None and rep.proc.poll() is not None:
                logger.error(
                    "fleet: replica %d worker exited rc=%s before "
                    "registering", rep.id, rep.proc.poll())
                return False
            time.sleep(0.1)
        return False

    def _peek_version(self) -> Optional[int]:
        """Newest PUBLISHED weight version on the fleet channel (the
        re-admission gate's target), floored at what any sibling
        already serves."""
        versions = [r.weights_version for r in self.replicas.values()
                    if r.weights_version is not None]
        if self.channel is not None:
            from ..native.store import NativeError
            from ..redist.stream import version_key
            try:
                raw = self._kv.get(version_key(self.channel),
                                   timeout=0.05)
                versions.append(int(raw.decode()))
            except (NativeError, ValueError):
                pass
        return max(versions) if versions else None

    def start(self) -> "ProcessFleetRouter":
        if self.started:
            return self
        self._stop.clear()
        for rep in self.replicas.values():
            self._spawn(rep)
        laggards = [rep.id for rep in self.replicas.values()
                    if not self._wait_ready(rep, self.spawn_timeout_s)]
        if laggards:
            for rep in self.replicas.values():
                rep.kill()
            raise RuntimeError(
                f"fleet: replica worker(s) {laggards} did not register "
                f"within {self.spawn_timeout_s:.0f}s")
        for rep in self.replicas.values():
            rep.state = "up"
            self._m_up[rep.id].set(1)
        self._m_capacity.set(len(self.replicas))
        self._update_pool_gauges(len(self.replicas))
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="hvd-procfleet-health")
        self._health_thread.start()
        self.started = True
        return self

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        for rep in self.replicas.values():
            rep.terminate()
        deadline = time.monotonic() + 5.0
        for rep in self.replicas.values():
            while rep.proc is not None and rep.proc.poll() is None \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            rep.kill()
        # a respawn thread racing this close may have spawned a FRESH
        # process after the kill loop above ran over the old one: wait
        # out the respawners (they abort on _stop and kill their own
        # spawn), then re-kill to cover the last window
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            with self._lock:
                if not self._respawning:
                    break
            time.sleep(0.05)
        for rep in self.replicas.values():
            rep.kill()
        self._kv.close()
        with self._lock:
            hb_clients = list(self._hb_clients.values())
            self._hb_clients.clear()
        for c in hb_clients:
            c.close()
        self.started = False

    def drain(self, timeout_s: float = 30.0) -> None:
        """Stop admitting (submits shed with retry-after), wait out the
        in-flight tail, resolve leftovers as rejected, stop the worker
        processes. Safe against a concurrent respawn: the respawn
        thread re-checks ``draining`` before re-admission and aborts,
        and leftovers it might still own are resolved here."""
        with self._lock:
            self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    break
            time.sleep(0.02)
        with self._lock:
            leftovers = list(self._inflight.values())
            self._inflight.clear()
        for tr in leftovers:
            if tr.handle._resolve(
                    "rejected", retry_after_ms=self.drain_retry_after_ms):
                self._m_rejected.inc()
        self.close()

    # -- request path --------------------------------------------------------
    def _capacity_scale(self) -> float:
        up = sum(1 for r in self.replicas.values() if r.state == "up")
        return len(self.replicas) / max(up, 1)

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int = 0) -> FleetHandle:
        """Route a request; returns a :class:`FleetHandle`. Raises
        :class:`Rejected` synchronously only when the fleet cannot
        accept at all (draining, zero live replicas) — queue-level
        shed from the workers resolves the handle as ``rejected``
        asynchronously, always with a ``retry_after_ms`` scaled to
        live capacity. Sampling controls ride the same at-most-once
        bookkeeping as greedy requests: seeded streams are
        deterministic across re-dispatch, so a failover replays the
        SAME tokens (validated here, fail-fast, mirroring the worker
        queue's door checks — a bad value must be a 400, not an async
        shed)."""
        if not self.started:
            raise RuntimeError("ProcessFleetRouter.start() first")
        temperature, top_p = float(temperature), float(top_p)
        if not (temperature >= 0.0):
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy); got "
                f"{temperature!r}")
        if not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1]; got {top_p!r}")
        t0 = time.monotonic()
        if self.draining:
            self._m_rejected.inc()
            self._trace_shed("draining")
            raise Rejected("fleet draining",
                           retry_after_ms=self.drain_retry_after_ms)
        if not any(r.state == "up" for r in self.replicas.values()):
            # capacity is ZERO: shed loudly, hint scaled to the whole
            # fleet being gone (never a silent drop, never a hang)
            self._m_rejected.inc()
            self._trace_shed("zero_capacity")
            raise Rejected(
                "no live replica (fleet at zero capacity)",
                retry_after_ms=SHED_BASE_MS * self._capacity_scale())
        if deadline_ms is None:
            deadline_ms = float(
                self.worker_cfg.get("deadline_ms", 30000.0))
        with self._lock:
            # each in-flight request holds one dispatcher thread and
            # one socket for its whole generation — the bound keeps
            # that honest under overload by shedding loudly instead of
            # accumulating threads without limit. RESERVED under the
            # lock at submit (not counted at the later _inflight
            # insertion): a burst of concurrent submits must each take
            # a slot before any dispatcher thread runs, or they would
            # all pass a check-then-act reading of the table
            if self._reserved >= self.max_inflight:
                over = True
            else:
                over = False
                self._reserved += 1
        if over:
            self._m_rejected.inc()
            self._trace_shed("max_inflight")
            raise Rejected(
                f"fleet at max in-flight ({self.max_inflight})",
                retry_after_ms=SHED_BASE_MS * self._capacity_scale())
        with self._lock:
            self._recent_prompts.append(len(prompt))
        fid = next(self._fids)
        handle = FleetHandle(fid)
        handle.on_done = self._release_slot   # exactly once, on the
        tr = _Tracked(fid, [int(t) for t in prompt],   # accepted
                      int(max_new_tokens),             # resolution
                      t0 + deadline_ms / 1000.0, t0, handle,
                      temperature=temperature, top_p=top_p,
                      seed=int(seed))
        if self.tracer is not None:
            tr.trace = self.tracer.start(rid=fid).to_wire()
        threading.Thread(
            target=self._run_request, args=(tr,), daemon=True,
            name=f"hvd-procfleet-dispatch-{fid}").start()
        return handle

    def _release_slot(self) -> None:
        with self._lock:
            if self._reserved > 0:
                self._reserved -= 1

    def _trace_shed(self, reason: str) -> None:
        """A synchronous front-door shed still leaves a retained trace
        (the tail sampler keeps every shed), so 'why was I rejected'
        is answerable from the flight recorder."""
        if self.tracer is None:
            return
        ctx = self.tracer.start(rid=None)
        self.tracer.mark(ctx, f"shed:{reason}")
        self.tracer.finish(ctx, "shed", e2e_ms=0.0)

    def _candidates(self, exclude: Optional[int] = None
                    ) -> List[ProcessReplica]:
        out = [r for r in self.replicas.values()
               if r.state == "up" and r.id != exclude
               and r.addr is not None]
        return sorted(out, key=lambda r: (r.load, r.id))

    def _run_request(self, tr: _Tracked,
                     exclude: Optional[int] = None) -> None:
        err = self._dispatch_blocking(tr, exclude=exclude)
        if err is not None:
            if tr.handle._resolve("rejected",
                                  retry_after_ms=err.retry_after_ms):
                self._m_rejected.inc()
        # close the trace only at a real resolution: a dispatcher
        # thread that returned because a FAILOVER now owns the request
        # must leave the trace open for the requeue thread
        if self.tracer is not None and tr.trace is not None \
                and tr.handle.done():
            self.tracer.finish(
                tr.trace, tr.handle.status,
                e2e_ms=tr.handle.latency_ms,
                attempts=tr.handle.attempts)

    def _dispatch_blocking(self, tr: _Tracked,
                           exclude: Optional[int] = None
                           ) -> Optional[Rejected]:
        """Place ``tr`` and see it through to resolution on the
        CALLING thread (a dispatcher thread, never submit's). Returns
        None when the handle was resolved (or a failover path owns
        it), or the Rejected the caller must deliver."""
        retry_hint: Optional[float] = None
        t_d0 = time.monotonic()
        cands = self._candidates(exclude=exclude)
        matched: Dict[int, int] = {}
        if self.kvtier_index is not None and cands:
            cands, matched = prefer_holders(
                cands, tr.prompt, self.kvtier_index,
                versions={r.id: r.weights_version for r in cands})
        for rep in cands:
            # re-derived PER candidate: time burned on a failed
            # predecessor (a stalled ack, a spent ladder) must shrink
            # the budget the next replica enforces, not silently extend
            # the client's deadline — and a deadline that lapsed while
            # failing over resolves as the structured "expired"
            remaining_ms = (tr.deadline - time.monotonic()) * 1000.0
            if remaining_ms <= 0:
                tr.handle._resolve(
                    "expired",
                    latency_ms=(time.monotonic() - tr.submitted_at)
                    * 1000.0)
                return None
            with self._lock:
                if self.draining:
                    return Rejected(
                        "fleet draining",
                        retry_after_ms=self.drain_retry_after_ms)
                tr.rid = rep.id
                tr.inner = None
                self._inflight[tr.fid] = tr
            tr.handle.attempts += 1
            acked: List[float] = []
            try:
                kind, payload = self._rpc(
                    tr, rep, remaining_ms,
                    on_ack=lambda: acked.append(time.monotonic()))
            except Exception as e:  # noqa: BLE001 — ladder exhausted,
                # fatal wire fault, or caller-side abort (ejected)
                with self._lock:
                    if tr.rid != rep.id or tr.handle.done():
                        return None   # failover already owns it
                    tr.rid = None
                    self._inflight.pop(tr.fid, None)
                logger.warning(
                    "fleet: dispatch of request %d to replica %d "
                    "failed (%s); trying the next replica",
                    tr.fid, rep.id, e)
                continue
            if kind == "ok":
                if matched.get(rep.id):
                    # placed on a replica the index said holds a run
                    # of this prompt — the cross-replica locality win
                    self._m_kvtier_routed.inc()
                # the dispatch leg = pick + place: submit-thread start
                # to the replica's ACCEPTED ack (the generation itself
                # is the e2e leg's business)
                if acked:
                    self._m_router["dispatch"].observe(
                        (acked[0] - t_d0) * 1000.0)
                    if self.tracer is not None \
                            and tr.trace is not None:
                        base = time.time() - time.monotonic()
                        self.tracer.span(
                            tr.trace, "dispatch", t_d0 + base,
                            acked[0] + base, replica=rep.id)
                self._on_reply(tr, rep.id, payload)
                return None
            # control ack: the worker's queue door spoke
            with self._lock:
                if tr.rid != rep.id or tr.handle.done():
                    return None
                tr.rid = None
                self._inflight.pop(tr.fid, None)
            ack = payload.get("ack")
            hint = payload.get("retry_after_ms")
            if ack == "admit_dropped":
                # the door ate it (chaos): absorb by re-dispatching —
                # never the client's problem
                retry_hint = hint or retry_hint
                continue
            if ack == "rejected":
                if hint is None:
                    return Rejected(payload.get("reason", "rejected"),
                                    retry_after_ms=None)
                retry_hint = (hint if retry_hint is None
                              else min(retry_hint, hint))
                continue
            return Rejected(payload.get("error", f"bad ack {ack!r}"),
                            retry_after_ms=None)
        return Rejected(
            "no healthy replica available",
            retry_after_ms=(retry_hint or SHED_BASE_MS)
            * self._capacity_scale())

    def _rpc(self, tr: _Tracked, rep: ProcessReplica,
             remaining_ms: float,
             on_ack: Optional[Callable[[], None]] = None
             ) -> Tuple[str, dict]:
        """One laddered dispatch: connect, submit, ack, then block for
        the final reply. Connection-class faults anywhere in the
        exchange are absorbed by the resilience ladder — re-dial,
        REPLAY the submit (same fid; the worker dedupes), re-wait —
        until the ladder's budget (capped at the detection window) or
        the abort hook (this request failed over / the replica was
        ejected) stops it."""
        fid = f"{self._fid_ns}.{tr.fid}"
        addr = rep.addr
        submit_msg = {
            "op": "submit", "fid": fid, "prompt": tr.prompt,
            "max_new_tokens": tr.max_new_tokens,
            "deadline_ms": remaining_ms,
            "temperature": tr.temperature, "top_p": tr.top_p,
            "seed": tr.seed}
        if tr.trace is not None:
            # one JSON field carries the whole context; untraced
            # requests leave the frame byte-identical to before
            submit_msg["trace"] = tr.trace

        def attempt() -> Tuple[str, dict]:
            if _chaos._INJ is not None:
                with self._lock:
                    # .get: the replica may have been removed (scale
                    # down) between candidate pick and a ladder replay
                    n = self._dispatches.get(rep.id, 0)
                    self._dispatches[rep.id] = n + 1
                f = _chaos.fire("serve.dispatch", peer=rep.id, step=n)
                if f is not None and f.kind == "conn_reset":
                    # send the request, then REALLY sever before the
                    # ack: the worker processes it, the reply is lost —
                    # the replay must be served the deduped result
                    s = wire.connect(addr, timeout=2.0)
                    try:
                        wire.send_msg(s, submit_msg)
                        time.sleep(0.01)   # let the frame land
                    finally:
                        s.close()
                    raise wire.DispatchConnError(
                        f"chaos: injected conn_reset at serve.dispatch "
                        f"(replica {rep.id})")
                if f is not None and f.kind == "flaky":
                    raise wire.DispatchConnError(
                        f"chaos: injected flaky drop at serve.dispatch "
                        f"(replica {rep.id})")
            return wire.two_frame_request(
                addr, submit_msg,
                reply_timeout=remaining_ms / 1000.0 + 35.0,
                on_ack=on_ack)

        return self._ladder.run(
            attempt, what=f"dispatch(fid {fid})",
            site="serve.dispatch", plane="serve",
            abort=lambda: tr.rid != rep.id or tr.handle.done())

    def _on_reply(self, tr: _Tracked, rid: int, reply: dict) -> None:
        """At-most-once delivery across the process boundary: the SAME
        ghost-suppression discipline as the in-process router."""
        with self._lock:
            if tr.rid != rid or tr.handle.done():
                self.duplicates_suppressed += 1
                return
            self._inflight.pop(tr.fid, None)
        if self.tracer is not None and tr.trace is not None \
                and reply.get("spans"):
            self.tracer.add_spans(tr.trace, reply["spans"])
        accepted = tr.handle._resolve(
            reply.get("status", "error"),
            tokens=reply.get("tokens") or (),
            latency_ms=(time.monotonic() - tr.submitted_at) * 1000.0,
            error=reply.get("error"), replica=rid)
        if not accepted:
            with self._lock:
                self.duplicates_suppressed += 1
        elif tr.handle.latency_ms is not None:
            self._m_router["e2e"].observe(tr.handle.latency_ms)

    # -- health / failover / respawn -----------------------------------------
    def _health_loop(self) -> None:
        period = max(self.interval_s / 2.0, 0.02)
        while not self._stop.wait(period):
            try:
                self._sweep()
            except Exception as e:  # noqa: BLE001 — health must not die
                logger.error("fleet health sweep error: %s", e)

    def _hb_client(self, rid: int):
        """One chaos-exempt KV client PER replica, so the sweep can
        read every heartbeat key CONCURRENTLY (a StoreClient
        serializes its own requests): with sequential reads, one
        slow/blocked read would inflate the measured heartbeat age of
        every later replica in the same sweep — at N replicas x the
        read timeout that serial delay could falsely suspect a healthy
        sibling."""
        with self._lock:
            c = self._hb_clients.get(rid)
        if c is None:
            from ..native.store import StoreClient
            c = StoreClient(self.kv_addr, self.kv_port,
                            chaos_exempt=True)
            with self._lock:
                self._hb_clients[rid] = c
        return c

    def _read_hb(self, rep: ProcessReplica) -> Optional[int]:
        from ..native.store import NativeError
        try:
            t_before = time.time()
            raw = self._hb_client(rep.id).get(self._hb_key(rep),
                                              timeout=0.1)
            t_after = time.time()
            seq_s, _, wall_s = raw.decode().partition(":")
            seq = int(seq_s)
        except (NativeError, ValueError):
            return None
        if wall_s and self.tracer is not None:
            # a timestamped heartbeat (<seq>:<wall>) doubles as a free
            # round-trip clock sample for span alignment; a bare
            # integer (an older worker) simply contributes none
            try:
                self.tracer.note_heartbeat(
                    self.pool or self.ns, rep.id, float(wall_s),
                    t_before, t_after)
            except ValueError:
                pass
        return seq

    def _read_hb_all(self, reps: List[ProcessReplica]
                     ) -> Dict[int, Optional[int]]:
        if len(reps) <= 1:
            return {rep.id: self._read_hb(rep) for rep in reps}
        results: Dict[int, Optional[int]] = {}

        def read(rep):
            results[rep.id] = self._read_hb(rep)

        threads = [threading.Thread(target=read, args=(r,),
                                    daemon=True) for r in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=0.5)
        return results

    def _fetch_healthz(self, rep: ProcessReplica,
                       timeout: float = 1.0) -> Optional[dict]:
        if rep.addr is None:
            return None
        try:
            sock = wire.connect(rep.addr, timeout=timeout)
            try:
                wire.send_msg(sock, {"op": "healthz"})
                return wire.recv_msg(sock, timeout=timeout)
            finally:
                sock.close()
        except (wire.DispatchConnError, wire.DispatchError, OSError):
            # resilience: exempt (observer probe — liveness is decided
            # by the heartbeat accrual sweep, not this convenience poll)
            return None

    def _sweep(self) -> None:
        self._sweep_n = getattr(self, "_sweep_n", 0) + 1
        ups = [rep for rep in self.replicas.values()
               if rep.state == "up"]
        seqs = self._read_hb_all(ups)
        for rid, rep in list(self.replicas.items()):
            if rep.state == "up":
                event, age = self._tracker.observe(
                    rid, seqs.get(rid))
                if event == "suspect":
                    self._eject(
                        rid, f"heartbeat age {age:.2f}s > "
                        f"suspect {self.suspect_s:.2f}s")
                    continue
                if self._sweep_n % 4:
                    # the convenience load/health poll runs at a 4x
                    # coarser cadence than the heartbeat sweep — a
                    # wedged endpoint must not slow DETECTION of its
                    # siblings
                    continue
                h = self._fetch_healthz(rep, timeout=0.3)
                if h is not None:
                    rep.load = float(h.get("load") or 0.0)
                    rep.queue_depth = int(h.get("queue_depth") or 0)
                    rep.weights_version = h.get("weights_version")
                    rep.dedupe_hits = int(h.get("dedupe_hits") or 0)
                    rep.healthz_cache = h
                    # fleet KV-tier index feed: tier events piggyback
                    # the healthz reply (worker.py) — same channel, one
                    # heartbeat of advisory lag
                    evs = h.get("kvtier_events")
                    if evs:
                        if self.kvtier_index is None:
                            bs = int(h.get("kv_block_size") or 0)
                            if bs > 0:
                                self.kvtier_index = \
                                    FleetRadixIndex(bs)
                        if self.kvtier_index is not None:
                            self.kvtier_index.apply_events(rid, evs)
            elif rep.state == "down" and self.auto_respawn \
                    and not self.draining:
                with self._lock:
                    if rid in self._respawning:
                        continue
                    self._respawning.add(rid)
                threading.Thread(
                    target=self._respawn, args=(rep,), daemon=True,
                    name=f"hvd-procfleet-respawn-{rid}").start()
        up_n = sum(1 for r in self.replicas.values()
                   if r.state == "up")
        self._m_capacity.set(up_n)
        self._update_pool_gauges(up_n)

    def _update_pool_gauges(self, up_n: Optional[int] = None) -> None:
        """Mirror the pool's live capacity facts onto the labeled
        ``hvd_serve_pool_*{pool=...}`` gauges (refreshed per sweep and
        on every membership change)."""
        max_q = int(self.worker_cfg.get("max_queue", 64))
        q_free = kv_free = n_up = 0
        for rep in self.replicas.values():
            if rep.state != "up":
                continue
            n_up += 1
            q_free += max(max_q - rep.queue_depth, 0)
            h = rep.healthz_cache
            if "kv_blocks_total" in h:
                # evictable = prefix-cache-retained blocks, reclaimed
                # on demand by the paged admission gate — headroom,
                # not occupancy
                kv_free += max(
                    int(h["kv_blocks_total"])
                    - int(h.get("kv_blocks_in_use") or 0)
                    + int(h.get("kv_blocks_evictable") or 0), 0)
        self._m_pool_qfree.set(q_free)
        self._m_pool_kvfree.set(kv_free)
        self._m_pool_up.set(up_n if up_n is not None else n_up)

    def _requeue_victims(self, rid: int) -> Tuple[int, int]:
        """Detach every in-flight request owned by ``rid`` and see each
        to a resolution exactly once: re-dispatch onto a sibling while
        attempts remain, else a structured rejection — never a silent
        drop. Shared by ejection and hard scale-down."""
        with self._lock:
            victims = [tr for tr in self._inflight.values()
                       if tr.rid == rid and not tr.handle.done()]
        requeued = rejected = 0
        t_f0 = time.time()
        for tr in victims:
            with self._lock:
                if tr.handle.done() or tr.rid != rid:
                    continue
                tr.rid = None   # detach: the waiter thread's ladder
                self._inflight.pop(tr.fid, None)   # aborts, its late
                # answer (if any) suppresses as a ghost
            if self.tracer is not None and tr.trace is not None:
                # failover-touched traces are always retained
                self.tracer.mark(tr.trace, "failover")
                self.tracer.span(tr.trace, "failover", t_f0,
                                 time.time(), victim_replica=rid)
            if tr.handle.attempts >= self.max_attempts:
                if tr.handle._resolve(
                        "rejected",
                        retry_after_ms=self.drain_retry_after_ms):
                    self._m_rejected.inc()
                    rejected += 1
                if self.tracer is not None and tr.trace is not None:
                    self.tracer.finish(
                        tr.trace, tr.handle.status,
                        e2e_ms=tr.handle.latency_ms,
                        attempts=tr.handle.attempts)
                continue
            requeued += 1
            self._m_requeued.inc()
            threading.Thread(
                target=self._run_request, args=(tr, rid), daemon=True,
                name=f"hvd-procfleet-requeue-{tr.fid}").start()
        return requeued, rejected

    def _eject(self, rid: int, reason: str) -> None:
        rep = self.replicas[rid]
        t0 = time.monotonic()
        rep.state = "down"
        self._m_up[rid].set(0)
        self._m_failovers.inc()
        if self.kvtier_index is not None:
            # a dead process holds nothing — forget its runs so the
            # index stops steering prefix traffic at a ghost
            self.kvtier_index.drop_replica(rid)
        logger.error("fleet: EJECTING replica %d process (%s) — "
                     "re-enqueueing its in-flight requests", rid, reason)
        requeued, rejected = self._requeue_victims(rid)
        failover_ms = (time.monotonic() - t0) * 1000.0
        self.last_failover_ms = failover_ms
        self._m_failover_ms.observe(failover_ms)
        self._emit("eject", rid, reason=reason, requeued=requeued,
                   rejected=rejected, failover_ms=round(failover_ms, 2))
        if self.tracer is not None and self.events_dir:
            # flight recorder: the victim's in-flight traces (with the
            # failover/re-dispatch spans just attached) + the event
            # ring + the retained tail, archived next to the fleet's
            # event log
            try:
                os.makedirs(self.events_dir, exist_ok=True)
                path = os.path.join(
                    self.events_dir,
                    f"incident.eject.r{rid}"
                    f".{next(self._incident_seq)}.jsonl")
                self.tracer.dump_incident(
                    path, reason=f"eject replica {rid}: {reason}")
            except OSError as e:
                # resilience: exempt (local filesystem write, not a
                # wire fault — a failed dump must never stall failover)
                logger.warning(
                    "fleet: incident dump for replica %d failed: %s",
                    rid, e)

    def _respawn(self, rep: ProcessReplica) -> None:
        """Replace a dead replica with a fresh worker process, gated on
        the newest published weights before re-admission."""
        rid = rep.id
        try:
            if self.draining or self._stop.is_set():
                return
            rep.kill()      # make sure the old incarnation is gone
            rep.restarts += 1
            self._m_respawns.inc()
            self._emit("respawn", rid, gen=rep.gen + 1)
            self._spawn(rep)
            if not self._wait_ready(rep, self.spawn_timeout_s):
                if self.draining or self._stop.is_set():
                    # the router is going away and its health thread
                    # with it: nobody will sweep this replica again, so
                    # the process just spawned must die HERE or it
                    # outlives the fleet forever
                    rep.kill()
                    return
                rep.state = "down"   # next sweep retries
                logger.error(
                    "fleet: replica %d respawn did not register in "
                    "%.0fs", rid, self.spawn_timeout_s)
                self._emit("respawn_failed", rid)
                return
            if self.draining or self._stop.is_set():
                rep.kill()           # too late to re-admit
                return
            # fresh accrual history: the respawned replica re-enters
            # never-seen and cannot be insta-suspected
            self._tracker.reset(rid)
            rep.state = "up"
            self._m_up[rid].set(1)
            logger.info(
                "fleet: replica %d re-admitted (respawned pid %s, "
                "weights v%s)", rid, rep.pid, rep.weights_version)
            self._emit("readmit", rid, rebuilt=True, pid=rep.pid,
                       weights_version=rep.weights_version)
        except Exception as e:  # noqa: BLE001
            rep.state = "down"
            logger.error("fleet: replica %d respawn failed: %s", rid, e)
            self._emit("respawn_failed", rid, error=str(e)[:200])
        finally:
            with self._lock:
                self._respawning.discard(rid)

    # -- runtime scaling (autoscale actuator) --------------------------------
    def add_replica(self, *, rid: Optional[int] = None,
                    pre_admit: Optional[
                        Callable[[ProcessReplica], None]] = None,
                    timeout_s: Optional[float] = None) -> int:
        """Grow the fleet by ONE replica at runtime.

        Rides the exact respawn substrate: spawn a fresh worker
        process, wait for its endpoint registration, audit the weight
        gate (the newcomer must serve the channel's newest published
        version — ``_wait_ready``'s existing re-admission check,
        generalized), and only then admit it to the candidate set. Live
        traffic never routes to the newcomer before admission
        (``_candidates`` reads state "up" only), so a newcomer dying
        mid-warmup costs nothing but the retry.

        ``pre_admit`` is the chaos hook for the ``autoscale.scale``
        fault site, called between spawn and the readiness wait — it
        may kill or stall the newcomer. A newcomer that fails to
        register is retried ONCE before the call fails loudly; the
        hook is not re-fired on the retry.

        Returns the new replica id; raises RuntimeError when no worker
        could be admitted within the timeout.
        """
        if not self.started:
            raise RuntimeError("ProcessFleetRouter.start() first")
        timeout = (self.spawn_timeout_s if timeout_s is None
                   else float(timeout_s))
        with self._scale_lock:
            if self.draining:
                raise RuntimeError("fleet draining — cannot scale up")
            with self._lock:
                if rid is None:
                    rid = max(self.replicas) + 1
                elif int(rid) in self.replicas:
                    raise ValueError(
                        f"replica id {rid} already exists")
            rid = int(rid)
            rep = ProcessReplica(rid, python=self._python,
                                 log_dir=self._log_dir)
            R = obs_metrics.get_registry()
            g = R.gauge("hvd_serve_replica_up", REPLICA_UP_HELP,
                        dict(self._pl, replica=str(rid)))
            g.set(0)
            with self._lock:
                # register BEFORE spawning (atomic dict swaps —
                # _candidates/_sweep iterate these without the lock):
                # the warming newcomer must read as PENDING capacity in
                # healthz_infos(), so a scale event never 503s the
                # front door. It cannot take traffic — _candidates and
                # the sweep both act on state "up" only.
                reps = dict(self.replicas)
                reps[rid] = rep
                disp = dict(self._dispatches)
                disp.setdefault(rid, 0)
                mu = dict(self._m_up)
                mu[rid] = g
                self.replicas, self._dispatches = reps, disp
                self._m_up = mu
            self._emit("scale_up_begin", rid)
            admitted = False
            for _ in range(2):
                self._spawn(rep)
                if pre_admit is not None:
                    hook, pre_admit = pre_admit, None
                    hook(rep)
                if self._wait_ready(rep, timeout):
                    admitted = True
                    break
                rep.kill()
                rep.restarts += 1
                self._emit("scale_up_retry", rid)
            if not admitted or self.draining or self._stop.is_set():
                rep.kill()
                with self._lock:
                    reps = dict(self.replicas)
                    reps.pop(rid, None)
                    disp = dict(self._dispatches)
                    disp.pop(rid, None)
                    mu = dict(self._m_up)
                    mu.pop(rid, None)
                    self.replicas, self._dispatches = reps, disp
                    self._m_up = mu
                self._emit("scale_up_failed", rid)
                raise RuntimeError(
                    f"fleet: scale-up replica {rid} was not admitted "
                    f"within {timeout:.0f}s")
            self._tracker.add(rid)
            rep.state = "up"
            g.set(1)
            up_n = sum(1 for r in self.replicas.values()
                       if r.state == "up")
            self._m_capacity.set(up_n)
            self._update_pool_gauges(up_n)
            logger.info(
                "fleet: replica %d admitted by scale-up (pid %s, "
                "weights v%s)", rid, rep.pid, rep.weights_version)
            self._emit("scale_up", rid, pid=rep.pid,
                       weights_version=rep.weights_version)
            return rid

    def remove_replica(self, rid: Optional[int] = None, *,
                       graceful: bool = True,
                       timeout_s: float = 30.0) -> int:
        """Shrink the fleet by ONE replica at runtime.

        Graceful (the default): the victim leaves the candidate set
        immediately (state "removing" — new dispatches skip it), the
        router waits out the victim's own in-flight dispatches AND the
        worker's reported queue/parked tail (a parked row is a
        sequence mid-migration — killing its host would drop it), then
        sends SIGTERM so the worker's drain path finishes the rest.

        On drain timeout — or with ``graceful=False`` (the chaos
        "drop the drain" fault) — the process is SIGKILLed and the
        victim's in-flight requests ride the exact ejection discipline
        (:meth:`_requeue_victims`): re-dispatch or structured reject,
        exactly once, never a silent drop.

        Picks the highest-id admitted replica when ``rid`` is None.
        Refuses (ValueError) to take the fleet below one admitted
        replica. Returns the removed replica id.
        """
        with self._scale_lock:
            with self._lock:
                ups = [r for r in self.replicas.values()
                       if r.state == "up"]
                if rid is None:
                    if not ups:
                        raise ValueError(
                            "no admitted replica to remove")
                    rep = max(ups, key=lambda r: r.id)
                else:
                    rep = self.replicas.get(int(rid))
                    if rep is None:
                        raise ValueError(f"unknown replica id {rid}")
                if rep.state == "up" and len(ups) <= 1:
                    raise ValueError(
                        "refusing to scale below one admitted replica")
                rid = rep.id
                rep.state = "removing"
            self._m_up[rid].set(0)
            self._emit("scale_down_begin", rid,
                       graceful=bool(graceful))
            drained = False
            if graceful:
                deadline = time.monotonic() + float(timeout_s)
                while time.monotonic() < deadline \
                        and not self._stop.is_set():
                    with self._lock:
                        busy = any(
                            tr.rid == rid and not tr.handle.done()
                            for tr in self._inflight.values())
                    if not busy:
                        h = self._fetch_healthz(rep, timeout=0.5)
                        if h is None:
                            break   # worker already gone
                        if int(h.get("queue_depth") or 0) == 0 \
                                and int(h.get("parked") or 0) == 0:
                            drained = True
                            break
                    # lock-order: exempt (_scale_lock EXISTS to
                    # serialize add/remove_replica against each other
                    # across the whole drain; dispatch runs under the
                    # separate self._lock, which is NOT held here)
                    time.sleep(0.05)
                rep.terminate()   # SIGTERM: the worker drains itself
                deadline = time.monotonic() + 10.0
                while rep.proc is not None \
                        and rep.proc.poll() is None \
                        and time.monotonic() < deadline:
                    # lock-order: exempt (same: only the scale-op
                    # serialization lock is held while waiting out the
                    # victim's exit — siblings are other scale ops)
                    time.sleep(0.05)
            rep.kill()            # hard kill (no-op after clean exit)
            requeued, rejected = self._requeue_victims(rid)
            with self._lock:
                reps = dict(self.replicas)
                reps.pop(rid, None)
                disp = dict(self._dispatches)
                disp.pop(rid, None)
                mu = dict(self._m_up)
                mu.pop(rid, None)
                self.replicas, self._dispatches = reps, disp
                self._m_up = mu
                hb = self._hb_clients.pop(rid, None)
            self._tracker.remove(rid)
            if hb is not None:
                hb.close()
            up_n = sum(1 for r in self.replicas.values()
                       if r.state == "up")
            self._m_capacity.set(up_n)
            self._update_pool_gauges(up_n)
            logger.info(
                "fleet: replica %d removed by scale-down (graceful=%s "
                "drained=%s requeued=%d rejected=%d)", rid,
                bool(graceful), drained, requeued, rejected)
            self._emit("scale_down", rid, graceful=bool(graceful),
                       drained=drained, requeued=requeued,
                       rejected=rejected)
            return rid

    def recent_prompt_lens(self) -> List[int]:
        """Prompt lengths of recently admitted requests (bounded
        window) — the autoscale signal plane's prompt-mix source."""
        with self._lock:
            return list(self._recent_prompts)

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
        reps = {}
        for rid, rep in self.replicas.items():
            reps[rid] = {
                "state": rep.state,
                "restarts": rep.restarts,
                "pid": rep.pid,
                "queue_depth": rep.queue_depth,
                "weights_version": rep.weights_version,
                "dedupe_hits": rep.dedupe_hits,
            }
        return {
            "replicas_up": sum(1 for r in self.replicas.values()
                               if r.state == "up"),
            "replicas": reps,
            "inflight": inflight,
            "draining": self.draining,
            "duplicates_suppressed": self.duplicates_suppressed,
            "failovers": int(self._m_failovers.value),
            "requeued": int(self._m_requeued.value),
            "rejected": int(self._m_rejected.value),
            "respawns": int(self._m_respawns.value),
            "last_failover_ms": self.last_failover_ms,
        }

    def healthz_infos(self) -> Dict[int, dict]:
        """Per-replica healthz facts from the health-poll cache — the
        ``aggregate_healthz`` input, exposed separately so a pool-split
        router (serve/disagg.py) can merge several pools' infos into
        one front-door payload."""
        max_q = int(self.worker_cfg.get("max_queue", 64))
        infos = {}
        for rid, rep in self.replicas.items():
            h = rep.healthz_cache if rep.state == "up" else {}
            up = rep.state == "up" and bool(h.get("replica_up", True))
            info = {
                "state": rep.state, "up": up,
                "draining": bool(h.get("draining", False)),
                "queue_depth": rep.queue_depth,
                "weights_version": rep.weights_version,
                "restarts": rep.restarts,
                "queue_free": max(max_q - rep.queue_depth, 0),
            }
            if up and "kv_blocks_total" in h:
                info["kv_blocks_total"] = h["kv_blocks_total"]
                info["kv_blocks_in_use"] = h.get("kv_blocks_in_use", 0)
                info["kv_blocks_evictable"] = h.get(
                    "kv_blocks_evictable", 0)
            if up and "prefix_tokens_resident" in h:
                info["prefix_tokens_resident"] = \
                    h["prefix_tokens_resident"]
                info["prefix_tokens_evictable"] = h.get(
                    "prefix_tokens_evictable", 0)
            infos[rid] = info
        return infos

    def metrics_snapshots(self, timeout: float = 2.0) -> List[dict]:
        """Scrape every live replica's in-process metrics snapshot
        (the worker's ``{"op": "metrics"}`` ctrl endpoint) — the
        ``/metrics?fleet=1`` merge input (obs ``merge_snapshots``).
        Unreachable replicas are skipped: a scrape must degrade the
        merge, never wedge the front door."""
        snaps: List[dict] = []
        for rep in list(self.replicas.values()):
            if rep.state != "up" or rep.addr is None:
                continue
            try:
                sock = wire.connect(rep.addr, timeout=timeout)
                try:
                    wire.send_msg(sock, {"op": "metrics"})
                    reply = wire.recv_msg(sock, timeout=timeout)
                finally:
                    sock.close()
            except (wire.DispatchConnError, wire.DispatchError,
                    OSError):
                # resilience: exempt (observer scrape — a missing
                # snapshot is a gap in one scrape, not a fault)
                continue
            snap = reply.get("snapshot")
            if isinstance(snap, dict):
                snaps.append(snap)
        return snaps

    def healthz(self) -> dict:
        """The fleet front door's aggregate liveness payload
        (serve/http.py ``make_fleet_server``): per-replica
        up/draining/respawning plus LIVE capacity (free queue depth and
        free KV blocks summed over admitted replicas). ``ok`` is False
        — the HTTP face answers 503 — once live capacity is zero.
        Shape built by the shared ``fleet.aggregate_healthz``; this
        router sources the per-replica facts from its health-poll
        cache (the workers are separate processes)."""
        from .fleet import aggregate_healthz
        return aggregate_healthz(
            self.healthz_infos(), draining=self.draining,
            retry_after_ms=SHED_BASE_MS * self._capacity_scale())
