"""Continuous batcher: iteration-level scheduling over fixed shapes.

The Orca insight, TPU-flavored: requests join and leave the running
batch *between decode iterations*, never mid-program, and every program
the scheduler launches has one of a small closed set of shapes —
``[max_batch, 1]`` for decode, ``[max_batch, bucket]`` for each
configured prefill bucket (HOROVOD_SERVE_BUCKETS), and
``[max_batch, spec_k + 1]`` for the speculative verify step — so jit
compiles each exactly once and batch churn can never trigger a
recompile.

One `step()` is one scheduling iteration:

1. **retire** — finished (max_new_tokens / EOS / context-full) and
   deadline-expired sequences resolve their handles and free their KV
   capacity (slot, or block-table references + prefix refcounts) in
   the SAME iteration — a leaked block is capacity gone forever.
2. **admit** — pop queued requests into free capacity. Slotted caches
   admit on free slots; paged caches (serve/kv_cache.py `PagedKVCache`)
   admit on free BLOCKS — tokens, not slots — through
   `queue.pop_fitting`. With the radix prefix cache enabled
   (serve/prefix.py), each prompt is first matched against cached
   shared prefixes: matched blocks join the sequence's table by
   reference (copy-on-write at a mid-block divergence) and only the
   suffix is prefilled.
3. **decode** — one `[max_batch, 1]` step for every live sequence; or,
   with a draft executor attached, SPECULATIVE decoding: the drafter
   proposes up to `spec_k` tokens per row ([max_batch, 1] draft steps),
   the target scores all of them in ONE `[max_batch, spec_k+1]` verify
   step, and the greedy accept/rollback rule emits tokens BIT-IDENTICAL
   to target-only greedy decode — between 1 and spec_k+1 of them per
   target step.

Prefill counts as producing the first generated token (its last-logit
argmax), so a request admitted in iteration k has a token by k — no
separate prefill queue.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..chaos import inject as _chaos
from ..obs import metrics as obs_metrics
from ..trace.spans import get_recorder as _trace_recorder
from .kv_cache import BlockPool, PagedKVCache, SlotKVCache
from .kvtier.tier import ReplicaKVTier
from .prefix import RadixPrefixCache
from .queue import AdmissionQueue, ServeRequest

logger = logging.getLogger("horovod_tpu")

#: acceptance-rate histogram bounds: fractions in (0, 1]
_ACCEPT_BOUNDS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0)


class ReplicaDead(RuntimeError):
    """A chaos ``serve.step`` crash: this replica's scheduler thread
    dies here — the in-process analog of losing the replica's host.
    Its heartbeats stop, which is what the fleet router's accrual
    tracker detects (serve/fleet.py)."""


@dataclass
class _Active:
    req: ServeRequest
    slot: int
    #: generated tokens so far (first comes from the prefill step)
    out: List[int] = field(default_factory=list)
    #: tokens written into the KV cache (prompt + confirmed generations)
    cache_len: int = 0
    #: paged admission plan: prefix-matched blocks awaiting attachment
    plan: Optional[dict] = None
    #: prompt tokens served from the prefix cache instead of recompute
    prefix_tokens: int = 0
    #: tokens of this sequence VALIDLY ingested into the drafter cache
    draft_len: int = 0
    #: per-row random-draw counter: every sampling event (prefill,
    #: decode, each speculative position) consumes a fixed counter
    #: budget, so a request's token stream depends only on its own
    #: (seed, counter) history — deterministic across batch positions
    #: and restarts (a re-prefill replays from 0 and reproduces the
    #: original stream)
    rng_ctr: int = 0
    #: params version the PREFILL step actually ran under — what the
    #: migration packet stamps as its weight fence. Captured at the
    #: prefill (not at pack time): a hot swap landing between prefill
    #: and migration must fence the packet OUT, not relabel stale KV
    #: as current.
    params_version: Optional[int] = None
    #: monotonic stamp of the first generated token (prefill-step end,
    #: or install time for a migrated sequence) — the traced decode
    #: span's start (docs/tracing.md)
    t_first: Optional[float] = None
    #: monotonic stamp when the sequence parked for migration — the
    #: traced park span's start (serve/kv_migrate.py records its end
    #: at pack time)
    parked_at: Optional[float] = None


class ContinuousBatcher:
    """Schedules an `AdmissionQueue` onto a `ShardedExecutor`."""

    def __init__(self, executor, queue: AdmissionQueue, *,
                 buckets: Sequence[int] = (32, 128, 512),
                 eos_id: Optional[int] = None,
                 replica_id: Optional[int] = None,
                 kv_crc: Optional[bool] = None,
                 on_kv_corrupt: str = "reprefill",
                 draft_executor=None,
                 spec_k: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_tier: Optional[bool] = None,
                 kvtier_host_mb: Optional[int] = None,
                 kvtier_dir: Optional[str] = None):
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints; got {buckets}")
        if buckets[-1] > executor.max_len:
            raise ValueError(
                f"largest prefill bucket {buckets[-1]} exceeds the model "
                f"context {executor.max_len}")
        if on_kv_corrupt not in ("reprefill", "error"):
            raise ValueError(
                f"on_kv_corrupt must be 'reprefill' or 'error'; "
                f"got {on_kv_corrupt!r}")
        self.executor = executor
        self.queue = queue
        self.buckets = buckets
        self.eos_id = eos_id
        #: fleet identity (None = standalone): labels the metric
        #: series and addresses chaos serve.step / serve.kv faults
        self.replica_id = replica_id
        cfg = None
        if kv_crc is None or spec_k is None or prefix_cache is None \
                or kv_tier is None:
            from ..core.config import Config
            cfg = Config.from_env()
        #: per-slot/per-block crc-on-write / verify-on-read
        #: (HOROVOD_SERVE_KV_CRC or explicit): every cache write is
        #: folded into the crc ledger and every retiring request's
        #: valid prefix is re-read and verified BEFORE its tokens can
        #: reach a client — a corrupted cache either re-prefills from
        #: the prompt or fails cleanly ("error"/kv_corrupt), never
        #: returns garbage. Costs one device->host readback of the
        #: written slice per step plus one full-prefix readback per
        #: retiring request; an integrity option for chaos runs and
        #: paranoid deployments, off by default.
        self.kv_crc = bool(cfg.serve_kv_crc if kv_crc is None else kv_crc)
        self.on_kv_corrupt = on_kv_corrupt
        self.kv_corruptions_detected = 0
        self.kv_corruptions_injected = 0
        self.kv_reprefills = 0
        #: a fired serve.kv corrupt waiting for a written slot, (slot,)
        self._pending_corrupt = None
        # unservable prompts get shed at submit time, not discovered
        # holding a decode slot
        if queue.max_prompt_len is None or \
                queue.max_prompt_len > buckets[-1]:
            queue.max_prompt_len = buckets[-1]

        # -- KV storage: paged (block pool + optional radix prefix
        # cache) when the model config says so, slotted otherwise
        self.paged = bool(getattr(executor, "paged", False))
        if self.paged:
            pool = BlockPool(executor.kv_pool_blocks,
                             executor.kv_block_size)
            self.kv = PagedKVCache(executor.max_batch,
                                   executor.blocks_per_seq, pool)
            if prefix_cache is None:
                prefix_cache = cfg.serve_prefix_cache
            self.prefix: Optional[RadixPrefixCache] = (
                RadixPrefixCache(pool, replica_id=replica_id)
                if prefix_cache else None)
            if self.prefix is not None:
                self.kv.evictable = self.prefix.evictable_blocks
                self.kv.evictor = self.prefix.evict
        else:
            self.kv = SlotKVCache(executor.max_batch, executor.max_len)
            self.prefix = None
        #: params version the prefix cache's contents were computed
        #: under; a swap flushes the cache before any further lookup
        self._prefix_version = executor.params_version
        #: router-raised out-of-band flush (re-admission gate)
        self._prefix_flush = threading.Event()

        # -- fleet KV tier (serve/kvtier/): evicted prefix runs demote
        # down the HBM -> host -> disk ladder and promote back through
        # the verified install path. Paged + prefix-cache only — with
        # either off the knob is inert (same contract as the prefix
        # cache itself being paged-only).
        if kv_tier is None:
            kv_tier = cfg.serve_kvtier
        self.kvtier: Optional[ReplicaKVTier] = None
        if kv_tier and self.paged and self.prefix is not None:
            if kvtier_host_mb is None or kvtier_dir is None:
                if cfg is None:
                    from ..core.config import Config
                    cfg = Config.from_env()
                if kvtier_host_mb is None:
                    kvtier_host_mb = cfg.serve_kvtier_host_mb
                if kvtier_dir is None:
                    kvtier_dir = cfg.serve_kvtier_dir
            self.kvtier = ReplicaKVTier(
                executor, self.kv.pool, self.prefix,
                replica_id=replica_id, kv_crc=self.kv_crc,
                host_bytes=int(kvtier_host_mb) * 1024 * 1024,
                spill_dir=kvtier_dir or None)
            self.prefix.on_evict = self.kvtier.on_evict

        # -- speculative decoding: a draft executor proposes spec_k
        # tokens per iteration; the target verifies them in one step
        if spec_k is None:
            spec_k = cfg.serve_spec_k
        self.spec_k = int(spec_k) if draft_executor is not None else 0
        self.draft = draft_executor if self.spec_k > 0 else None
        if self.draft is not None:
            if getattr(self.draft, "paged", False):
                raise ValueError(
                    "the draft executor must use the slotted cache "
                    "(its rows mirror the target batch 1:1; paging the "
                    "throwaway draft state buys nothing)")
            if self.draft.max_batch != executor.max_batch:
                raise ValueError(
                    f"draft max_batch {self.draft.max_batch} must equal "
                    f"the target's {executor.max_batch} (rows pair 1:1)")
            if buckets[-1] > self.draft.max_len:
                raise ValueError(
                    f"largest prefill bucket {buckets[-1]} exceeds the "
                    f"draft model context {self.draft.max_len}")
        #: (per-SEQUENCE target verify+decode step participations,
        #: tokens emitted by them) — the machine-independent
        #: speculative win the bench gate asserts (< 0.7 target steps
        #: per generated token). Row-granular on purpose: batched plain
        #: decode pegs at exactly 1.0 (each row pays one target step
        #: per token it emits), so only speculation can push the ratio
        #: below 1 — batching wins cannot masquerade as draft wins.
        self.gen_steps = 0
        self.gen_tokens = 0

        self._active: Dict[int, _Active] = {}   # slot/row -> sequence
        self._reprefill: List[ServeRequest] = []
        # -- disaggregated serving (serve/disagg.py, serve/kv_migrate.py)
        #: PARKED sequences: cleanly retired hold_kv requests whose row
        #: + blocks stay allocated awaiting KV-block migration to a
        #: decode replica. Keyed by request rid; mutations under
        #: _parked_lock (a LEAF lock: nothing else is ever taken under
        #: it), reads from the endpoint thread are snapshot copies.
        self.parked: Dict[int, _Active] = {}
        self._parked_lock = threading.Lock()
        #: rids whose parked row the endpoint released (migration done
        #: or abandoned) — freed on the scheduler thread at step top
        self._parked_release: List[int] = []
        #: pin counts: a parked row being PACKED for migration must
        #: not be freed (released or TTL-reaped) mid-read — the pool
        #: could re-issue its blocks to a new owner and the pack would
        #: stamp self-consistent crcs over the wrong sequence's bytes
        self._parked_pins: Dict[int, int] = {}
        #: pending migrated-sequence installs (endpoint-submitted;
        #: installed on the scheduler thread through the same
        #: reservation-gated capacity check admission uses)
        self._migrate_in: List[dict] = []
        self._migrate_lock = threading.Lock()
        #: how long a parked row outlives its request deadline before
        #: the reaper frees it (the router died / abandoned it)
        self.parked_grace_s = 5.0
        self.migrations_in = 0
        self.migrate_rejects = 0
        self.parked_reaped = 0
        #: migration payloads whose per-block crc failed on arrival —
        #: incremented by the endpoint (note_migrate_corrupt), counted
        #: here so /healthz and the soak verdict see one number
        self.migrate_corrupt_detected = 0
        self.iterations = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead = False
        #: fleet liveness hook: called once per scheduling iteration
        #: (busy or idle) on the batcher thread; a crashed/stuck
        #: replica stops calling it, which is the router's signal
        self.heartbeat: Optional[Callable[[], None]] = None
        #: router-visible drain flag (mirrored into /healthz)
        self.draining = False
        # -- metrics: time-to-first-token (admission wait + prefill),
        # live KV occupancy, and — paged — the block-occupancy gauge.
        # Standalone batchers claim fresh; fleet replicas use labeled
        # children (same discipline as AdmissionQueue/ShardedExecutor).
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        R = obs_metrics.get_registry()
        if replica_id is None:
            R.unregister("hvd_serve_ttft_ms")
            R.unregister("hvd_serve_kv_occupancy")
            R.unregister("hvd_serve_kv_blocks_in_use")
            R.unregister("hvd_serve_spec_accept_rate")
        self._m_ttft = R.histogram(
            "hvd_serve_ttft_ms",
            "time to first generated token (submit -> prefill), ms",
            rl or None)
        self._m_occupancy = R.gauge(
            "hvd_serve_kv_occupancy",
            "fraction of KV capacity in use (slots, or pool blocks "
            "when paged — tokens resident, not sequences)", rl or None)
        self._m_blocks = R.gauge(
            "hvd_serve_kv_blocks_in_use",
            "paged KV blocks currently allocated (0 when slotted)",
            rl or None)
        self._m_accept = R.histogram(
            "hvd_serve_spec_accept_rate",
            "speculative decode: fraction of draft tokens accepted per "
            "verify step", rl or None, bounds=_ACCEPT_BOUNDS)
        self._m_kv_corrupt = R.counter(
            "hvd_serve_kv_corruptions_total",
            "KV slots whose verify-on-read crc failed (corruption "
            "caught before reaching a client)", rl or None)
        self._m_migrate_corrupt = R.counter(
            "hvd_serve_migrate_corrupt_total",
            "migrated-KV payloads whose per-block crc failed on "
            "arrival (corruption caught before install)", rl or None)
        #: optional weight-stream subscriber (redist/stream.py): polled
        #: between scheduling iterations, rate-limited so an idle or
        #: not-yet-published channel cannot stall the decode loop
        self._weights = None
        self._weights_interval = 0.25
        self._weights_next_poll = 0.0

    # -- hot weight streaming ------------------------------------------------
    def attach_weights(self, subscriber,
                       min_interval_s: float = 0.25) -> None:
        """Attach a ``WeightSubscriber``: the scheduler polls it
        between iterations — at most every ``min_interval_s`` seconds,
        because a poll against a channel with no published head blocks
        for the subscriber's KV timeout (~50 ms), which must not be
        paid per ~ms decode iteration — and adopts newer param
        versions via ``executor.swap_params`` (the executor's step
        lock is the no-mid-step fence). A transient stream failure
        logs and keeps serving on the current weights; it never takes
        the fleet down."""
        self._weights = subscriber
        self._weights_interval = float(min_interval_s)
        self._weights_next_poll = 0.0       # first step polls
        self._weights_thread = None

    def _maybe_swap_weights(self) -> None:
        """Kick (never join) a background adoption: the KV fetch, crc
        verify, assembly and device placement of a multi-GB tree must
        not run inline on the decode scheduling thread — only the final
        pointer swap is fenced, inside ``swap_params``'s step lock, so
        in-flight requests pay at most one step of swap latency, never
        the full adoption."""
        if self._weights is None:
            return
        now = time.monotonic()
        if now < self._weights_next_poll:
            return
        t = self._weights_thread
        if t is not None and t.is_alive():
            return                        # previous adoption in flight
        self._weights_next_poll = now + self._weights_interval

        def adopt():
            try:
                got = self._weights.poll()
                if got is not None:
                    version, tree = got
                    t_sw = time.time()
                    self.executor.swap_params(tree, version=version)
                    _trace_recorder().record_process(
                        "weight_fence", t_sw, time.time(),
                        version=version)
            except Exception as e:  # noqa: BLE001 — serve on stale
                import logging
                logging.getLogger("horovod_tpu").warning(
                    "weight stream poll failed (serving continues on "
                    "version %s): %s", self.executor.params_version, e)

        self._weights_thread = threading.Thread(
            target=adopt, daemon=True, name="hvd-serve-weights")
        self._weights_thread.start()

    # -- prefix-cache version fencing ---------------------------------------
    def request_prefix_flush(self) -> None:
        """Out-of-band invalidation (fleet re-admission gate): the
        flush itself runs on the scheduler thread at the top of the
        next iteration, BEFORE any admission can match — single-writer
        discipline, no lock needed."""
        self._prefix_flush.set()

    def _maybe_flush_prefix(self) -> None:
        if self.prefix is None:
            return
        v = self.executor.params_version
        if v != self._prefix_version or self._prefix_flush.is_set():
            dropped = self.prefix.flush()
            self._prefix_version = v
            self._prefix_flush.clear()
            if self.kvtier is not None:
                # ladder entries under the old version can never
                # promote (the fence refuses them): drop the host ring
                # and tell the fleet index this replica holds nothing
                self.kvtier.on_flush()
            if dropped:
                logger.info(
                    "serve replica %s: prefix cache flushed (%d runs) "
                    "on weight version change -> %s",
                    self.replica_id, dropped, v)

    # -- shape warmup --------------------------------------------------------
    def warmup(self) -> None:
        """Compile every shape the scheduler can launch — decode, one
        prefill per bucket, the speculative verify ([max_batch,
        spec_k+1]) and draft shapes, and the CoW block copy — with
        all-False masks (state untouched). Run once at startup so
        overload/churn never meets a compile; the draft/verify shapes
        joining this set is what keeps the jit cache flat when
        speculation is on."""
        B = self.executor.max_batch
        zero = np.zeros(B, np.int32)
        off = np.zeros(B, bool)
        tbl = (np.full((B, self.executor.blocks_per_seq), -1, np.int32)
               if self.paged else None)
        for b in self.buckets:
            self.executor.step(np.zeros((B, b), np.int32), zero, off,
                               zero, kind="prefill", block_tables=tbl)
        self.executor.step(np.zeros((B, 1), np.int32), zero, off, zero,
                           kind="decode", block_tables=tbl)
        if self.paged:
            self.executor.copy_kv_block(0, 0)   # compile the CoW copy
        if self.draft is not None:
            self.executor.step(
                np.zeros((B, self.spec_k + 1), np.int32), zero, off,
                zero, kind="verify", block_tables=tbl)
            for b in self.buckets:
                self.draft.step(np.zeros((B, b), np.int32), zero, off,
                                zero, kind="prefill")
            self.draft.step(np.zeros((B, 1), np.int32), zero, off, zero,
                            kind="decode")

    # -- chaos guards (one attribute read when disarmed) ---------------------
    def _fire_step_chaos(self) -> None:
        """``serve.step`` site: crash kills THIS replica (the scheduler
        thread dies and heartbeats stop — the router's problem from
        here); delay/slow_rank sleep inside the injector, stalling the
        replica mid-decode exactly like an overloaded host."""
        if _chaos._INJ is None:
            return
        f = _chaos.fire("serve.step", peer=self.replica_id,
                        step=self.iterations)
        if f is not None and f.kind == "crash":
            raise ReplicaDead(
                f"chaos: replica {self.replica_id} crashed mid-decode "
                f"(iteration {self.iterations})")

    def _fire_kv_chaos(self) -> None:
        """``serve.kv`` site: corrupt flips a real bit inside a live
        sequence's device cache — a slot row when slotted, a BLOCK of
        the pool when paged (detection must come from the per-block crc
        ledger, nothing else knows). A corrupt fired on an iteration
        with no written data is DEFERRED to the next one that has some,
        so an exact-``at`` address always lands exactly one flip."""
        if _chaos._INJ is None and self._pending_corrupt is None:
            return
        if _chaos._INJ is not None:
            f = _chaos.fire("serve.kv", peer=self.replica_id,
                            step=self.iterations)
            if f is not None and f.kind == "corrupt" \
                    and self._pending_corrupt is None:
                self._pending_corrupt = (f.slot,)
        if self._pending_corrupt is not None and self._active:
            want = self._pending_corrupt[0]
            slot = want if (want is not None and want in self._active) \
                else min(self._active)
            length = self._active[slot].cache_len
            if length > 0:
                self._pending_corrupt = None
                if self.paged:
                    bs = self.kv.block_size
                    bi = (int(length) - 1) // bs
                    blk = self.kv.blocks[slot][bi]
                    self.executor.corrupt_kv_block(
                        blk, ((int(length) - 1) % bs) + 1)
                else:
                    self.executor.corrupt_kv_slot(slot, int(length))
                self.kv_corruptions_injected += 1

    # -- one scheduling iteration -------------------------------------------
    def step(self) -> bool:
        """Run one retire/admit/prefill/decode iteration; returns True
        while there is (or may be) work in flight."""
        hb = self.heartbeat
        if hb is not None:
            hb()
        self._fire_step_chaos()
        self._maybe_swap_weights()
        # stale-weight KV must never serve a new version: any adopted
        # swap (or router-requested flush) invalidates the prefix cache
        # BEFORE this iteration can match against it
        self._maybe_flush_prefix()
        # expired-but-still-queued requests get their structured
        # deadline completion NOW, even when every slot is busy —
        # within one iteration, not at slot-drain time
        self.queue.reap_expired()
        # migration plumbing (single-writer: all pool/row bookkeeping
        # happens HERE, on the scheduler thread — the endpoint only
        # enqueues): free rows the endpoint released, reap abandoned
        # parked rows, install migrated sequences BEFORE admission so
        # a mid-stream arrival is never starved by local newcomers
        self._drain_parked_release()
        self._install_migrated()
        self._retire()
        # KV tier (serve/kvtier/): install router-pulled runs, then
        # promote ladder-held prefixes of waiting prompts BEFORE the
        # admission wave matches — a promoted block is indistinguishable
        # from a locally cached one by the time _plan walks the tree
        if self.kvtier is not None:
            if self.kvtier.has_grafts():
                self.kvtier.install_grafts()
            if not self.kvtier.empty():
                for p in self.queue.peek_prompts(
                        self.executor.max_batch):
                    self.kvtier.promote_for(p)
        admitted = self._admit()
        if admitted:
            self._prefill(admitted)
            self._retire()  # a 1-token request finishes at prefill
        if self._active:
            self._decode()
        # evaluated EVERY iteration, busy or idle: the iteration counter
        # below ticks regardless, so an exact-'at' corrupt landing while
        # the replica is idle must still be captured (and deferred to
        # the next written slot) — inside the busy branch the counter
        # would walk past the address without fire() ever seeing it
        self._fire_kv_chaos()
        if self._active:
            self._retire()
        self.iterations += 1
        return bool(self._active) or bool(self._reprefill) \
            or self.queue.depth() > 0 or bool(self._migrate_in) \
            or bool(self._parked_release) \
            or (self.kvtier is not None and self.kvtier.has_grafts())

    def run(self, max_iterations: Optional[int] = None) -> None:
        """Drive until drained (loopback/bench mode)."""
        it = 0
        while self.step():
            it += 1
            if max_iterations is not None and it >= max_iterations:
                break

    # -- background service mode (http front end / fleet replica) -----------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._dead = False

        def loop():
            try:
                while not self._stop.is_set():
                    if not self.step():
                        # drained: sleep until a submit wakes us
                        self.queue.wait_for_work(timeout=0.05)
            except BaseException as e:  # noqa: BLE001 — replica death
                # The thread dying IS the failure signal: alive() goes
                # False, heartbeats stop, /healthz turns 503 and the
                # fleet router ejects this replica. Nothing here may
                # mask that by keeping the loop running.
                self._dead = True
                logger.error(
                    "serve replica %s batcher thread died: %s",
                    self.replica_id, e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvd-serve-batcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def alive(self) -> bool:
        """The liveness signal /healthz and the fleet router consume:
        False once ``stop()`` ran or the scheduler thread died (chaos
        crash, unhandled error). A not-yet-started batcher (loopback
        ``run()`` mode) counts as alive — the caller drives it."""
        if self._stop.is_set() or self._dead:
            return False
        t = self._thread
        return t.is_alive() if t is not None else True

    def load(self) -> float:
        """The fleet router's capacity signal: waiting plus in-flight,
        with in-flight measured in the unit that actually limits this
        batcher — live rows when slotted, BLOCKS in use scaled to
        row-equivalents when paged. Two paged replicas with the same
        sequence count can differ several-fold in memory pressure (one
        long context vs many short ones); routing on blocks sends the
        next long prompt to the replica that can actually hold it."""
        if self.paged:
            per_row = max(self.executor.blocks_per_seq, 1)
            return self.queue.depth() + self.kv.pool.in_use() / per_row
        return self.queue.depth() + float(self.kv.live())

    # -- disaggregated serving: park / migrate-install ----------------------
    def parked_seq(self, rid: int) -> Optional[_Active]:
        """The parked sequence for request ``rid`` (None if unknown /
        already released). A point-in-time read; callers that go on
        to READ the row's blocks must hold a pin
        (:meth:`pin_parked`) or the TTL reaper could free — and the
        pool re-issue — those blocks mid-read."""
        with self._parked_lock:
            return self.parked.get(rid)

    def pin_parked(self, rid: int) -> Optional[_Active]:
        """Claim a read pin on ``rid``'s parked row (None if not
        parked): while any pin is held, neither release_parked nor
        the TTL reaper will free the row — the migration pack's
        device reads see stable blocks. Balance with
        :meth:`unpin_parked`."""
        with self._parked_lock:
            seq = self.parked.get(rid)
            if seq is not None:
                self._parked_pins[rid] = \
                    self._parked_pins.get(rid, 0) + 1
            return seq

    def unpin_parked(self, rid: int) -> None:
        with self._parked_lock:
            n = self._parked_pins.get(rid, 0) - 1
            if n > 0:
                self._parked_pins[rid] = n
            else:
                self._parked_pins.pop(rid, None)

    def release_parked(self, rid: int) -> None:
        """Ask the scheduler to free ``rid``'s parked row (migration
        landed or was abandoned). Endpoint-thread safe; idempotent."""
        with self._parked_lock:
            if rid in self.parked:
                self._parked_release.append(rid)
        self.queue._work.set()   # wake an idle scheduler to free it

    def _drain_parked_release(self) -> None:
        """Scheduler-thread half of release_parked, plus the TTL
        reaper: a parked row whose router died mid-orchestration must
        not hold pool blocks forever."""
        now = time.monotonic()
        with self._parked_lock:
            pinned = set(self._parked_pins)
            # a pinned row (mid-pack on the endpoint thread) is never
            # freed this iteration: releases defer to the next drain,
            # reaps re-qualify next time around
            rids = [r for r in self._parked_release
                    if r not in pinned]
            self._parked_release = [r for r in self._parked_release
                                    if r in pinned]
            reap = [rid for rid, seq in self.parked.items()
                    if now > seq.req.deadline + self.parked_grace_s
                    and rid not in rids and rid not in pinned]
            self.parked_reaped += len(reap)
            seqs = [self.parked.pop(rid) for rid in rids + reap
                    if rid in self.parked]
        for seq in seqs:
            self._free_seq(seq.slot)

    def submit_migrated(self, meta: dict,
                        blocks: List[dict]) -> dict:
        """Enqueue a migrated sequence for install (the decode-side
        receive path, serve/kv_migrate.py). ``meta`` carries the
        sequence state (prompt, emitted tokens, cache_len, sampling,
        rng_ctr, weights_version, deadline_ms); ``blocks`` is one dict
        per KV block — {"filled", "leaf_bytes", "crcs"} — already
        crc-VERIFIED by the caller. Returns the pending entry; the
        caller waits on ``entry["evt"]`` and reads
        ``entry["outcome"]``/``entry["handle"]`` — the actual install
        (capacity reservation, device writes, ledger seeding, version
        fence) runs on the scheduler thread at the top of the next
        iteration."""
        from .queue import ServeHandle
        handle = ServeHandle(int(meta.get("rid", -1)))
        entry = {"meta": dict(meta), "blocks": blocks,
                 "handle": handle, "outcome": None,
                 "evt": threading.Event()}
        with self._migrate_lock:
            self._migrate_in.append(entry)
        self.queue._work.set()   # wake an idle scheduler to install
        return entry

    def note_migrate_corrupt(self) -> None:
        """Endpoint hook: a migration payload failed its per-block crc
        on arrival (counted before any install could happen)."""
        self.migrate_corrupt_detected += 1
        self._m_migrate_corrupt.inc()

    def _install_migrated(self) -> None:
        with self._migrate_lock:
            pending, self._migrate_in = self._migrate_in, []
        for ent in pending:
            try:
                outcome = self._install_one(ent)
            except Exception as e:  # noqa: BLE001 — a torn install must
                # surface as a structured reject, never kill the
                # scheduler thread (the sender re-prefills)
                logger.error(
                    "serve replica %s: migrated install failed: %s",
                    self.replica_id, e)
                outcome = ("error", str(e)[:200])
            if outcome[0] != "installed":
                self.migrate_rejects += 1
            ent["outcome"] = outcome
            ent["evt"].set()

    def _install_one(self, ent: dict) -> tuple:
        """Install one migrated sequence: weight-version fence,
        reservation-gated capacity, device block writes, crc-ledger
        seeding, batch enrollment. Returns ("installed", None) or a
        structured ("version_mismatch"|"rejected"|"incompatible",
        detail) the endpoint acks back to the sender."""
        if not self.paged:
            return ("incompatible", "decode replica is not paged")
        meta, blocks = ent["meta"], ent["blocks"]
        # -- weight-version FENCE: migrated KV was computed under the
        # sender's version; installing it under any other version
        # would mix cache bytes across versions — the sender
        # re-prefills instead, never stale-KV tokens
        want = meta.get("weights_version")
        have = self.executor.params_version
        if want != have:
            return ("version_mismatch",
                    {"have": have, "want": want})
        cache_len = int(meta["cache_len"])
        out = [int(t) for t in meta.get("out", [])]
        max_new = int(meta["max_new_tokens"])
        remaining = max_new - len(out)
        if remaining <= 0 or cache_len >= self.executor.max_len:
            return ("incompatible", "sequence already complete")
        margin = self.spec_k + 1 if self.draft is not None else 0
        budget = min(cache_len + remaining + margin,
                     self.executor.max_len)
        bs = self.kv.block_size
        if int(meta.get("block_size", bs)) != bs:
            return ("incompatible",
                    f"block size {meta.get('block_size')} != {bs}")
        n_payload = -(-cache_len // bs)
        if len(blocks) != n_payload:
            return ("incompatible",
                    f"{len(blocks)} payload blocks for cache_len "
                    f"{cache_len} (need {n_payload})")
        need_total = self.kv.blocks_needed(budget)
        # the RESERVATION-GATED admission check local newcomers pass
        # through — a migrated install can never starve an admitted
        # sequence either
        if not self.kv.can_admit(need_total):
            return ("rejected", self.queue._retry_after_ms())
        row = self.kv.alloc_row(need_total)
        try:
            fresh = self.kv.ensure(row, cache_len)
            assert len(fresh) == n_payload
            self.executor.install_kv_blocks(
                fresh, [b["leaf_bytes"] for b in blocks],
                [int(b["filled"]) for b in blocks])
            if self.kv_crc:
                # seed the per-block ledger from the VERIFIED bytes
                # so verify-on-read covers the migrated prefix
                # exactly like locally written KV
                for blk, b in zip(fresh, blocks):
                    self.kv.pool.crc_reset(
                        blk, b["leaf_bytes"], int(b["filled"]))
        except ValueError as e:
            self.kv.free_row(row)
            return ("incompatible", str(e)[:200])
        # re-check the fence: a hot swap may have landed between the
        # check above and the last device write (swap_params only
        # fences individual steps/writes, not this whole span)
        if self.executor.params_version != want:
            self.kv.free_row(row)
            return ("version_mismatch",
                    {"have": self.executor.params_version,
                     "want": want})
        now = time.monotonic()
        req = ServeRequest(
            rid=int(meta.get("rid", -1)),
            prompt=[int(t) for t in meta["prompt"]],
            max_new_tokens=max_new,
            deadline=now + float(meta.get("deadline_ms", 30000.0))
            / 1000.0,
            submitted_at=now, handle=ent["handle"],
            temperature=float(meta.get("temperature", 0.0)),
            top_p=float(meta.get("top_p", 1.0)),
            seed=int(meta.get("seed", 0)),
            trace=meta.get("trace"))
        seq = _Active(req=req, slot=row, out=out,
                      cache_len=cache_len,
                      rng_ctr=int(meta.get("rng_ctr", 1)),
                      t_first=now)
        self.kv.lengths[row] = cache_len
        self._active[row] = seq
        self.migrations_in += 1
        return ("installed", None)

    # -- internals -----------------------------------------------------------
    def _stats(self) -> dict:
        occ = self.kv.occupancy()
        self._m_occupancy.set(occ)
        if self.paged:
            self._m_blocks.set(self.kv.pool.in_use())
        return {"queue_depth": self.queue.depth(),
                "occupancy": round(occ, 3),
                "shed": self.queue.shed_count}

    # -- on-device sampling row data -----------------------------------------
    def _sample_args(self, rows, ctr_offset: int = 0) -> dict:
        """Per-row sampling arrays for the jitted step: each active
        row's request temperature / top-p / seed plus its draw counter
        (``rng_ctr + ctr_offset``). Rows not listed stay at the greedy
        defaults (temperature 0) and are masked out anyway."""
        B = self.executor.max_batch
        s = {"temperature": np.zeros(B, np.float32),
             "top_p": np.ones(B, np.float32),
             "seed": np.zeros(B, np.uint32),
             "ctr": np.zeros(B, np.int32)}
        for slot in rows:
            seq = self._active[slot]
            req = seq.req
            s["temperature"][slot] = getattr(req, "temperature", 0.0)
            s["top_p"][slot] = getattr(req, "top_p", 1.0)
            s["seed"][slot] = int(getattr(req, "seed", 0)) & 0xFFFFFFFF
            s["ctr"][slot] = seq.rng_ctr + ctr_offset
        return s

    # -- crc plumbing (slot- or block-granular) ------------------------------
    def _crc_write(self, slot: int, lo: int, hi: int) -> None:
        """Fold cache positions ``[lo, hi)`` just written for ``slot``
        into the crc ledger. Paged: per-BLOCK ledger entries; an
        overwrite below a block's high-water mark (speculative
        rollback) recomputes that block's crc from a fresh readback —
        streaming crc32 cannot be truncated."""
        if not self.kv_crc or hi <= lo:
            return
        if not self.paged:
            filled = self.kv.crc_filled(slot)
            if lo == filled:
                self.kv.crc_update(
                    slot, self.executor.kv_slot_bytes(slot, lo, hi), hi)
            else:
                # speculative rollback overwrote below the high-water
                # mark: the append-only stream breaks — recompute the
                # slot's ledger from a full re-read
                new_filled = max(filled, hi)
                self.kv.crc_reset(
                    slot,
                    self.executor.kv_slot_bytes(slot, 0, new_filled),
                    new_filled)
            return
        bs = self.kv.block_size
        pool = self.kv.pool
        blocks = self.kv.blocks[slot]
        for bi in range(lo // bs, (hi - 1) // bs + 1):
            blk = blocks[bi]
            blo = max(lo - bi * bs, 0)
            bhi = min(hi - bi * bs, bs)
            filled = pool.crc_filled(blk)
            if blo == filled:
                pool.crc_stream(
                    blk, self.executor.kv_block_bytes(blk, blo, bhi),
                    bhi)
            else:
                new_filled = max(filled, bhi)
                pool.crc_reset(
                    blk,
                    self.executor.kv_block_bytes(blk, 0, new_filled),
                    new_filled)

    def _kv_verify(self, seq: _Active) -> bool:
        """Verify-on-read: re-read the sequence's whole valid prefix
        and check it against the write-side crc ledger. Runs only at
        retirement (and only with kv_crc on), so a request's tokens are
        NEVER released to a client from cache bytes that changed behind
        the scheduler's back. Paged sequences verify per BLOCK — shared
        prefix blocks included, under the pool-wide ledger."""
        if not self.kv_crc or seq.cache_len <= 0:
            return True
        if not self.paged:
            # the ledger's high-water mark can exceed cache_len (a
            # verify step's rejected tail is written but not accepted);
            # verify exactly the covered prefix
            hi = self.kv.crc_filled(seq.slot) or seq.cache_len
            raw = self.executor.kv_slot_bytes(seq.slot, 0, hi)
            return self.kv.crc_check(seq.slot, raw)
        pool = self.kv.pool
        for blk in self.kv.blocks[seq.slot]:
            filled = pool.crc_filled(blk)
            if filled == 0:
                continue
            if not pool.crc_check(
                    blk, self.executor.kv_block_bytes(blk, 0, filled)):
                return False
        return True

    def _free_seq(self, slot: int) -> None:
        """Release a retiring sequence's KV capacity — its slot, or its
        whole block table (decrementing shared-prefix refcounts) — in
        the SAME iteration it retires."""
        if self.paged:
            self.kv.free_row(slot)
        else:
            self.kv.free(slot)

    def _retire(self) -> None:
        now = time.monotonic()
        for slot in list(self._active):
            seq = self._active[slot]
            req = seq.req
            done_ok = (len(seq.out) >= req.max_new_tokens
                       or (self.eos_id is not None and seq.out
                           and seq.out[-1] == self.eos_id)
                       or seq.cache_len >= self.executor.max_len)
            expired = req.expired(now)
            if not (done_ok or expired):
                continue
            ms = (now - req.submitted_at) * 1000.0
            if not self._kv_verify(seq):
                # corrupted KV: the generated tokens are untrusted and
                # must not reach the client. Re-prefill from the prompt
                # (a fresh slot, a clean generation) while the deadline
                # allows; otherwise fail cleanly.
                self.kv_corruptions_detected += 1
                self._m_kv_corrupt.inc()
                logger.warning(
                    "serve replica %s: KV %s %d failed crc "
                    "verify-on-read (request %d) — %s",
                    self.replica_id,
                    "row" if self.paged else "slot", slot, req.rid,
                    "re-prefilling" if self.on_kv_corrupt == "reprefill"
                    and not expired else "failing the request")
                if self.prefix is not None:
                    # the corrupt block may BE a cached prefix run; a
                    # re-prefill matching it would corrupt again
                    self.prefix.flush()
                self._free_seq(slot)
                del self._active[slot]
                if self.on_kv_corrupt == "reprefill" and not expired:
                    self.kv_reprefills += 1
                    self._reprefill.append(req)
                else:
                    req.handle._resolve(
                        [], "error", latency_ms=ms, error="kv_corrupt")
                continue
            if req.trace is not None and seq.t_first is not None \
                    and not (req.hold_kv and self.paged):
                base = time.time() - time.monotonic()
                _trace_recorder().record(
                    req.trace, "decode",
                    seq.t_first + base, now + base,
                    rid=req.rid, tokens=len(seq.out))
            if expired and not done_ok:
                self.queue.expired_count += 1
                req.handle._resolve(seq.out, "expired", latency_ms=ms)
            elif req.hold_kv and self.paged:
                # disaggregated prefill: PARK the verified sequence —
                # row and blocks stay allocated so the endpoint can
                # migrate them (serve/kv_migrate.py pack_parked).
                # Parked BEFORE the handle resolves: the endpoint's
                # migrate op keys off the resolution and must find the
                # entry already there.
                seq.parked_at = now
                with self._parked_lock:
                    self.parked[req.rid] = seq
                del self._active[slot]
                req.handle._resolve(seq.out, "ok", latency_ms=ms)
                self.queue.note_service_ms(ms)
                continue
            else:
                req.handle._resolve(seq.out, "ok", latency_ms=ms)
                self.queue.note_service_ms(ms)
            self._free_seq(slot)
            del self._active[slot]

    # -- admission -----------------------------------------------------------
    def _seq_token_budget(self, req: ServeRequest) -> int:
        """Worst-case cache positions this request can touch: prompt +
        generation budget + the speculative write-ahead margin."""
        margin = self.spec_k + 1 if self.draft is not None else 0
        return min(len(req.prompt) + req.max_new_tokens + margin,
                   self.executor.max_len)

    def _plan(self, req: ServeRequest) -> dict:
        """Paged admission plan: prefix match (references pinned) plus
        the fresh-block budget the admission gate charges."""
        if self.prefix is not None:
            full, partial, m = self.prefix.match(req.prompt)
        else:
            full, partial, m = [], None, 0
        total = self.kv.blocks_needed(self._seq_token_budget(req))
        # the partially matched block still costs a fresh block (its
        # copy-on-write copy), so only FULL shared blocks are free
        return {"full": full, "partial": partial, "m": m,
                "new_blocks": max(total - len(full), 0)}

    def _release_plan(self, plan: dict) -> None:
        if self.prefix is None:
            return
        self.prefix.release(plan["full"])
        if plan["partial"] is not None:
            self.prefix.release([plan["partial"][0]])

    def _admit(self) -> List[_Active]:
        if not self.paged:
            return self._admit_slotted()
        free_rows = self.kv.num_rows - self.kv.live()
        if free_rows <= 0:
            return []
        admitted: List[_Active] = []
        # ONE evictable-tree walk per admission wave (the live hook is
        # O(cached blocks) and fits() runs under the queue lock); the
        # wave's own acceptances are charged against the snapshot:
        # `planned` for reservations that land at alloc_row, `pinned`
        # for matched prefix blocks whose new reference may have made
        # a previously-evictable run un-evictable. Each candidate is
        # charged for its OWN pins too, not just its predecessors' —
        # a request whose match pins the last evictable runs must not
        # be admitted against them (free + evictable - reserved would
        # go negative the moment the pins land, and a RESERVED append
        # of an already-running sequence would find the pool dry).
        # All three charges only ever UNDER-admit — the reservation
        # invariant cannot be pierced.
        ev0 = (self.prefix.evictable_blocks()
               if self.prefix is not None else 0)
        planned = 0
        pinned = 0

        def pins_of(plan: dict) -> int:
            return len(plan["full"]) + \
                (1 if plan["partial"] is not None else 0)

        def admit_one(req: ServeRequest, plan: dict) -> None:
            row = self.kv.alloc_row(plan["new_blocks"])
            a = _Active(req=req, slot=row, plan=plan)
            admitted.append(a)
            self._active[row] = a

        # corrupted-and-reset sequences re-enter ahead of the queue
        # (they already waited their turn once)
        while self._reprefill and len(admitted) < free_rows:
            plan = self._plan(self._reprefill[0])
            if not self.kv.can_admit(
                    plan["new_blocks"] + planned,
                    max(ev0 - pinned - pins_of(plan), 0)):
                self._release_plan(plan)
                # ahead-of-queue means AHEAD: admitting smaller queue
                # requests past a blocked reprefill would let them eat
                # the blocks it is waiting for (priority inversion —
                # it could starve to its deadline while parked here)
                return admitted
            # no `planned` charge here: admit_one's alloc_row reserves
            # immediately, so reserved_total already carries it
            pinned += pins_of(plan)
            admit_one(self._reprefill.pop(0), plan)

        plans: Dict[int, dict] = {}

        def fits(req: ServeRequest) -> bool:
            nonlocal planned, pinned
            plan = self._plan(req)
            if self.kv.can_admit(plan["new_blocks"] + planned,
                                 max(ev0 - pinned - pins_of(plan), 0)):
                plans[req.rid] = plan
                planned += plan["new_blocks"]
                pinned += pins_of(plan)
                return True
            self._release_plan(plan)
            return False

        for req in self.queue.pop_fitting(free_rows - len(admitted),
                                          fits):
            admit_one(req, plans[req.rid])
        return admitted

    def _admit_slotted(self) -> List[_Active]:
        free = self.kv.num_slots - self.kv.live()
        if free <= 0:
            return []
        admitted: List[_Active] = []
        while self._reprefill and len(admitted) < free:
            req = self._reprefill.pop(0)
            slot = self.kv.alloc()
            admitted.append(_Active(req=req, slot=slot))
            self._active[slot] = admitted[-1]
        for req in self.queue.pop(free - len(admitted)):
            slot = self.kv.alloc()  # free>=len(pop) => never None
            admitted.append(_Active(req=req, slot=slot))
            self._active[slot] = admitted[-1]
        return admitted

    def _bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise AssertionError(
            f"prompt of {length} passed admission but fits no bucket "
            f"{self.buckets}")  # queue.max_prompt_len makes this unreachable

    # -- prefill -------------------------------------------------------------
    def _prefill(self, admitted: List[_Active]) -> None:
        B = self.executor.max_batch
        t_p0 = time.monotonic()   # queue_wait ends / prefill begins
        hit_rows: List[_Active] = []
        if self.paged:
            # materialize each admission plan: shared full blocks join
            # the table by reference; a mid-block partial match is
            # copy-on-written into a fresh block the suffix then
            # overwrites from its divergence point
            for a in admitted:
                plan, row = a.plan, a.slot
                for blk in plan["full"]:
                    self.kv.attach_shared(row, blk)
                if plan["partial"] is not None:
                    src, _j = plan["partial"]
                    dst = self.kv.append_block(row)
                    self.executor.copy_kv_block(src, dst)
                    self.kv.pool.crc_clone(src, dst)
                    self.prefix.release([src])   # drop the CoW pin
                a.prefix_tokens = plan["m"]
                a.plan = None
                if self.prefix is not None:
                    self.prefix.note_lookup(a.prefix_tokens)
                if a.prefix_tokens:
                    hit_rows.append(a)
                self.kv.ensure(row, len(a.req.prompt))
        # ONE packed prefill at the smallest bucket fitting the longest
        # SUFFIX (the unmatched prompt tail; the whole prompt when the
        # prefix cache missed or is off)
        bucket = self._bucket_for(
            max(len(a.req.prompt) - a.prefix_tokens for a in admitted))
        tokens = np.zeros((B, bucket), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        last_idx = np.zeros(B, np.int32)
        for a in admitted:
            m = a.prefix_tokens
            suffix = a.req.prompt[m:]
            tokens[a.slot, :len(suffix)] = suffix
            positions[a.slot] = m
            mask[a.slot] = True
            last_idx[a.slot] = len(suffix) - 1
        expected_v = self._prefix_version
        nxt = self.executor.step(
            tokens, positions, mask, last_idx, kind="prefill",
            stats=self._stats(),
            sample=self._sample_args([a.slot for a in admitted]),
            block_tables=self.kv.table() if self.paged else None)
        if hit_rows and self.executor.last_step_version != expected_v:
            # a weight swap landed between the prefix match and this
            # prefill: the hit rows mixed old-version cached KV with
            # new-version compute. Tear them down and re-prefill from
            # scratch (the flush at the next step top drops the stale
            # cache); miss rows ran entirely under one version and
            # stand.
            logger.warning(
                "serve replica %s: weight swap landed mid-prefill — "
                "re-prefilling %d prefix-hit sequences on version %s",
                self.replica_id, len(hit_rows),
                self.executor.params_version)
            self._prefix_flush.set()
            for a in hit_rows:
                self._free_seq(a.slot)
                del self._active[a.slot]
                self._reprefill.append(a.req)
            admitted = [a for a in admitted if a not in hit_rows]
        t_first = time.monotonic()
        # spans are wall-clock (cross-process merge); map the
        # scheduler's monotonic stamps through one base per batch
        base = time.time() - time.monotonic()
        rec = _trace_recorder()
        for a in admitted:
            self._m_ttft.observe(
                (t_first - a.req.submitted_at) * 1000.0)
            a.t_first = t_first
            if a.req.trace is not None:
                rec.record(a.req.trace, "queue_wait",
                           a.req.submitted_at + base, t_p0 + base)
                rec.record(a.req.trace, "prefill",
                           t_p0 + base, t_first + base,
                           rid=a.req.rid)
            n = len(a.req.prompt)
            a.cache_len = n
            a.params_version = self.executor.last_step_version
            a.rng_ctr = 1   # the prefill's first token consumed draw 0
            # the prompt is fully cached but only [0, n) is valid; the
            # first generated token is the prompt's last-logit argmax
            a.out.append(int(nxt[a.slot]))
            self.kv.lengths[a.slot] = n
            # crc-on-write covers exactly the written span [m, n) (pad
            # positions past n are unreachable and unverified; shared
            # prefix blocks carry their writer's ledger already)
            self._crc_write(a.slot, a.prefix_tokens, n)
            if self.paged and self.prefix is not None:
                # publish this prompt's FULL blocks for future sharing
                self.prefix.insert(a.req.prompt,
                                   self.kv.blocks[a.slot])
                if self.kvtier is not None:
                    # fleet index event: this replica now holds the run
                    self.kvtier.note_insert(a.req.prompt,
                                            a.params_version)
        if self.draft is not None and admitted:
            self._draft_prefill(admitted)

    def _draft_prefill(self, admitted: List[_Active]) -> None:
        """Ingest each admitted prompt into the DRAFT model's cache
        (full prompt — the drafter has no prefix cache; it is small,
        that is the point). Its last-logit output is discarded: the
        first draft of the next iteration feeds the target's first
        emitted token."""
        B = self.draft.max_batch
        bucket = self._bucket_for(
            max(len(a.req.prompt) for a in admitted))
        tokens = np.zeros((B, bucket), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        last_idx = np.zeros(B, np.int32)
        for a in admitted:
            n = len(a.req.prompt)
            tokens[a.slot, :n] = a.req.prompt
            mask[a.slot] = True
            last_idx[a.slot] = n - 1
        self.draft.step(tokens, positions, mask, last_idx,
                        kind="prefill")
        for a in admitted:
            a.draft_len = len(a.req.prompt)

    # -- decode --------------------------------------------------------------
    def _decode(self) -> None:
        if self.draft is None:
            self._decode_plain(list(self._active))
            return
        spec_rows, plain_rows = [], []
        for slot, seq in self._active.items():
            # speculative write-ahead must stay inside both contexts;
            # boundary sequences fall back to plain decode
            if seq.cache_len + self.spec_k + 1 <= self.executor.max_len \
                    and seq.draft_len + self.spec_k <= self.draft.max_len:
                spec_rows.append(slot)
            else:
                plain_rows.append(slot)
        if spec_rows:
            self._decode_spec(spec_rows)
        if plain_rows:
            self._decode_plain(plain_rows)

    def _decode_plain(self, rows: List[int]) -> None:
        B = self.executor.max_batch
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        last_idx = np.zeros(B, np.int32)
        for slot in rows:
            seq = self._active[slot]
            # the newest token is not yet in the cache: this step writes
            # it at position cache_len, attends, and samples the next
            tokens[slot, 0] = seq.out[-1]
            positions[slot] = seq.cache_len
            mask[slot] = True
            if self.paged:
                self.kv.ensure(slot, seq.cache_len + 1)
        nxt = self.executor.step(
            tokens, positions, mask, last_idx, kind="decode",
            stats=self._stats(), sample=self._sample_args(rows),
            block_tables=self.kv.table() if self.paged else None)
        self.gen_steps += len(rows)
        for slot in rows:
            seq = self._active[slot]
            # this step wrote one K/V entry at the old cache_len
            self._crc_write(slot, seq.cache_len, seq.cache_len + 1)
            seq.cache_len += 1
            self.kv.lengths[slot] = seq.cache_len
            seq.out.append(int(nxt[slot]))
            seq.rng_ctr += 1
            self.gen_tokens += 1

    def _decode_spec(self, rows: List[int]) -> None:
        """One speculative iteration: k draft proposals per row, ONE
        fused target verify step, on-device accept + rollback.

        The accept rule runs INSIDE the verify step
        (ops/pallas_paged.py speculative_accept): at temperature 0 it
        is the argmax rule — draft token i+1 is emitted iff it equals
        the target's argmax at position i, the first disagreement
        emits the target's own argmax — which keeps the emitted stream
        BIT-IDENTICAL to target-only greedy decode, just produced
        1..k+1 tokens per target step. Sampled rows instead apply
        rejection sampling against each proposal's draft distribution
        (kept on device from the draft steps), so the emitted stream
        is distribution-identical to target-only sampling. Rejected
        draft positions were written into the cache by the verify
        step; they sit beyond the new cache_len, unreachable by the
        positional validity mask, and are overwritten by the next
        iteration — rollback is bookkeeping, not data movement.
        """
        import jax.numpy as jnp

        k = self.spec_k
        B = self.executor.max_batch
        known = {slot: self._active[slot].req.prompt
                 + self._active[slot].out for slot in rows}
        # tokens the drafter has NOT validly ingested yet; feeding them
        # (forced) re-syncs its cache after a full-accept iteration
        # left it one token behind
        forced = {slot: known[slot][self._active[slot].draft_len:]
                  for slot in rows}
        #: proposals of row r start at draft step len(forced_r) - 1
        #: (the step that consumes the last forced token emits the
        #: first proposal) — what aligns each proposal with the step
        #: whose distribution it was drawn from
        first_prop = {slot: len(forced[slot]) - 1 for slot in rows}
        drafts: Dict[int, List[int]] = {slot: [] for slot in rows}
        fed: Dict[int, List[int]] = {slot: [] for slot in rows}
        prev: Dict[int, int] = {}
        step_probs = []
        for i in range(k):
            tokens = np.zeros((B, 1), np.int32)
            positions = np.zeros(B, np.int32)
            mask = np.zeros(B, bool)
            zero = np.zeros(B, np.int32)
            for slot in rows:
                seq = self._active[slot]
                if forced[slot]:
                    tok = forced[slot][0]
                else:
                    tok = prev[slot]
                tokens[slot, 0] = tok
                positions[slot] = seq.draft_len + i
                mask[slot] = True
            out, probs = self.draft.step(
                tokens, positions, mask, zero, kind="decode",
                sample=self._sample_args(rows, ctr_offset=i))
            step_probs.append(probs)
            for slot in rows:
                o = int(out[slot])
                if forced[slot]:
                    fed[slot].append(forced[slot].pop(0))
                    if not forced[slot]:
                        drafts[slot].append(o)   # drafted past known
                else:
                    fed[slot].append(prev[slot])
                    drafts[slot].append(o)
                prev[slot] = o
        # ONE batched verify: token 0 is each row's last emitted token
        # (its K/V enters the cache here, same as plain decode), tokens
        # 1..n_d are the drafts; the target scores every position and
        # applies the accept rule on device against each proposal's
        # draft distribution (gathered per row: proposal j of row r
        # came from draft step first_prop[r] + j)
        tokens = np.zeros((B, k + 1), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        zero = np.zeros(B, np.int32)
        n_draft = np.zeros(B, np.int32)
        offs = np.zeros(B, np.int32)
        for slot in rows:
            seq = self._active[slot]
            row_toks = [known[slot][-1]] + drafts[slot][:k]
            tokens[slot, :len(row_toks)] = row_toks
            positions[slot] = seq.cache_len
            mask[slot] = True
            n_draft[slot] = len(drafts[slot])
            offs[slot] = max(first_prop[slot], 0)
            if self.paged:
                self.kv.ensure(slot, seq.cache_len + k + 1)
        stacked = jnp.stack(step_probs)                    # [k, B, V]
        idx = np.clip(offs[:, None] + np.arange(k)[None, :], 0, k - 1)
        dprobs = stacked[jnp.asarray(idx),
                         jnp.arange(B)[:, None]]           # [B, k, V]
        emitted_all, n_acc = self.executor.step(
            tokens, positions, mask, zero, kind="verify",
            stats=self._stats(), sample=self._sample_args(rows),
            draft_probs=dprobs, n_draft=n_draft,
            block_tables=self.kv.table() if self.paged else None)
        self.gen_steps += len(rows)
        for slot in rows:
            seq = self._active[slot]
            n_d = len(drafts[slot])
            a = int(n_acc[slot])
            if n_d:
                self._m_accept.observe(a / n_d)
            emitted = [int(t) for t in emitted_all[slot, :a + 1]]
            remaining = seq.req.max_new_tokens - len(seq.out)
            emitted = emitted[:remaining]
            if self.eos_id is not None and self.eos_id in emitted:
                emitted = emitted[:emitted.index(self.eos_id) + 1]
            # the verify step wrote k+1 cache positions regardless;
            # crc them all — a later overwrite of the rejected tail
            # recomputes those blocks' ledgers
            self._crc_write(slot, seq.cache_len, seq.cache_len + k + 1)
            seq.out.extend(emitted)
            seq.cache_len += len(emitted)
            self.kv.lengths[slot] = seq.cache_len
            self.gen_tokens += len(emitted)
            # every speculative iteration consumes a FIXED draw budget
            # (k proposal draws + the verify's per-position draws), so
            # the stream stays deterministic however many were accepted
            seq.rng_ctr += k + 1
            # drafter rollback: its valid prefix is however far the fed
            # token stream still agrees with the true sequence
            nk = known[slot] + emitted
            base = seq.draft_len
            p = 0
            while p < len(fed[slot]) and base + p < len(nk) \
                    and fed[slot][p] == nk[base + p]:
                p += 1
            seq.draft_len = base + p
