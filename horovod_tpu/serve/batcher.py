"""Continuous batcher: iteration-level scheduling over fixed shapes.

The Orca insight, TPU-flavored: requests join and leave the running
batch *between decode iterations*, never mid-program, and every program
the scheduler launches has one of a small closed set of shapes —
``[max_batch, 1]`` for decode and ``[max_batch, bucket]`` for each
configured prefill bucket (HOROVOD_SERVE_BUCKETS) — so jit compiles
each exactly once and batch churn can never trigger a recompile.

One `step()` is one scheduling iteration:

1. **retire** — finished (max_new_tokens / EOS / context-full) and
   deadline-expired sequences resolve their handles and free their KV
   slot (serve/kv_cache.py `SlotKVCache`).
2. **admit** — pop queued requests into free slots; newly admitted
   prompts are packed into ONE prefill call at the smallest bucket that
   fits the longest of them (rows right-padded, per-row `last_idx`
   picks each prompt's true last logit). Rows owned by already-running
   sequences ride along with `update_mask=False`, so their cache state
   is untouched.
3. **decode** — one `[max_batch, 1]` step for every live sequence; each
   gets exactly one new token (the iteration-granularity fairness that
   keeps p50 flat under mixed lengths).

Prefill counts as producing the first generated token (its last-logit
argmax), so a request admitted in iteration k has a token by k — no
separate prefill queue.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..chaos import inject as _chaos
from ..obs import metrics as obs_metrics
from .kv_cache import SlotKVCache
from .queue import AdmissionQueue, ServeRequest

logger = logging.getLogger("horovod_tpu")


class ReplicaDead(RuntimeError):
    """A chaos ``serve.step`` crash: this replica's scheduler thread
    dies here — the in-process analog of losing the replica's host.
    Its heartbeats stop, which is what the fleet router's accrual
    tracker detects (serve/fleet.py)."""


@dataclass
class _Active:
    req: ServeRequest
    slot: int
    #: generated tokens so far (first comes from the prefill step)
    out: List[int] = field(default_factory=list)
    #: tokens written into the KV cache (prompt + confirmed generations)
    cache_len: int = 0


class ContinuousBatcher:
    """Schedules an `AdmissionQueue` onto a `ShardedExecutor`."""

    def __init__(self, executor, queue: AdmissionQueue, *,
                 buckets: Sequence[int] = (32, 128, 512),
                 eos_id: Optional[int] = None,
                 replica_id: Optional[int] = None,
                 kv_crc: Optional[bool] = None,
                 on_kv_corrupt: str = "reprefill"):
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints; got {buckets}")
        if buckets[-1] > executor.max_len:
            raise ValueError(
                f"largest prefill bucket {buckets[-1]} exceeds the model "
                f"context {executor.max_len}")
        if on_kv_corrupt not in ("reprefill", "error"):
            raise ValueError(
                f"on_kv_corrupt must be 'reprefill' or 'error'; "
                f"got {on_kv_corrupt!r}")
        self.executor = executor
        self.queue = queue
        self.buckets = buckets
        self.eos_id = eos_id
        #: fleet identity (None = standalone): labels the metric
        #: series and addresses chaos serve.step / serve.kv faults
        self.replica_id = replica_id
        #: per-slot crc-on-write / verify-on-read (HOROVOD_SERVE_KV_CRC
        #: or explicit): every cache write is folded into the slot's
        #: crc ledger and every retiring request's valid prefix is
        #: re-read and verified BEFORE its tokens can reach a client —
        #: a corrupted slot either re-prefills from the prompt or fails
        #: cleanly ("error"/kv_corrupt), never returns garbage. Costs
        #: one device->host readback of the written slice per step plus
        #: one full-prefix readback per retiring request; an integrity
        #: option for chaos runs and paranoid deployments, off by
        #: default.
        if kv_crc is None:
            from ..core.config import Config
            kv_crc = Config.from_env().serve_kv_crc
        self.kv_crc = bool(kv_crc)
        self.on_kv_corrupt = on_kv_corrupt
        self.kv_corruptions_detected = 0
        self.kv_corruptions_injected = 0
        self.kv_reprefills = 0
        #: a fired serve.kv corrupt waiting for a written slot, (slot,)
        self._pending_corrupt = None
        # unservable prompts get shed at submit time, not discovered
        # holding a decode slot
        if queue.max_prompt_len is None or \
                queue.max_prompt_len > buckets[-1]:
            queue.max_prompt_len = buckets[-1]
        self.kv = SlotKVCache(executor.max_batch, executor.max_len)
        self._active: Dict[int, _Active] = {}   # slot -> sequence
        self._reprefill: List[ServeRequest] = []
        self.iterations = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dead = False
        #: fleet liveness hook: called once per scheduling iteration
        #: (busy or idle) on the batcher thread; a crashed/stuck
        #: replica stops calling it, which is the router's signal
        self.heartbeat: Optional[Callable[[], None]] = None
        #: router-visible drain flag (mirrored into /healthz)
        self.draining = False
        # -- metrics: time-to-first-token (admission wait + prefill) and
        # live KV occupancy, next to the queue's depth/shed series.
        # Standalone batchers claim fresh; fleet replicas use labeled
        # children (same discipline as AdmissionQueue/ShardedExecutor).
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        R = obs_metrics.get_registry()
        if replica_id is None:
            R.unregister("hvd_serve_ttft_ms")
            R.unregister("hvd_serve_kv_occupancy")
        self._m_ttft = R.histogram(
            "hvd_serve_ttft_ms",
            "time to first generated token (submit -> prefill), ms",
            rl or None)
        self._m_occupancy = R.gauge(
            "hvd_serve_kv_occupancy", "fraction of KV slots in use",
            rl or None)
        self._m_kv_corrupt = R.counter(
            "hvd_serve_kv_corruptions_total",
            "KV slots whose verify-on-read crc failed (corruption "
            "caught before reaching a client)", rl or None)
        #: optional weight-stream subscriber (redist/stream.py): polled
        #: between scheduling iterations, rate-limited so an idle or
        #: not-yet-published channel cannot stall the decode loop
        self._weights = None
        self._weights_interval = 0.25
        self._weights_next_poll = 0.0

    # -- hot weight streaming ------------------------------------------------
    def attach_weights(self, subscriber,
                       min_interval_s: float = 0.25) -> None:
        """Attach a ``WeightSubscriber``: the scheduler polls it
        between iterations — at most every ``min_interval_s`` seconds,
        because a poll against a channel with no published head blocks
        for the subscriber's KV timeout (~50 ms), which must not be
        paid per ~ms decode iteration — and adopts newer param
        versions via ``executor.swap_params`` (the executor's step
        lock is the no-mid-step fence). A transient stream failure
        logs and keeps serving on the current weights; it never takes
        the fleet down."""
        self._weights = subscriber
        self._weights_interval = float(min_interval_s)
        self._weights_next_poll = 0.0       # first step polls
        self._weights_thread = None

    def _maybe_swap_weights(self) -> None:
        """Kick (never join) a background adoption: the KV fetch, crc
        verify, assembly and device placement of a multi-GB tree must
        not run inline on the decode scheduling thread — only the final
        pointer swap is fenced, inside ``swap_params``'s step lock, so
        in-flight requests pay at most one step of swap latency, never
        the full adoption."""
        if self._weights is None:
            return
        now = time.monotonic()
        if now < self._weights_next_poll:
            return
        t = self._weights_thread
        if t is not None and t.is_alive():
            return                        # previous adoption in flight
        self._weights_next_poll = now + self._weights_interval

        def adopt():
            try:
                got = self._weights.poll()
                if got is not None:
                    version, tree = got
                    self.executor.swap_params(tree, version=version)
            except Exception as e:  # noqa: BLE001 — serve on stale
                import logging
                logging.getLogger("horovod_tpu").warning(
                    "weight stream poll failed (serving continues on "
                    "version %s): %s", self.executor.params_version, e)

        self._weights_thread = threading.Thread(
            target=adopt, daemon=True, name="hvd-serve-weights")
        self._weights_thread.start()

    # -- shape warmup --------------------------------------------------------
    def warmup(self) -> None:
        """Compile every shape the scheduler can launch (decode + one
        prefill per bucket) with all-False masks — state untouched. Run
        once at startup so overload/churn never meets a compile."""
        B = self.executor.max_batch
        zero = np.zeros(B, np.int32)
        off = np.zeros(B, bool)
        for b in self.buckets:
            self.executor.step(np.zeros((B, b), np.int32), zero, off, zero,
                               kind="prefill")
        self.executor.step(np.zeros((B, 1), np.int32), zero, off, zero,
                           kind="decode")

    # -- chaos guards (one attribute read when disarmed) ---------------------
    def _fire_step_chaos(self) -> None:
        """``serve.step`` site: crash kills THIS replica (the scheduler
        thread dies and heartbeats stop — the router's problem from
        here); delay/slow_rank sleep inside the injector, stalling the
        replica mid-decode exactly like an overloaded host."""
        if _chaos._INJ is None:
            return
        f = _chaos.fire("serve.step", peer=self.replica_id,
                        step=self.iterations)
        if f is not None and f.kind == "crash":
            raise ReplicaDead(
                f"chaos: replica {self.replica_id} crashed mid-decode "
                f"(iteration {self.iterations})")

    def _fire_kv_chaos(self) -> None:
        """``serve.kv`` site: corrupt flips a real bit inside a live
        slot's device cache prefix — detection must come from the crc
        ledger, nothing else knows. A corrupt fired on an iteration
        with no written slot is DEFERRED to the next one that has one,
        so an exact-``at`` address always lands exactly one flip."""
        if _chaos._INJ is None and self._pending_corrupt is None:
            return
        if _chaos._INJ is not None:
            f = _chaos.fire("serve.kv", peer=self.replica_id,
                            step=self.iterations)
            if f is not None and f.kind == "corrupt" \
                    and self._pending_corrupt is None:
                self._pending_corrupt = (f.slot,)
        if self._pending_corrupt is not None and self._active:
            want = self._pending_corrupt[0]
            slot = want if (want is not None and want in self._active) \
                else min(self._active)
            length = self._active[slot].cache_len
            if length > 0:
                self._pending_corrupt = None
                self.executor.corrupt_kv_slot(slot, int(length))
                self.kv_corruptions_injected += 1

    # -- one scheduling iteration -------------------------------------------
    def step(self) -> bool:
        """Run one retire/admit/prefill/decode iteration; returns True
        while there is (or may be) work in flight."""
        hb = self.heartbeat
        if hb is not None:
            hb()
        self._fire_step_chaos()
        self._maybe_swap_weights()
        # expired-but-still-queued requests get their structured
        # deadline completion NOW, even when every slot is busy —
        # within one iteration, not at slot-drain time
        self.queue.reap_expired()
        self._retire()
        admitted = self._admit()
        if admitted:
            self._prefill(admitted)
            self._retire()  # a 1-token request finishes at prefill
        if self._active:
            self._decode()
        # evaluated EVERY iteration, busy or idle: the iteration counter
        # below ticks regardless, so an exact-'at' corrupt landing while
        # the replica is idle must still be captured (and deferred to
        # the next written slot) — inside the busy branch the counter
        # would walk past the address without fire() ever seeing it
        self._fire_kv_chaos()
        if self._active:
            self._retire()
        self.iterations += 1
        return bool(self._active) or bool(self._reprefill) \
            or self.queue.depth() > 0

    def run(self, max_iterations: Optional[int] = None) -> None:
        """Drive until drained (loopback/bench mode)."""
        it = 0
        while self.step():
            it += 1
            if max_iterations is not None and it >= max_iterations:
                break

    # -- background service mode (http front end / fleet replica) -----------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._dead = False

        def loop():
            try:
                while not self._stop.is_set():
                    if not self.step():
                        # drained: sleep until a submit wakes us
                        self.queue.wait_for_work(timeout=0.05)
            except BaseException as e:  # noqa: BLE001 — replica death
                # The thread dying IS the failure signal: alive() goes
                # False, heartbeats stop, /healthz turns 503 and the
                # fleet router ejects this replica. Nothing here may
                # mask that by keeping the loop running.
                self._dead = True
                logger.error(
                    "serve replica %s batcher thread died: %s",
                    self.replica_id, e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvd-serve-batcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def alive(self) -> bool:
        """The liveness signal /healthz and the fleet router consume:
        False once ``stop()`` ran or the scheduler thread died (chaos
        crash, unhandled error). A not-yet-started batcher (loopback
        ``run()`` mode) counts as alive — the caller drives it."""
        if self._stop.is_set() or self._dead:
            return False
        t = self._thread
        return t.is_alive() if t is not None else True

    # -- internals -----------------------------------------------------------
    def _stats(self) -> dict:
        occ = self.kv.occupancy()
        self._m_occupancy.set(occ)
        return {"queue_depth": self.queue.depth(),
                "occupancy": round(occ, 3),
                "shed": self.queue.shed_count}

    def _kv_verify(self, seq: _Active) -> bool:
        """Verify-on-read: re-read the slot's whole valid prefix and
        check it against the write-side crc ledger. Runs only at
        retirement (and only with kv_crc on), so a request's tokens are
        NEVER released to a client from a cache row whose bytes changed
        behind the scheduler's back."""
        if not self.kv_crc or seq.cache_len <= 0:
            return True
        raw = self.executor.kv_slot_bytes(seq.slot, 0, seq.cache_len)
        return self.kv.crc_check(seq.slot, raw)

    def _retire(self) -> None:
        now = time.monotonic()
        for slot in list(self._active):
            seq = self._active[slot]
            req = seq.req
            done_ok = (len(seq.out) >= req.max_new_tokens
                       or (self.eos_id is not None and seq.out
                           and seq.out[-1] == self.eos_id)
                       or seq.cache_len >= self.kv.max_len)
            expired = req.expired(now)
            if not (done_ok or expired):
                continue
            ms = (now - req.submitted_at) * 1000.0
            if not self._kv_verify(seq):
                # corrupted KV: the generated tokens are untrusted and
                # must not reach the client. Re-prefill from the prompt
                # (a fresh slot, a clean generation) while the deadline
                # allows; otherwise fail cleanly.
                self.kv_corruptions_detected += 1
                self._m_kv_corrupt.inc()
                logger.warning(
                    "serve replica %s: KV slot %d failed crc "
                    "verify-on-read (request %d) — %s",
                    self.replica_id, slot, req.rid,
                    "re-prefilling" if self.on_kv_corrupt == "reprefill"
                    and not expired else "failing the request")
                self.kv.free(slot)
                del self._active[slot]
                if self.on_kv_corrupt == "reprefill" and not expired:
                    self.kv_reprefills += 1
                    self._reprefill.append(req)
                else:
                    req.handle._resolve(
                        [], "error", latency_ms=ms, error="kv_corrupt")
                continue
            if expired and not done_ok:
                self.queue.expired_count += 1
                req.handle._resolve(seq.out, "expired", latency_ms=ms)
            else:
                req.handle._resolve(seq.out, "ok", latency_ms=ms)
                self.queue.note_service_ms(ms)
            self.kv.free(slot)
            del self._active[slot]

    def _admit(self) -> List[_Active]:
        free = self.kv.num_slots - self.kv.live()
        if free <= 0:
            return []
        admitted: List[_Active] = []
        # corrupted-and-reset sequences re-enter ahead of the queue
        # (they already waited their turn once)
        while self._reprefill and len(admitted) < free:
            req = self._reprefill.pop(0)
            slot = self.kv.alloc()
            admitted.append(_Active(req=req, slot=slot))
            self._active[slot] = admitted[-1]
        for req in self.queue.pop(free - len(admitted)):
            slot = self.kv.alloc()  # free>=len(pop) => never None
            admitted.append(_Active(req=req, slot=slot))
            self._active[slot] = admitted[-1]
        return admitted

    def _bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise AssertionError(
            f"prompt of {length} passed admission but fits no bucket "
            f"{self.buckets}")  # queue.max_prompt_len makes this unreachable

    def _prefill(self, admitted: List[_Active]) -> None:
        B = self.executor.max_batch
        bucket = self._bucket_for(max(len(a.req.prompt) for a in admitted))
        tokens = np.zeros((B, bucket), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        last_idx = np.zeros(B, np.int32)
        for a in admitted:
            n = len(a.req.prompt)
            tokens[a.slot, :n] = a.req.prompt
            mask[a.slot] = True
            last_idx[a.slot] = n - 1
        nxt = self.executor.step(tokens, positions, mask, last_idx,
                                 kind="prefill", stats=self._stats())
        t_first = time.monotonic()
        for a in admitted:
            self._m_ttft.observe(
                (t_first - a.req.submitted_at) * 1000.0)
            n = len(a.req.prompt)
            a.cache_len = n
            # the prompt is fully cached but only [0, n) is valid; the
            # first generated token is the prompt's last-logit argmax
            a.out.append(int(nxt[a.slot]))
            self.kv.lengths[a.slot] = n
            if self.kv_crc:
                # crc-on-write covers exactly the valid prefix (pad
                # positions past n are unreachable and unverified)
                self.kv.crc_update(
                    a.slot, self.executor.kv_slot_bytes(a.slot, 0, n))

    def _decode(self) -> None:
        B = self.executor.max_batch
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        last_idx = np.zeros(B, np.int32)
        for slot, seq in self._active.items():
            # the newest token is not yet in the cache: this step writes
            # it at position cache_len, attends, and samples the next
            tokens[slot, 0] = seq.out[-1]
            positions[slot] = seq.cache_len
            mask[slot] = True
        nxt = self.executor.step(tokens, positions, mask, last_idx,
                                 kind="decode", stats=self._stats())
        for slot, seq in self._active.items():
            if self.kv_crc:
                # this step wrote one K/V entry at the old cache_len
                self.kv.crc_update(
                    slot, self.executor.kv_slot_bytes(
                        slot, seq.cache_len, seq.cache_len + 1))
            seq.cache_len += 1
            self.kv.lengths[slot] = seq.cache_len
            seq.out.append(int(nxt[slot]))
