"""Fleet radix index: which replica holds which prefix run, and where.

The router-side half of the fleet KV tier (docs/serving.md): a
jax-free radix tree over BLOCK-granular prompt-token runs mapping each
cached run to its holders — ``(replica, tier, weights version)`` per
node. The index is built entirely from the admission/eviction events
every replica's :class:`~horovod_tpu.serve.kvtier.tier.ReplicaKVTier`
emits (``drain_events``), piggybacked on the healthz/heartbeat channel
the router already reads: the in-process fleet drains them on the
monitor sweep, the multi-process fleet carries them in the worker's
healthz reply. No new sockets, no new threads.

Routing contract: :meth:`lookup` returns, per replica, the length (in
blocks) of the LONGEST CONTIGUOUS run of ``prompt`` that replica holds
under the matching weight version — contiguous from the root, because
a replica holding block 3 of a run without blocks 0-2 cannot serve any
of it. :func:`prefer_holders` folds that into the candidate ordering
every router face shares: deepest matched run first, then the router's
own load order. Tiers order ``hbm > host > disk`` only as a tiebreak —
a resident run beats one that must promote through the ladder.

The index is ADVISORY by construction: it lags the replicas by one
heartbeat, so a routed request may find its run evicted (it
re-prefills — the miss path) and an unrouted request may luck into a
hit. Correctness never depends on it; only locality does.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["FleetRadixIndex", "prefer_holders", "TIERS"]

#: tier names, promotion-distance order (hbm is already resident)
TIERS = ("hbm", "host", "disk")

_TIER_RANK = {t: i for i, t in enumerate(TIERS)}


class _INode:
    __slots__ = ("children", "holders")

    def __init__(self):
        self.children: Dict[Tuple[int, ...], "_INode"] = {}
        #: rid -> (tier, weights_version)
        self.holders: Dict[int, Tuple[str, Optional[int]]] = {}


class FleetRadixIndex:
    """Router-side radix tree over block-granular token runs.

    Thread-safe (one lock): events arrive on the monitor/health-poll
    thread while lookups run on the submit path.
    """

    def __init__(self, block_size: int):
        if int(block_size) < 1:
            raise ValueError(
                f"block_size must be >= 1; got {block_size}")
        self.block_size = int(block_size)
        self._root = _INode()
        self._lock = threading.Lock()
        self.events_applied = 0

    # -- event ingestion (heartbeat/healthz channel) -------------------------
    def apply_events(self, rid: int, events: Sequence[dict]) -> int:
        """Fold one replica's drained tier events into the index.
        Unknown kinds are skipped (forward compat — an older router
        reading a newer replica's events must not wedge the sweep)."""
        n = 0
        for ev in events:
            kind = ev.get("kind")
            if kind == "insert":
                self.note_insert(rid, ev.get("tokens", ()), "hbm",
                                 ev.get("version"))
            elif kind == "demote":
                self.note_tier(rid, ev.get("tokens", ()),
                               str(ev.get("tier", "host")),
                               ev.get("version"))
            elif kind == "drop":
                self.note_drop(rid, ev.get("tokens", ()))
            elif kind == "flush":
                self.drop_replica(rid)
            else:
                continue
            n += 1
        self.events_applied += n
        return n

    def _walk(self, tokens, create: bool) -> Optional[List[_INode]]:
        """Nodes along ``tokens``'s full-block path (root-first;
        excludes the root itself). None when absent and not creating."""
        bs = self.block_size
        toks = [int(t) for t in tokens]
        out: List[_INode] = []
        node = self._root
        for pos in range(0, (len(toks) // bs) * bs, bs):
            seg = tuple(toks[pos:pos + bs])
            child = node.children.get(seg)
            if child is None:
                if not create:
                    return None
                child = node.children[seg] = _INode()
            out.append(child)
            node = child
        return out

    def note_insert(self, rid: int, tokens, tier: str,
                    version: Optional[int]) -> None:
        """``rid`` cached the run ``tokens`` (every full block of it)
        in ``tier`` under weight ``version``."""
        with self._lock:
            for node in self._walk(tokens, create=True) or []:
                node.holders[int(rid)] = (tier, version)

    def note_tier(self, rid: int, tokens, tier: str,
                  version: Optional[int]) -> None:
        """The LAST block of run ``tokens`` moved tiers on ``rid``
        (a demotion/promotion event addresses one node — evictions are
        leaf-at-a-time)."""
        with self._lock:
            nodes = self._walk(tokens, create=True)
            if nodes:
                nodes[-1].holders[int(rid)] = (tier, version)

    def note_drop(self, rid: int, tokens) -> None:
        """``rid`` no longer holds the last block of run ``tokens`` in
        any tier."""
        with self._lock:
            nodes = self._walk(tokens, create=False)
            if nodes:
                nodes[-1].holders.pop(int(rid), None)

    def drop_replica(self, rid: int) -> None:
        """Forget every run ``rid`` held (flush, eject, respawn)."""
        rid = int(rid)
        with self._lock:
            stack = [self._root]
            while stack:
                node = stack.pop()
                node.holders.pop(rid, None)
                stack.extend(node.children.values())

    # -- lookup (the routing signal) -----------------------------------------
    def lookup(self, prompt,
               versions: Optional[Dict[int, Optional[int]]] = None
               ) -> Dict[int, Tuple[int, str]]:
        """Per-replica longest CONTIGUOUS matched run of ``prompt``:
        ``{rid: (blocks_matched, deepest_tier)}``. ``versions`` (rid ->
        the replica's current weights version) fences stale entries out
        of the match — a run recorded under another version cannot be
        served and must not attract traffic."""
        bs = self.block_size
        toks = [int(t) for t in prompt]
        depths: Dict[int, int] = {}
        tiers: Dict[int, str] = {}
        with self._lock:
            node = self._root
            depth = 0
            for pos in range(0, (len(toks) // bs) * bs, bs):
                child = node.children.get(tuple(toks[pos:pos + bs]))
                if child is None:
                    break
                depth += 1
                for rid, (tier, ver) in child.holders.items():
                    if versions is not None and \
                            ver != versions.get(rid, ver):
                        continue
                    if depths.get(rid, 0) == depth - 1:
                        depths[rid] = depth
                        tiers[rid] = tier
                node = child
        return {rid: (d, tiers[rid]) for rid, d in depths.items()
                if d > 0}

    def stats(self) -> dict:
        with self._lock:
            nodes = holders = 0
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                nodes += 1
                holders += len(node.holders)
                stack.extend(node.children.values())
        return {"nodes": nodes, "holders": holders,
                "events_applied": self.events_applied}


def prefer_holders(candidates, prompt, index: Optional[FleetRadixIndex],
                   *, versions: Optional[dict] = None,
                   min_blocks: int = 1) -> Tuple[list, Dict[int, int]]:
    """The shared candidate-ordering helper every router face uses:
    stable-reorder ``candidates`` (already in the router's own
    least-loaded order; items expose ``.id``) so replicas holding at
    least ``min_blocks`` contiguous blocks of ``prompt`` come first,
    deepest run first, resident tier breaking ties. Returns the
    reordered list plus ``{rid: blocks_matched}`` so the caller can
    count a routed-by-index dispatch. With no index (or no match) the
    input order is returned unchanged — the tier never degrades plain
    load routing."""
    if index is None:
        return list(candidates), {}
    matched = index.lookup(prompt, versions)
    matched = {rid: m for rid, m in matched.items()
               if m[0] >= min_blocks}
    if not matched:
        return list(candidates), {}

    def key(i_c):
        i, c = i_c
        m = matched.get(c.id)
        if m is None:
            return (0, 0, i)
        return (-m[0], _TIER_RANK.get(m[1], len(TIERS)), i)

    ordered = [c for _i, c in
               sorted(enumerate(candidates), key=lambda ic: key(ic))]
    return ordered, {rid: m[0] for rid, m in matched.items()}
