"""Fleet-wide KV tier: cross-replica prefix routing + the
HBM -> host-RAM -> disk eviction ladder (docs/serving.md).

Two halves:

* :mod:`.index` — the router-side, jax-free fleet radix index mapping
  block-granular prefix runs to their holders, fed by replica tier
  events over the healthz/heartbeat channel; ``prefer_holders`` is the
  candidate-ordering helper every router face shares.
* :mod:`.tier` — the replica-side eviction ladder: a refcount-zero
  prefix run demotes to a bounded host-RAM ring, overflows to hvdkv-v1
  disk spill files, and promotes back through the crc-gated,
  version-fenced ``install_kv_blocks`` path.
"""
from .index import FleetRadixIndex, TIERS, prefer_holders
from .tier import (DiskTier, HostRing, ReplicaKVTier, TierEntry,
                   read_spill_file, spill_file_name)

__all__ = [
    "FleetRadixIndex",
    "TIERS",
    "prefer_holders",
    "DiskTier",
    "HostRing",
    "ReplicaKVTier",
    "TierEntry",
    "read_spill_file",
    "spill_file_name",
]
