"""The eviction ladder: HBM -> host-RAM ring -> disk spill.

The replica-side half of the fleet KV tier (docs/serving.md): instead
of dying, a refcount-zero prefix run evicted from the device pool
DEMOTES — its block bytes (read back through the same
``executor.kv_block_bytes`` path the migration pack uses) land in a
bounded host-RAM ring, overflowing to an hvdkv-v1 spill directory on
disk. A returning conversation PROMOTES the run back: per-leaf crc32s
are verified BEFORE any byte touches the device, the install goes
through ``executor.install_kv_blocks`` (the verified migration-install
path), the weight-version fence is checked before AND after the device
writes, and the block is grafted back onto the radix tree
(``RadixPrefixCache.attach``) where the normal prefix match picks it
up. Promotion is bit-identical by construction — the bytes ARE the
originally written blocks.

Integrity/fencing contract (the kv_migrate discipline, applied to
tier moves):

* every entry carries the per-leaf crc32 ledger stamped at demotion;
  a promotion whose re-read fails any crc discards the entry and falls
  back to re-prefill — counted, never an error, never a device byte;
* every entry carries the weights version its KV was computed under;
  a version mismatch (hot swap since demotion) refuses the promotion —
  stale-weight KV is unreachable through the ladder exactly as it is
  through the migration wire;
* chaos sites ``kvtier.demote`` / ``kvtier.promote`` (docs/chaos.md):
  ``drop`` skips the tier move (the run dies / stays put; the request
  re-prefills — the miss path, never an error), ``corrupt`` flips one
  bit in the moving bytes so the crc gate must catch it.

Everything here except the device read/install is jax-free; the spill
file format is stdlib-parsable (``tools/kvtier_inspect.py``).
"""
from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ...chaos import inject as _chaos
from ...obs import metrics as obs_metrics
from ...trace.spans import get_recorder as _trace_recorder

logger = logging.getLogger("horovod_tpu")

__all__ = ["HostRing", "DiskTier", "ReplicaKVTier", "TierEntry",
           "FORMAT", "read_spill_file", "spill_file_name"]

#: spill file magic/format id (hvdkv-v1: magic line, 4-byte LE header
#: length, JSON header, raw concatenated per-leaf payload)
FORMAT = "hvdkv-v1"
_MAGIC = b"hvdkv-v1\n"

# -- metric help strings (one literal per family, shared across every
# registration site — the metric-help lint's rule) ---------------------------
DEMOTIONS_HELP = ("prefix-run blocks demoted down the KV tier ladder "
                  "(tier = where they landed)")
PROMOTIONS_HELP = ("prefix-run blocks promoted back to HBM through the "
                   "verified install path (tier = where they came from)")
HITS_HELP = "KV tier lookups that found a promotable block (by tier)"
MISSES_HELP = ("KV tier lookups that found nothing promotable (the "
               "re-prefill fallback)")
BYTES_HELP = "bytes resident in a KV tier (by tier)"
CORRUPT_HELP = ("KV tier blocks whose crc32 failed verification "
                "(caught before any device byte landed)")
PULLS_HELP = ("cross-replica prefix-run pulls over the migration wire "
              "(router-orchestrated, crc-gated on arrival)")
ROUTED_HELP = ("requests dispatched to the replica the fleet index "
               "says holds their longest cached prefix run")


class TierEntry:
    """One demoted block: the run's root->node token path, the block's
    per-leaf bytes as written, the crc32 ledger stamped at demotion,
    and the weight version fence."""

    __slots__ = ("tokens", "leaf_bytes", "crcs", "filled", "version")

    def __init__(self, tokens: Tuple[int, ...],
                 leaf_bytes: List[bytes], crcs: List[int],
                 filled: int, version: Optional[int]):
        self.tokens = tuple(int(t) for t in tokens)
        self.leaf_bytes = list(leaf_bytes)
        self.crcs = [int(c) for c in crcs]
        self.filled = int(filled)
        self.version = version

    @property
    def nbytes(self) -> int:
        return sum(len(b) for b in self.leaf_bytes)

    def verify(self, leaf_bytes: Optional[List[bytes]] = None) -> bool:
        """Per-leaf crc check of ``leaf_bytes`` (default: the stored
        bytes) against the demotion-time ledger."""
        raw = self.leaf_bytes if leaf_bytes is None else leaf_bytes
        return len(raw) == len(self.crcs) and all(
            zlib.crc32(b) == c for b, c in zip(raw, self.crcs))


class HostRing:
    """Bounded-bytes host-RAM tier: an LRU ring of :class:`TierEntry`
    keyed by token path. ``put`` returns the entries the byte bound
    pushed out (oldest first) — the caller spills them to disk or lets
    them die. Thread-safe: demotions run on the scheduler thread while
    cross-replica exports read from the router thread."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(int(max_bytes), 0)
        self._entries: "OrderedDict[Tuple[int, ...], TierEntry]" = \
            OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def put(self, entry: TierEntry) -> List[TierEntry]:
        evicted: List[TierEntry] = []
        with self._lock:
            old = self._entries.pop(entry.tokens, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.tokens] = entry
            self._bytes += entry.nbytes
            while self._bytes > self.max_bytes and self._entries:
                _k, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                evicted.append(ev)
        return evicted

    def get(self, tokens) -> Optional[TierEntry]:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
            return ent

    def pop(self, tokens) -> Optional[TierEntry]:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent.nbytes
            return ent

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            return n

    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def count(self) -> int:
        with self._lock:
            return len(self._entries)


def spill_file_name(tokens) -> str:
    """Deterministic spill file name for a run's token path: a crc32
    of the token bytes plus the depth — collisions are disambiguated by
    the full token list in the header (read_spill_file verifies)."""
    toks = [int(t) for t in tokens]
    rid = zlib.crc32(b"".join(t.to_bytes(4, "little", signed=True)
                              for t in toks))
    return f"run-{rid:08x}-{len(toks):05d}.hvdkv"


def write_spill_file(path: str, entry: TierEntry,
                     block_size: int) -> None:
    """Write one hvdkv-v1 spill file atomically (tmp + rename, the
    ckpt/store.py convention — a crash leaves the old file or the new
    one, never a torn mix)."""
    payload = b"".join(entry.leaf_bytes)
    header = {
        "format": FORMAT,
        "tokens": list(entry.tokens),
        "block_size": int(block_size),
        "filled": entry.filled,
        "weights_version": entry.version,
        "nbytes": [len(b) for b in entry.leaf_bytes],
        "crcs": entry.crcs,
        "payload_crc": zlib.crc32(payload),
    }
    raw = json.dumps(header, sort_keys=True).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(raw)))
        f.write(raw)
        f.write(payload)
    os.replace(tmp, path)


def read_spill_file(path: str) -> Tuple[dict, bytes]:
    """Parse one hvdkv-v1 spill file into ``(header, payload)``.
    Raises ValueError on a malformed file; crc verification is the
    CALLER's job (the promote path checks per-leaf crcs, the inspect
    tool checks the payload crc too)."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(
                f"{path}: not an {FORMAT} spill file "
                f"(magic {magic!r})")
        (hlen,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hlen).decode("utf-8"))
        payload = f.read()
    if header.get("format") != FORMAT:
        raise ValueError(
            f"{path}: header format {header.get('format')!r} != "
            f"{FORMAT}")
    return header, payload


class DiskTier:
    """Disk spill tier: one hvdkv-v1 file per demoted block under
    ``root``. Membership is cached in memory (scanned once at init,
    maintained on put/pop) so the promote path's miss check never hits
    the filesystem. Thread-safe like :class:`HostRing`."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._files: Dict[Tuple[int, ...], str] = {}
        for name in os.listdir(self.root):
            if not name.endswith(".hvdkv"):
                continue
            try:
                header, _ = read_spill_file(
                    os.path.join(self.root, name))
                self._files[tuple(int(t) for t in
                                  header.get("tokens", ()))] = name
            except (ValueError, OSError, KeyError):
                # resilience: exempt (local spill-file read, no
                # sockets — an unreadable file is just not membership)
                logger.warning(
                    "kvtier: skipping unreadable spill file %s", name)

    def put(self, entry: TierEntry, block_size: int) -> bool:
        name = spill_file_name(entry.tokens)
        try:
            write_spill_file(os.path.join(self.root, name), entry,
                             block_size)
        except OSError as e:
            # resilience: exempt (local disk write, no sockets — a
            # failed spill degrades to the miss path by design)
            logger.warning(
                "kvtier: disk spill of %d bytes failed (%s) — run "
                "dropped, will re-prefill", entry.nbytes, e)
            return False
        with self._lock:
            self._files[entry.tokens] = name
        return True

    def get(self, tokens) -> Optional[TierEntry]:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            name = self._files.get(key)
        if name is None:
            return None
        try:
            header, payload = read_spill_file(
                os.path.join(self.root, name))
        except (ValueError, OSError):
            # resilience: exempt (local spill-file read, no sockets —
            # an unreadable entry is a promote miss, never an error)
            return None
        if tuple(int(t) for t in header.get("tokens", ())) != key:
            return None          # file-name crc collision: a miss
        leaf_bytes, off = [], 0
        for n in header.get("nbytes", []):
            leaf_bytes.append(payload[off:off + int(n)])
            off += int(n)
        return TierEntry(key, leaf_bytes, header.get("crcs", []),
                         header.get("filled", 0),
                         header.get("weights_version"))

    def pop(self, tokens) -> None:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            name = self._files.pop(key, None)
        if name is not None:
            try:
                os.remove(os.path.join(self.root, name))
            except OSError:
                # resilience: exempt (local best-effort unlink — a
                # leftover file is re-verified by any later reader)
                pass

    def count(self) -> int:
        with self._lock:
            return len(self._files)

    def bytes(self) -> int:
        with self._lock:
            names = list(self._files.values())
        total = 0
        for name in names:
            try:
                total += os.path.getsize(os.path.join(self.root, name))
            except OSError:
                # resilience: exempt (local stat for a gauge — a file
                # racing deletion just reads as zero bytes)
                pass
        return total

    def contains(self, tokens) -> bool:
        with self._lock:
            return tuple(int(t) for t in tokens) in self._files


class ReplicaKVTier:
    """One replica's tier ladder + its event feed to the fleet index.

    Scheduler-thread methods (the batcher's single-writer discipline):
    :meth:`on_evict` (the prefix cache's eviction hook — demotion),
    :meth:`promote_for` (pre-admission promotion), :meth:`install_
    grafts` (cross-replica pull install), :meth:`on_flush`.
    Router/endpoint-thread methods: :meth:`export_run`,
    :meth:`submit_graft`, :meth:`drain_events`, :meth:`stats` — all
    over locked structures.
    """

    def __init__(self, executor, pool, prefix, *,
                 replica_id: Optional[int] = None,
                 kv_crc: bool = False,
                 host_bytes: int = 64 * 1024 * 1024,
                 spill_dir: Optional[str] = None):
        self.executor = executor
        self.pool = pool
        self.prefix = prefix
        self.replica_id = replica_id
        self.kv_crc = bool(kv_crc)
        self.block_size = pool.block_size
        self.host = HostRing(host_bytes)
        self.disk = DiskTier(spill_dir) if spill_dir else None
        #: index event feed (heartbeat/healthz channel); bounded so an
        #: unattended replica cannot grow without a router draining it
        self._events: "deque[dict]" = deque(maxlen=1024)
        self._events_lock = threading.Lock()
        #: cross-replica pull installs awaiting the scheduler thread
        self._grafts: List[dict] = []
        self._grafts_lock = threading.Lock()
        # chaos addressing: per-replica tier-op counters (the serve.kv
        # pattern — deterministic per replica across the fleet)
        self._demote_ops = 0
        self._promote_ops = 0
        self.demote_drops = 0
        self.promote_drops = 0
        self.corrupt_detected = 0
        self.promoted_blocks = 0
        self.demoted_blocks = 0
        self.pulls_in = 0
        # -- metrics (the serve labeling discipline: standalone claims
        # fresh, fleet replicas get labeled children)
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        R = obs_metrics.get_registry()
        if replica_id is None:
            for fam in ("hvd_serve_kvtier_demotions_total",
                        "hvd_serve_kvtier_promotions_total",
                        "hvd_serve_kvtier_hits_total",
                        "hvd_serve_kvtier_misses_total",
                        "hvd_serve_kvtier_bytes",
                        "hvd_serve_kvtier_corrupt_total"):
                R.unregister(fam)
        self._m_demote = {
            t: R.counter("hvd_serve_kvtier_demotions_total",
                         DEMOTIONS_HELP, dict(rl, tier=t))
            for t in ("host", "disk")}
        self._m_promote = {
            t: R.counter("hvd_serve_kvtier_promotions_total",
                         PROMOTIONS_HELP, dict(rl, tier=t))
            for t in ("host", "disk")}
        self._m_hits = {
            t: R.counter("hvd_serve_kvtier_hits_total", HITS_HELP,
                         dict(rl, tier=t))
            for t in ("host", "disk")}
        self._m_misses = R.counter(
            "hvd_serve_kvtier_misses_total", MISSES_HELP, rl or None)
        self._m_bytes = {
            t: R.gauge("hvd_serve_kvtier_bytes", BYTES_HELP,
                       dict(rl, tier=t))
            for t in ("host", "disk")}
        self._m_corrupt = R.counter(
            "hvd_serve_kvtier_corrupt_total", CORRUPT_HELP, rl or None)

    # -- event feed (fleet index channel) ------------------------------------
    def _emit(self, kind: str, tokens=None, tier: Optional[str] = None,
              version=None) -> None:
        ev: dict = {"kind": kind}
        if tokens is not None:
            ev["tokens"] = [int(t) for t in tokens]
        if tier is not None:
            ev["tier"] = tier
        if version is not None or kind in ("insert", "demote",
                                           "promote"):
            ev["version"] = version
        with self._events_lock:
            self._events.append(ev)

    def drain_events(self) -> List[dict]:
        with self._events_lock:
            out = list(self._events)
            self._events.clear()
        return out

    def note_insert(self, prompt, version) -> None:
        """Batcher hook, after ``prefix.insert``: the run's full blocks
        are now HBM-resident — tell the index."""
        bs = self.block_size
        n_full = (len(prompt) // bs) * bs
        if n_full:
            self._emit("insert", prompt[:n_full], version=version)

    def _gauge_refresh(self) -> None:
        self._m_bytes["host"].set(self.host.bytes())
        self._m_bytes["disk"].set(
            self.disk.bytes() if self.disk is not None else 0)

    # -- demotion (the prefix cache's on_evict hook) -------------------------
    def on_evict(self, ev: dict) -> None:
        """Demote one evicted run block down the ladder instead of
        letting it die. Scheduler thread (eviction runs inside the
        admission wave). Chaos ``kvtier.demote``: ``drop`` skips the
        demotion (the run dies, a follow-up re-prefills — the miss
        path), ``corrupt`` flips one bit in the DEMOTED copy after the
        crc ledger is stamped, so promotion's crc gate must catch it."""
        tokens = ev["tokens"]
        blk = int(ev["block"])
        version = self.executor.params_version
        step = self._demote_ops
        self._demote_ops += 1
        f = None
        if _chaos._INJ is not None:
            f = _chaos.fire("kvtier.demote", peer=self.replica_id,
                            step=step)
            if f is not None and f.kind == "drop":
                self.demote_drops += 1
                self._emit("drop", tokens)
                return
        filled = self.block_size
        leaf_bytes = self.executor.kv_block_bytes(blk, 0, filled)
        if self.kv_crc and self.pool.crc_filled(blk) >= filled:
            # pre-flight: a block corrupted at rest must not demote
            # with freshly stamped (self-consistent) crcs — the
            # pack_parked rule, applied to the ladder
            if not self.pool.crc_check(blk, leaf_bytes):
                self.corrupt_detected += 1
                self._m_corrupt.inc()
                self._emit("drop", tokens)
                logger.warning(
                    "kvtier replica %s: block %d failed its crc "
                    "ledger at demotion — run dropped",
                    self.replica_id, blk)
                return
        crcs = [zlib.crc32(b) for b in leaf_bytes]
        if f is not None and f.kind == "corrupt":
            # corrupt the DEMOTED copy, crcs already stamped over the
            # clean bytes: only the promote-side crc gate can catch it
            leaf_bytes = list(leaf_bytes)
            leaf_bytes[0] = _chaos.corrupt_copy(leaf_bytes[0])
        entry = TierEntry(tokens, leaf_bytes, crcs, filled, version)
        overflow = self.host.put(entry)
        self.demoted_blocks += 1
        self._m_demote["host"].inc()
        self._emit("demote", entry.tokens, tier="host",
                   version=version)
        for ov in overflow:
            if self.disk is not None and self.disk.put(
                    ov, self.block_size):
                self._m_demote["disk"].inc()
                self._emit("demote", ov.tokens, tier="disk",
                           version=ov.version)
            else:
                self._emit("drop", ov.tokens)
        self._gauge_refresh()

    # -- promotion (pre-admission, scheduler thread) -------------------------
    def _lookup(self, tokens) -> Tuple[Optional[TierEntry],
                                       Optional[str]]:
        ent = self.host.get(tokens)
        if ent is not None:
            return ent, "host"
        if self.disk is not None:
            ent = self.disk.get(tokens)
            if ent is not None:
                return ent, "disk"
        return None, None

    def _discard(self, tokens, tier: Optional[str]) -> None:
        if tier == "host":
            self.host.pop(tokens)
        elif tier == "disk" and self.disk is not None:
            self.disk.pop(tokens)
        self._emit("drop", tokens)

    def empty(self) -> bool:
        return self.host.count() == 0 and \
            (self.disk is None or self.disk.count() == 0)

    def promote_for(self, prompt) -> int:
        """Promote every ladder-held block of ``prompt``'s prefix back
        into the pool + radix tree, shallowest first, stopping at the
        first miss/fence/full-pool. Returns blocks promoted. The
        subsequent prefix match then reuses them exactly like
        locally-computed runs — bit-identical bytes, verified crcs,
        fenced version. Two phases so the whole span lands in ONE
        batched device write (one scatter per cache leaf, not per
        block — a 21-block returning conversation pays one swap-lock
        acquisition, not 21): gather verifies host-side, install
        writes."""
        if self.prefix is None or self.empty():
            return 0
        bs = self.block_size
        toks = [int(t) for t in prompt]
        # one token must always be prefilled (the match cap) — the
        # deepest useful block ends at len(prompt) - 1
        n_blocks = (len(toks) - 1) // bs
        if n_blocks < 1:
            return 0
        have = self.executor.params_version
        t0 = time.time()
        staged = self._stage_runs(toks, n_blocks, have)
        promoted = self._install_staged(staged, have) if staged else 0
        if promoted:
            self.promoted_blocks += promoted
            self._emit("promote", toks[:self._promoted_depth(
                toks, promoted)], tier="hbm", version=have)
            # trace: exempt (process-level span, leg None — see
            # SPAN_LEGS; recorded once per promotion burst)
            _trace_recorder().record_process(
                "kvtier_promote", t0, time.time(), blocks=promoted)
            self._gauge_refresh()
        return promoted

    def _stage_runs(self, toks, n_blocks: int, have) -> list:
        """Gather half of :meth:`promote_for`: the contiguous
        ladder-held span past the deepest HBM-resident node, each
        block chaos-fired, version-fenced and crc-verified BEFORE any
        device byte lands — exactly the per-block discipline, just
        decoupled from the write. Returns
        ``[(run, entry, leaf_bytes, tier), ...]``."""
        bs = self.block_size
        staged: list = []
        node_children = self.prefix._children
        for bi in range(n_blocks):
            if not staged:
                node = node_children.get(
                    tuple(toks[bi * bs:(bi + 1) * bs]))
                if node is not None:
                    node_children = node.children
                    continue        # HBM-resident already
            # the radix tree never evicts a parent under a live child,
            # so past the first missing block every deeper one is
            # missing too — no more tree probes needed
            run = tuple(toks[:(bi + 1) * bs])
            entry, tier = self._lookup(run)
            if entry is None:
                self._m_misses.inc()
                break
            self._m_hits[tier].inc()
            step = self._promote_ops
            self._promote_ops += 1
            leaf_bytes = entry.leaf_bytes
            if _chaos._INJ is not None:
                f = _chaos.fire("kvtier.promote", peer=self.replica_id,
                                step=step)
                if f is not None and f.kind == "drop":
                    # promotion lost: the request re-prefills this
                    # suffix — the miss path, never an error
                    self.promote_drops += 1
                    break
                if f is not None and f.kind == "corrupt":
                    leaf_bytes = list(leaf_bytes)
                    leaf_bytes[0] = _chaos.corrupt_copy(leaf_bytes[0])
            if entry.version != have:
                # weight-version fence: demoted under another version —
                # unusable forever (the swap invalidated it), discard
                self._discard(run, tier)
                break
            if not entry.verify(leaf_bytes):
                # crc gate: caught BEFORE any device byte lands
                self.corrupt_detected += 1
                self._m_corrupt.inc()
                self._discard(run, tier)
                logger.warning(
                    "kvtier replica %s: run block %d failed its crc32 "
                    "at promotion — discarded, falling back to "
                    "re-prefill", self.replica_id, bi)
                break
            staged.append((run, entry, leaf_bytes, tier))
        return staged

    def _install_staged(self, staged: list, want_version) -> int:
        """Install half of :meth:`promote_for`: pool allocs, ONE
        batched device write for the whole staged span, pool crc-ledger
        seed, post-write fence re-check, then shallowest-first tree
        grafts. Mirrors the migrated-install discipline (batcher
        ``_install_one``); any failure frees every block and falls
        back to re-prefill."""
        blks: list = []
        for _ in staged:
            blk = self.pool.alloc()
            if blk is None:
                break               # pool full: admission wins
            blks.append(blk)
        staged = staged[:len(blks)]
        if not blks:
            return 0
        try:
            self.executor.install_kv_blocks(
                blks, [lb for _, _, lb, _ in staged],
                [entry.filled for _, entry, _, _ in staged])
            if self.kv_crc:
                for blk, (_, entry, lb, _) in zip(blks, staged):
                    self.pool.crc_reset(blk, lb, entry.filled)
        except ValueError as e:
            for blk in blks:
                self.pool.decref(blk)
            logger.warning(
                "kvtier replica %s: promote install failed (%s) — "
                "falling back to re-prefill", self.replica_id, e)
            return 0
        # the fence RE-CHECK: a hot swap landing between the check and
        # the device write tears the promotion down, never the stream
        if self.executor.params_version != want_version:
            for blk in blks:
                self.pool.decref(blk)
            return 0
        promoted = 0
        for blk, (run, entry, lb, tier) in zip(blks, staged):
            if not self.prefix.attach(run, blk):
                self.pool.decref(blk)  # someone recomputed it: theirs wins
                continue
            self.pool.decref(blk)   # the tree's refcount is THE owner
            self._discard_quiet(run, tier)
            self._m_promote[tier].inc()
            promoted += 1
        return promoted

    def _promoted_depth(self, toks, promoted: int) -> int:
        # the promote loop walks contiguously from the shallowest
        # missing block; the event's run is the full matched path
        bs = self.block_size
        depth = 0
        children = self.prefix._children
        for bi in range((len(toks) - 1) // bs):
            node = children.get(tuple(toks[bi * bs:(bi + 1) * bs]))
            if node is None:
                break
            depth = bi + 1
            children = node.children
        return depth * bs

    def _discard_quiet(self, tokens, tier: Optional[str]) -> None:
        """Drop a ladder copy after a successful promotion — no index
        event (the promote event already moved the run to hbm)."""
        if tier == "host":
            self.host.pop(tokens)
        elif tier == "disk" and self.disk is not None:
            self.disk.pop(tokens)

    def _install_block(self, run, entry: TierEntry,
                       leaf_bytes: List[bytes],
                       want_version) -> bool:
        """The verified install: pool alloc, device write, crc-ledger
        seed, post-write fence re-check, tree graft. Mirrors the
        migrated-install discipline (batcher ``_install_one``)."""
        blk = self.pool.alloc()
        if blk is None:
            return False            # pool full: admission wins
        try:
            self.executor.install_kv_blocks(
                [blk], [leaf_bytes], [entry.filled])
            if self.kv_crc:
                self.pool.crc_reset(blk, leaf_bytes, entry.filled)
        except ValueError as e:
            self.pool.decref(blk)
            logger.warning(
                "kvtier replica %s: promote install failed (%s) — "
                "falling back to re-prefill", self.replica_id, e)
            return False
        # the fence RE-CHECK: a hot swap landing between the check and
        # the device write tears the promotion down, never the stream
        if self.executor.params_version != want_version:
            self.pool.decref(blk)
            return False
        if not self.prefix.attach(run, blk):
            self.pool.decref(blk)   # someone recomputed it: theirs wins
            return False
        self.pool.decref(blk)       # the tree's refcount is THE owner
        return True

    # -- cross-replica pulls (the serve.migrate-shaped leg) ------------------
    def export_run(self, prompt, version) -> Optional[
            Tuple[dict, bytes]]:
        """Pack this replica's ladder-held prefix of ``prompt`` into a
        kv_migrate-shaped ``(header, payload)`` — per-block per-leaf
        bytes + crc ledger + weight version, root-contiguous (a run
        whose shallow blocks are still HBM-resident is not exportable;
        the router dispatches TO this replica instead). Thread-safe:
        reads only the locked ladder, never device state."""
        bs = self.block_size
        toks = [int(t) for t in prompt]
        metas: List[dict] = []
        chunks: List[bytes] = []
        tokens_out: List[int] = []
        for bi in range((len(toks) - 1) // bs):
            run = tuple(toks[:(bi + 1) * bs])
            entry, _tier = self._lookup(run)
            if entry is None or entry.version != version:
                break
            metas.append({"filled": entry.filled,
                          "crcs": list(entry.crcs),
                          "nbytes": [len(b) for b in
                                     entry.leaf_bytes]})
            chunks.extend(entry.leaf_bytes)
            tokens_out = list(run)
        if not metas:
            return None
        payload = b"".join(chunks)
        header = {"op": "kvtier_pull",
                  "tokens": tokens_out,
                  "block_size": bs,
                  "weights_version": version,
                  "blocks": metas,
                  "payload_crc": zlib.crc32(payload)}
        return header, payload

    def submit_graft(self, header: dict, blocks: List[dict]) -> None:
        """Enqueue a pulled run for install on the scheduler thread —
        ``blocks`` is the crc-VERIFIED ``kv_migrate.unpack_blocks``
        output. Router/endpoint-thread safe."""
        with self._grafts_lock:
            self._grafts.append({"header": dict(header),
                                 "blocks": blocks})

    def install_grafts(self) -> int:
        """Scheduler-thread half of :meth:`submit_graft`: install each
        pulled block through the same verified path promotions use.
        Returns blocks installed."""
        with self._grafts_lock:
            pending, self._grafts = self._grafts, []
        installed = 0
        for g in pending:
            header, blocks = g["header"], g["blocks"]
            want = header.get("weights_version")
            if want != self.executor.params_version:
                continue            # fenced: the puller re-prefills
            toks = [int(t) for t in header.get("tokens", ())]
            bs = int(header.get("block_size", self.block_size))
            if bs != self.block_size:
                continue
            for bi, b in enumerate(blocks):
                run = tuple(toks[:(bi + 1) * bs])
                if len(run) < (bi + 1) * bs:
                    break
                entry = TierEntry(run, b["leaf_bytes"], b["crcs"],
                                  b["filled"], want)
                if not self._install_block(run, entry,
                                           entry.leaf_bytes, want):
                    continue        # exists already / pool full
                installed += 1
            if installed:
                self.pulls_in += 1
                self._emit("insert", toks, version=want)
        return installed

    def has_grafts(self) -> bool:
        with self._grafts_lock:
            return bool(self._grafts)

    # -- invalidation ---------------------------------------------------------
    def on_flush(self) -> None:
        """Weight-swap invalidation: host-tier entries under the old
        version can never promote again — drop them (disk entries stay;
        the version fence refuses them and the inspect tool can still
        audit them). Emits the index flush event."""
        self.host.clear()
        self._emit("flush")
        self._gauge_refresh()

    def stats(self) -> dict:
        return {"host_runs": self.host.count(),
                "host_bytes": self.host.bytes(),
                "disk_runs": (self.disk.count()
                              if self.disk is not None else 0),
                "demoted_blocks": self.demoted_blocks,
                "promoted_blocks": self.promoted_blocks,
                "demote_drops": self.demote_drops,
                "promote_drops": self.promote_drops,
                "corrupt_detected": self.corrupt_detected,
                "pulls_in": self.pulls_in}
