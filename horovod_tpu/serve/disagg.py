"""Prefill/decode disaggregated serving: dedicated pools, live
paged-KV block migration.

DistServe-style split of the multi-process fleet (ROADMAP item 2's
second half): a :class:`DisaggRouter` runs TWO worker-process pools
over the PR 11 substrate —

* the **prefill pool** computes prompt KV into paged blocks and emits
  the first token (TTFT is a prefill-pool property: its iterations are
  pure prefill, no resident decodes stretch them), then PARKS the
  sequence's blocks;
* the **decode pool** receives the blocks over the migration layer
  (serve/kv_migrate.py — crc-verified binary frames, replay-safe under
  the retry ladder), installs them through the reservation-gated
  admission path, fences on weight version, and continues decode
  BIT-IDENTICAL to colocated prefill+decode.

Each pool is a full :class:`~horovod_tpu.serve.proc_fleet.
ProcessFleetRouter` — spawn/registration, KV heartbeats, accrual
ejection, weight-gated respawn, per-pool metrics labels — so pool
health is the PR 11 machinery unchanged; only the REQUEST PATH is new.
Replica ids are fleet-wide (prefill ``0..P-1``, decode ``P..P+D-1``,
the ``rid_base`` convention) so chaos ``peer`` addressing and metric
labels never collide across pools.

One request's life (the dispatcher thread owns it end to end):

1. **prefill** — submitted to the least-loaded prefill replica with
   ``hold_kv`` and a budget of ONE token; the reply carries the first
   token (observed as the prefill-leg/TTFT histogram) and leaves the
   KV parked. Requests whose whole budget is one token resolve here —
   no migration, no decode-pool involvement.
2. **migrate** — a decode replica is chosen by free blocks + queue
   depth (the pool's load signal is exactly that composite) and the
   prefill worker is told to push: pack (pre-flight ledger check),
   binary frame, decode-side crc verify + version fence +
   reservation-gated install, fid-deduped against ladder replays.
3. **result** — the router blocks on the decode replica for the final
   token stream (fid-deduped like every dispatch wait).

Failure semantics ride the existing machinery, bounded and exactly
once: prefill death or a severed migration RE-PREFILLS elsewhere
exactly once (``max_attempts`` on the one-shot FleetHandle); decode
death re-enqueues to prefill the same way; a migration the decode pool
cannot hold sheds with capacity-scaled ``retry_after_ms``; version
mismatch at install re-prefills cleanly — stale-KV tokens are
unreachable. The ``serve.migrate`` chaos site (conn_reset / corrupt /
drop / delay) lands inside step 2 and the disagg soak
(serve/soak.py ``run_disagg_soak`` / ``evaluate_disagg``) proves the
matrix under seeded faults.

``/healthz`` (serve/http.py ``make_fleet_server`` over this router)
grows the per-pool breakdown: prefill/decode capacity + migration
backlog, 503 ONLY when admitting (prefill) capacity is zero — a
saturated decode pool degrades honestly instead of lying.

Metrics: ``hvd_serve_migrate_ms``, ``hvd_serve_migrate_bytes_total``,
``hvd_serve_migrations_total{outcome}``,
``hvd_serve_reprefills_total``, and per-pool leg histograms
``hvd_serve_pool_leg_ms{pool="prefill"|"decode"}`` (prefill = submit
-> first token, the router-visible TTFT; decode = migration done ->
final resolution).
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import metrics as obs_metrics
from ..trace import collect as _tr_collect
from . import wire
from .fleet import FLEET_REJECTED_HELP, FleetHandle
from .kvtier import prefer_holders
from .proc_fleet import (DEFAULT_SPAWN_TIMEOUT_S, ProcessFleetRouter,
                         SHED_BASE_MS, _PROMPT_WINDOW)
from .queue import Rejected

logger = logging.getLogger("horovod_tpu")

#: ctrl-RPC timeout for the migrate op: covers pack + the push ladder's
#: full retry budget + the decode install ack
MIGRATE_RPC_TIMEOUT_S = 45.0

MIGRATE_MS_HELP = ("KV-block migration end to end: prefill pack + "
                   "push + decode crc-verify/install (ms)")
MIGRATE_BYTES_HELP = "KV-block payload bytes migrated prefill->decode"
MIGRATIONS_HELP = ("migration attempts by outcome (ok / corrupt / "
                   "version_mismatch / rejected / unreachable / ...)")
REPREFILLS_HELP = ("requests re-prefilled after a prefill death, "
                   "severed migration, version fence or decode death "
                   "(each request re-prefills at most max_attempts-1 "
                   "times)")
POOL_LEG_HELP = ("disaggregated request legs by pool: prefill = "
                 "submit -> first token (TTFT), decode = migration "
                 "done -> final resolution (ms)")
MIGRATION_BACKLOG_HELP = (
    "requests parked in the migrate phase awaiting free decode "
    "capacity (the staging-buffer wait — the autoscale policy's "
    "decode-saturation signal)")


class _DisaggTracked:
    """Router-side bookkeeping for one in-flight disagg request."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "deadline",
                 "submitted_at", "handle", "temperature", "top_p",
                 "seed", "phase", "ttft_observed", "trace")

    def __init__(self, fid, prompt, max_new_tokens, deadline,
                 submitted_at, handle, temperature, top_p, seed):
        self.fid = fid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.submitted_at = submitted_at
        self.handle = handle
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.seed = int(seed)
        #: "prefill" | "migrate" | "decode" — the healthz migration
        #: backlog counts trackers sitting in "migrate"
        self.phase = "prefill"
        #: the TTFT histogram samples each REQUEST once, on its first
        #: successful prefill — a re-prefill after a failed migration
        #: must not contribute a second, migration-wait-inflated sample
        self.ttft_observed = False
        #: wire-form trace context (None = untraced); rides every
        #: phase RPC so the prefill, migration and decode spans join
        #: one tree (docs/tracing.md)
        self.trace: Optional[dict] = None


class DisaggRouter:
    """Two dedicated pools, one front door: ``submit`` returns the
    same :class:`FleetHandle` contract as the colocated routers
    (at-most-once, structured shed, drain), so serve/http.py's fleet
    server fronts it unchanged."""

    def __init__(self, prefill_replicas: int, decode_replicas: int, *,
                 kv_addr: str, kv_port: int,
                 prefill_worker: Optional[dict] = None,
                 decode_worker: Optional[dict] = None,
                 channel: Optional[str] = None, ns: str = "disagg",
                 interval_s: float = 0.25, suspect_s: float = 1.0,
                 auto_respawn: bool = True, max_attempts: int = 2,
                 migrate_attempts: int = 2,
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 drain_retry_after_ms: float = 1000.0,
                 chaos_plan=None, events_dir: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 max_inflight: int = 256,
                 python: Optional[str] = None):
        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError(
                f"a disaggregated fleet needs at least one replica per "
                f"pool; got prefill={prefill_replicas}, "
                f"decode={decode_replicas}")
        if max_attempts < 1 or migrate_attempts < 1:
            raise ValueError("max_attempts and migrate_attempts must "
                             "be >= 1")
        self.max_attempts = int(max_attempts)
        self.migrate_attempts = int(migrate_attempts)
        self.drain_retry_after_ms = float(drain_retry_after_ms)
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1; got {max_inflight}")
        self.max_inflight = int(max_inflight)
        # claimed FRESH here, once, before the pools construct: this
        # router is the routing process's one fleet, but its pools
        # get-or-create {pool=...} children (they must not clobber
        # each other), so the reset lives at the level that owns them
        # both — a second DisaggRouter in one process (a re-run soak)
        # must not inherit the first one's failover/migration counts,
        # or verdicts like failovers_only_kills go red on correct runs
        R = obs_metrics.get_registry()
        for fam in ("hvd_serve_replica_up", "hvd_serve_failovers_total",
                    "hvd_serve_requeued_total",
                    "hvd_serve_fleet_rejected_total",
                    "hvd_serve_router_ms", "hvd_serve_failover_ms",
                    "hvd_serve_respawns_total",
                    "hvd_serve_fleet_capacity",
                    "hvd_serve_migrate_ms",
                    "hvd_serve_migrate_bytes_total",
                    "hvd_serve_migrations_total",
                    "hvd_serve_reprefills_total",
                    "hvd_serve_pool_leg_ms",
                    "hvd_serve_pool_queue_free",
                    "hvd_serve_pool_kv_blocks_free",
                    "hvd_serve_pool_replicas_up",
                    "hvd_serve_pool_migration_backlog",
                    "hvd_trace_leg_ms", "hvd_trace_retained_total"):
            R.unregister(fam)
        common = dict(kv_addr=kv_addr, kv_port=kv_port,
                      channel=channel, interval_s=interval_s,
                      suspect_s=suspect_s, auto_respawn=auto_respawn,
                      max_attempts=max_attempts,
                      spawn_timeout_s=spawn_timeout_s,
                      drain_retry_after_ms=drain_retry_after_ms,
                      chaos_plan=chaos_plan, events_dir=events_dir,
                      log_dir=log_dir, python=python)
        #: the admitting pool: prompt KV is computed here (hold_kv
        #: submits with a 1-token budget), so ITS capacity is what
        #: gates admission fleet-wide
        self.prefill = ProcessFleetRouter(
            prefill_replicas, worker=prefill_worker,
            ns=f"{ns}.p", pool="prefill", rid_base=0, **common)
        #: the decode pool: receives migrated blocks, runs every
        #: decode iteration. Replica ids continue after the prefill
        #: pool's so peers/labels stay fleet-unique.
        self.decode = ProcessFleetRouter(
            decode_replicas, worker=decode_worker,
            ns=f"{ns}.d", pool="decode",
            rid_base=prefill_replicas, **common)
        #: distributed-tracing assembler, shared with BOTH pool
        #: routers (their health sweeps feed its clock samples, their
        #: eject paths its flight recorder) — the e2e owner is this
        #: router, so the merge lives here (trace/collect.py)
        self.tracer = _tr_collect.assembler_from_env("disagg")
        self.prefill.tracer = self.tracer
        self.decode.tracer = self.tracer
        self._lock = threading.Lock()
        self._inflight: Dict[int, _DisaggTracked] = {}
        self._reserved = 0
        self._fid_ns = os.urandom(4).hex()
        self._fids = itertools.count()
        self.draining = False
        self.started = False
        #: fleet-unique replica id allocator for runtime scale-ups:
        #: BOTH pools draw from one counter, so a prefill newcomer can
        #: never collide with the decode pool's rid_base range
        self._next_rid = int(prefill_replicas) + int(decode_replicas)
        self._recent_prompts: deque = deque(maxlen=_PROMPT_WINDOW)
        self._m_migrate_ms = R.histogram(
            "hvd_serve_migrate_ms", MIGRATE_MS_HELP)
        self._m_migrate_bytes = R.counter(
            "hvd_serve_migrate_bytes_total", MIGRATE_BYTES_HELP)
        self._m_migrations: Dict[str, object] = {}
        self._m_reprefills = R.counter(
            "hvd_serve_reprefills_total", REPREFILLS_HELP)
        self._m_leg = {
            pool: R.histogram("hvd_serve_pool_leg_ms", POOL_LEG_HELP,
                              {"pool": pool})
            for pool in ("prefill", "decode")}
        self._m_rejected = R.counter(
            "hvd_serve_fleet_rejected_total", FLEET_REJECTED_HELP,
            {"pool": "disagg"})
        self._m_backlog = R.gauge(
            "hvd_serve_pool_migration_backlog", MIGRATION_BACKLOG_HELP,
            {"pool": "decode"})

    def _count_migration(self, outcome: str) -> None:
        m = self._m_migrations.get(outcome)
        if m is None:
            m = obs_metrics.get_registry().counter(
                "hvd_serve_migrations_total", MIGRATIONS_HELP,
                {"outcome": outcome})
            self._m_migrations[outcome] = m
        m.inc()

    # -- events / lifecycle --------------------------------------------------
    def add_listener(self, fn) -> None:
        """Forward both pools' eject/respawn/readmit events (each
        event already carries the fleet-wide replica id)."""
        self.prefill.add_listener(fn)
        self.decode.add_listener(fn)

    def start(self) -> "DisaggRouter":
        if self.started:
            return self
        # spawn the pools CONCURRENTLY — worker startup (jax import +
        # warmup) dominates, and the pools are independent
        errs: List[BaseException] = []

        def boot(pool):
            try:
                pool.start()
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [threading.Thread(target=boot, args=(p,), daemon=True,
                                    name=f"hvd-disagg-boot-{p.pool}")
                   for p in (self.prefill, self.decode)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            for p in (self.prefill, self.decode):
                try:
                    p.close()
                except Exception:  # noqa: BLE001
                    pass
            raise RuntimeError(
                f"disagg fleet failed to start: {errs[0]}") from errs[0]
        self.started = True
        return self

    def close(self) -> None:
        for p in (self.prefill, self.decode):
            p.close()
        self.started = False

    def drain(self, timeout_s: float = 30.0) -> None:
        """Stop admitting, wait out the in-flight tail, resolve
        leftovers as rejected, stop both pools."""
        with self._lock:
            self.draining = True
        self.prefill.draining = True
        self.decode.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    break
            time.sleep(0.02)
        with self._lock:
            leftovers = list(self._inflight.values())
            self._inflight.clear()
        for tr in leftovers:
            if tr.handle._resolve(
                    "rejected",
                    retry_after_ms=self.drain_retry_after_ms):
                self._m_rejected.inc()
        self.close()

    # -- request path --------------------------------------------------------
    def _capacity_scale(self) -> float:
        """Shed hints scale with the ADMITTING pool's live capacity —
        the prefill pool's own formula, not a second copy of it."""
        return self.prefill._capacity_scale()

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int = 0) -> FleetHandle:
        """Admit a request into the disaggregated pipeline; returns a
        :class:`FleetHandle`. Synchronous :class:`Rejected` only when
        the fleet cannot accept at all (draining, zero PREFILL
        capacity, in-flight ceiling) — admission is gated on the
        prefill pool alone; decode saturation surfaces later as a
        structured shed with capacity-scaled retry-after."""
        if not self.started:
            raise RuntimeError("DisaggRouter.start() first")
        temperature, top_p = float(temperature), float(top_p)
        if not (temperature >= 0.0):
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy); got "
                f"{temperature!r}")
        if not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1]; got {top_p!r}")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}")
        t0 = time.monotonic()
        if self.draining:
            self._m_rejected.inc()
            self._trace_shed("draining")
            raise Rejected("fleet draining",
                           retry_after_ms=self.drain_retry_after_ms)
        if not any(r.state == "up"
                   for r in self.prefill.replicas.values()):
            # ADMITTING capacity is zero: nothing can compute prompt
            # KV — shed loudly (decode-pool health is irrelevant here)
            self._m_rejected.inc()
            self._trace_shed("zero_prefill_capacity")
            raise Rejected(
                "no live prefill replica (admitting capacity is zero)",
                retry_after_ms=SHED_BASE_MS * self._capacity_scale())
        if deadline_ms is None:
            deadline_ms = float(
                self.prefill.worker_cfg.get("deadline_ms", 30000.0))
        with self._lock:
            if self._reserved >= self.max_inflight:
                over = True
            else:
                over = False
                self._reserved += 1
        if over:
            self._m_rejected.inc()
            self._trace_shed("max_inflight")
            raise Rejected(
                f"fleet at max in-flight ({self.max_inflight})",
                retry_after_ms=SHED_BASE_MS * self._capacity_scale())
        with self._lock:
            self._recent_prompts.append(len(prompt))
        fid = next(self._fids)
        handle = FleetHandle(fid)
        handle.on_done = self._release_slot
        tr = _DisaggTracked(fid, [int(t) for t in prompt],
                            int(max_new_tokens),
                            t0 + float(deadline_ms) / 1000.0, t0,
                            handle, temperature, top_p, seed)
        if self.tracer is not None:
            tr.trace = self.tracer.start(rid=fid).to_wire()
        with self._lock:
            self._inflight[tr.fid] = tr
        threading.Thread(
            target=self._run_request, args=(tr,), daemon=True,
            name=f"hvd-disagg-dispatch-{fid}").start()
        return handle

    def _release_slot(self) -> None:
        with self._lock:
            if self._reserved > 0:
                self._reserved -= 1

    def _trace_shed(self, reason: str) -> None:
        """Synchronous front-door sheds never mint a FleetHandle, but
        the tail sampler must still see them: mint, flag, finish."""
        if self.tracer is None:
            return
        ctx = self.tracer.start(rid=None)
        self.tracer.mark(ctx, f"shed:{reason}")
        self.tracer.finish(ctx, "shed", e2e_ms=0.0)

    def migration_backlog(self) -> int:
        with self._lock:
            n = sum(1 for tr in self._inflight.values()
                    if tr.phase == "migrate")
        # refreshed on every read — healthz() and the autoscale signal
        # sampler both poll this, so the gauge tracks at poll cadence
        self._m_backlog.set(n)
        return n

    # -- runtime scaling (autoscale actuator) --------------------------------
    def _pool_named(self, pool: str) -> ProcessFleetRouter:
        if pool == "prefill":
            return self.prefill
        if pool == "decode":
            return self.decode
        raise ValueError(
            f"pool must be 'prefill' or 'decode'; got {pool!r}")

    def add_replica(self, pool: str, *, pre_admit=None,
                    timeout_s: Optional[float] = None) -> int:
        """Grow ``pool`` by one replica at runtime (the pool router's
        :meth:`ProcessFleetRouter.add_replica` admission discipline),
        with the replica id drawn from THIS router's fleet-unique
        allocator — a prefill newcomer must never collide with a
        decode rid for chaos ``peer`` addressing or metric labels."""
        p = self._pool_named(pool)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        return p.add_replica(rid=rid, pre_admit=pre_admit,
                             timeout_s=timeout_s)

    def remove_replica(self, pool: str, rid: Optional[int] = None, *,
                       graceful: bool = True,
                       timeout_s: float = 30.0) -> int:
        """Shrink ``pool`` by one replica at runtime; the graceful
        path waits out in-flight dispatches AND parked migration rows
        before terminating (see
        :meth:`ProcessFleetRouter.remove_replica`)."""
        return self._pool_named(pool).remove_replica(
            rid, graceful=graceful, timeout_s=timeout_s)

    def recent_prompt_lens(self) -> List[int]:
        """Prompt lengths of recently admitted requests (bounded
        window) — the autoscale signal plane's prompt-mix source."""
        with self._lock:
            return list(self._recent_prompts)

    def _run_request(self, tr: _DisaggTracked) -> None:
        try:
            err = self._pipeline(tr)
        except Exception as e:  # noqa: BLE001 — a dispatcher bug must
            # resolve the handle, never strand the client
            logger.error("disagg: request %d dispatcher error: %s",
                         tr.fid, e)
            err = Rejected(f"dispatcher error: {e}",
                           retry_after_ms=self.drain_retry_after_ms)
        with self._lock:
            self._inflight.pop(tr.fid, None)
        if err is not None:
            if tr.handle._resolve("rejected",
                                  retry_after_ms=err.retry_after_ms):
                self._m_rejected.inc()
        if self.tracer is not None and tr.trace is not None \
                and tr.handle.done():
            self.tracer.finish(tr.trace, tr.handle.status,
                               e2e_ms=tr.handle.latency_ms,
                               attempts=tr.handle.attempts)

    def _expired(self, tr: _DisaggTracked) -> bool:
        if (tr.deadline - time.monotonic()) > 0:
            return False
        tr.handle._resolve(
            "expired",
            latency_ms=(time.monotonic() - tr.submitted_at) * 1000.0)
        return True

    def _pipeline(self, tr: _DisaggTracked) -> Optional[Rejected]:
        """The whole request, owned by THIS dispatcher thread:
        prefill -> migrate -> result, with the bounded failure policy
        (re-prefill at most ``max_attempts - 1`` times, every exit a
        resolution or a Rejected the caller delivers)."""
        exclude: Optional[int] = None
        while True:
            st, val = self._phase_prefill(tr, exclude=exclude)
            if st == "resolved":
                return None
            if st == "shed":
                return val
            prep, pfid, _first = val
            tr.phase = "migrate"
            t_mig = time.monotonic()
            st2, val2 = self._phase_migrate(tr, prep, pfid)
            if st2 == "resolved":
                return None
            if st2 == "shed":
                return val2
            if st2 == "reprefill":
                self._m_reprefills.inc()
                if self.tracer is not None and tr.trace is not None:
                    self.tracer.mark(tr.trace, "failover")
                    now_w = time.time()
                    self.tracer.span(
                        tr.trace, "re_prefill",
                        now_w - (time.monotonic() - t_mig), now_w,
                        reason=str(val2))
                if tr.handle.attempts >= self.max_attempts:
                    return Rejected(
                        f"migration failed ({val2}) and re-prefill "
                        f"attempts are exhausted",
                        retry_after_ms=self.drain_retry_after_ms)
                logger.warning(
                    "disagg: request %d re-prefilling (%s)",
                    tr.fid, val2)
                exclude, tr.phase = prep.id, "prefill"
                continue
            drep, dfid = val2
            tr.phase = "decode"
            st3, val3 = self._phase_result(tr, drep, dfid)
            if st3 == "resolved":
                if tr.handle.latency_ms is not None:
                    self._m_leg["decode"].observe(
                        (time.monotonic() - t_mig) * 1000.0)
                return None
            # decode death / lost fid: re-enqueue to prefill
            self._m_reprefills.inc()
            if self.tracer is not None and tr.trace is not None:
                self.tracer.mark(tr.trace, "failover")
                now_w = time.time()
                self.tracer.span(
                    tr.trace, "re_prefill",
                    now_w - (time.monotonic() - t_mig), now_w,
                    reason=str(val3))
            if tr.handle.attempts >= self.max_attempts:
                return Rejected(
                    f"decode failed ({val3}) and re-prefill attempts "
                    f"are exhausted",
                    retry_after_ms=self.drain_retry_after_ms)
            logger.warning("disagg: request %d decode leg failed (%s) "
                           "— re-enqueueing to prefill", tr.fid, val3)
            exclude, tr.phase = None, "prefill"

    # -- phase 1: prefill ----------------------------------------------------
    def _phase_prefill(self, tr: _DisaggTracked,
                       exclude: Optional[int] = None) -> Tuple[str, object]:
        retry_hint: Optional[float] = None
        cands = self.prefill._candidates(exclude=exclude)
        matched: Dict[int, int] = {}
        if self.prefill.kvtier_index is not None and cands:
            # fleet KV tier: steer the prefill leg at the pool replica
            # holding the longest cached run of this prompt (advisory —
            # an evicted run just re-prefills)
            cands, matched = prefer_holders(
                cands, tr.prompt, self.prefill.kvtier_index,
                versions={r.id: r.weights_version for r in cands})
        for rep in cands:
            if self._expired(tr):
                return ("resolved", None)
            if self.draining:
                return ("shed", Rejected(
                    "fleet draining",
                    retry_after_ms=self.drain_retry_after_ms))
            remaining_ms = (tr.deadline - time.monotonic()) * 1000.0
            tr.handle.attempts += 1
            pfid = f"{self._fid_ns}.{tr.fid}.p{tr.handle.attempts}"
            try:
                kind, payload = self._submit_rpc(rep, pfid, tr,
                                                 remaining_ms)
            except Exception as e:  # noqa: BLE001 — ladder exhausted /
                # fatal wire fault: this replica is out, try the next
                logger.warning(
                    "disagg: prefill of request %d on replica %d "
                    "failed (%s); trying the next replica",
                    tr.fid, rep.id, e)
                continue
            if kind == "ctrl":
                ack = payload.get("ack")
                hint = payload.get("retry_after_ms")
                if ack in ("admit_dropped", "rejected"):
                    if hint is not None:
                        retry_hint = (hint if retry_hint is None
                                      else min(retry_hint, hint))
                    continue
                return ("shed", Rejected(
                    payload.get("error", f"bad ack {ack!r}"),
                    retry_after_ms=None))
            if matched.get(rep.id):
                # landed on the index-preferred holder
                self.prefill._m_kvtier_routed.inc()
            # prefill-side spans (queue_wait/prefill) piggyback on the
            # reply frame — merge them into the request's trace tree
            if self.tracer is not None and tr.trace is not None \
                    and payload.get("spans"):
                self.tracer.add_spans(tr.trace, payload["spans"])
            status = payload.get("status")
            toks = list(payload.get("tokens") or ())
            if status != "ok":
                # prefill-level expired/error is a clean terminal state
                tr.handle._resolve(
                    status or "error", tokens=toks,
                    latency_ms=(time.monotonic() - tr.submitted_at)
                    * 1000.0,
                    error=payload.get("error"), replica=rep.id)
                return ("resolved", None)
            # first token in hand: the router-visible TTFT — once per
            # REQUEST (a re-prefill's sample would fold the failed
            # migration's wait into a first-token claim)
            if not tr.ttft_observed:
                tr.ttft_observed = True
                self._m_leg["prefill"].observe(
                    (time.monotonic() - tr.submitted_at) * 1000.0)
            if len(toks) >= tr.max_new_tokens:
                # the whole budget was one token: done at prefill, no
                # migration — release the parked row and resolve
                self._release_parked(rep, pfid)
                tr.handle._resolve(
                    "ok", tokens=toks,
                    latency_ms=(time.monotonic() - tr.submitted_at)
                    * 1000.0, replica=rep.id)
                return ("resolved", None)
            return ("parked", (rep, pfid, toks))
        return ("shed", Rejected(
            "no healthy prefill replica available",
            retry_after_ms=(retry_hint or SHED_BASE_MS)
            * self._capacity_scale()))

    def _submit_rpc(self, rep, pfid: str, tr: _DisaggTracked,
                    remaining_ms: float) -> Tuple[str, dict]:
        msg = {"op": "submit", "fid": pfid, "prompt": tr.prompt,
               "max_new_tokens": 1, "deadline_ms": remaining_ms,
               "temperature": tr.temperature, "top_p": tr.top_p,
               "seed": tr.seed, "hold_kv": True}
        if tr.trace is not None:
            msg["trace"] = tr.trace
        return self.prefill._ladder.run(
            lambda: wire.two_frame_request(
                rep.addr, msg,
                reply_timeout=remaining_ms / 1000.0 + 35.0),
            what=f"prefill(fid {pfid})",
            site="serve.dispatch", plane="serve",
            abort=tr.handle.done)

    # -- phase 2: migrate ----------------------------------------------------
    def _decode_candidates(self) -> List:
        """Decode replicas by migration headroom: fewest (blocks in
        use, row-normalized) + queue depth first — exactly the
        worker's ``load()`` composite, which is the free-blocks/queue-
        depth signal the health poll caches."""
        return self.decode._candidates()

    def _ctrl_rpc(self, rep, msg: dict,
                  timeout_s: float = 10.0) -> dict:
        sock = wire.connect(rep.addr, timeout=2.0)
        try:
            wire.send_msg(sock, msg)
            return wire.recv_msg(sock, timeout=timeout_s)
        finally:
            sock.close()

    def _release_parked(self, rep, pfid: str) -> None:
        try:
            self._ctrl_rpc(rep, {"op": "release", "fid": pfid})
        except (wire.DispatchConnError, wire.DispatchError, OSError):
            # resilience: exempt (best-effort cleanup — a parked row
            # the release never reaches is freed by the worker's TTL
            # reaper; correctness never depends on this RPC landing)
            pass

    def _phase_migrate(self, tr: _DisaggTracked, prep,
                       pfid: str) -> Tuple[str, object]:
        """Push the parked blocks to a decode replica. The migrate op
        is a single ctrl RPC to the PREFILL worker (the push leg
        inside it carries its own retry ladder + serve.migrate chaos).

        Failure policy: corrupt-on-arrival / a dead decode target
        retry with a fresh pack against the next candidate (bounded
        by ``migrate_attempts``); a dead prefill worker re-prefills;
        and a decode pool that is merely FULL makes the migration
        WAIT — the parked row is a staging buffer, and re-shedding
        (or worse, re-prefilling) a computed prompt because decode
        capacity is momentarily busy would turn saturation into
        repeated prefill work. The wait is bounded: once the
        remaining deadline dips under the margin the decode leg still
        needs, the request sheds with the decode pool's own retry
        hint (capacity-scaled, never silent)."""
        retry_hint: Optional[float] = None
        hard_fails = 0
        mseq = 0
        idx = 0
        # keep enough runway for the decode leg itself: waiting for
        # capacity may burn at most 3/4 of the client's budget
        margin_s = max(2.0, 0.25 * (tr.deadline - tr.submitted_at))
        while hard_fails < self.migrate_attempts:
            if self._expired(tr):
                self._release_parked(prep, pfid)
                return ("resolved", None)
            if self.draining:
                self._release_parked(prep, pfid)
                return ("shed", Rejected(
                    "fleet draining",
                    retry_after_ms=self.drain_retry_after_ms))
            cands = self._decode_candidates()
            if not cands:
                # the whole decode pool is down/ejected: wait for a
                # respawn inside the margin, then shed
                if (tr.deadline - time.monotonic()) <= margin_s:
                    break
                time.sleep(0.1)
                continue
            drep = cands[idx % len(cands)]
            idx += 1
            mseq += 1
            dfid = f"{pfid}.m{mseq}"
            remaining_ms = (tr.deadline - time.monotonic()) * 1000.0
            t0 = time.monotonic()
            try:
                ack = self._ctrl_rpc(prep, {
                    "op": "migrate", "fid": pfid, "dfid": dfid,
                    "target": [drep.addr[0], drep.addr[1]],
                    "peer": drep.id,
                    "max_new_tokens": tr.max_new_tokens,
                    "deadline_ms": remaining_ms,
                }, timeout_s=MIGRATE_RPC_TIMEOUT_S)
            except (wire.DispatchConnError, wire.DispatchError) as e:
                # the PREFILL worker died / stalled mid-migration: its
                # parked row dies with it (or TTL-reaps) — re-prefill
                # elsewhere
                self._count_migration("unreachable")
                return ("reprefill", f"prefill {prep.id} unreachable "
                                     f"mid-migration: {e}")
            if ack.get("ack") == "migrated":
                # park/migrate_push spans ride the migrate ack (they
                # post-date the prefill reply's drain)
                if self.tracer is not None and tr.trace is not None \
                        and ack.get("spans"):
                    self.tracer.add_spans(tr.trace, ack["spans"])
                self._count_migration("ok")
                self._m_migrate_ms.observe(
                    float(ack.get("ms")
                          or (time.monotonic() - t0) * 1000.0))
                self._m_migrate_bytes.inc(int(ack.get("bytes") or 0))
                return ("migrated", (drep, dfid))
            reason = str(ack.get("reason", ack.get("ack", "unknown")))
            self._count_migration(reason)
            if reason in ("not_parked", "source_corrupt"):
                # the parked KV is gone or untrusted: only a fresh
                # prefill can answer this request
                return ("reprefill", reason)
            if reason == "version_mismatch":
                # decode runs a different weight version than the KV
                # was computed under: NEVER install — re-prefill once
                # the pools converge (the subscriber gate)
                self._release_parked(prep, pfid)
                return ("reprefill", reason)
            if reason == "rejected":
                # decode capacity: WAIT on the parked row (every
                # candidate full => sleep out the hint inside the
                # margin), never re-prefill over a full pool
                hint = float(ack.get("retry_after_ms")
                             or SHED_BASE_MS)
                retry_hint = (hint if retry_hint is None
                              else min(retry_hint, hint))
                if idx % len(cands) == 0:   # a full sweep said no
                    if (tr.deadline - time.monotonic()) <= margin_s:
                        break
                    time.sleep(min(hint, 250.0) / 1000.0)
                continue
            if reason in ("migrate_corrupt", "unreachable", "stalled"):
                # in-flight corruption (block crc caught it on
                # arrival) or a dead decode target: retry with a
                # fresh pack / the next candidate
                hard_fails += 1
                continue
            logger.warning(
                "disagg: request %d migration to decode %d failed "
                "(%s: %s)", tr.fid, drep.id, reason, ack.get("detail"))
            hard_fails += 1
            continue
        self._release_parked(prep, pfid)
        return ("shed", Rejected(
            "no decode replica could accept the migration",
            retry_after_ms=(retry_hint or SHED_BASE_MS)
            * self._capacity_scale()))

    # -- phase 3: result -----------------------------------------------------
    def _phase_result(self, tr: _DisaggTracked, drep,
                      dfid: str) -> Tuple[str, object]:
        if self._expired(tr):
            return ("resolved", None)
        remaining_ms = (tr.deadline - time.monotonic()) * 1000.0
        msg = {"op": "result", "fid": dfid,
               "deadline_ms": remaining_ms}
        if tr.trace is not None:
            msg["trace"] = tr.trace
        try:
            kind, payload = self.decode._ladder.run(
                lambda: wire.two_frame_request(
                    drep.addr, msg,
                    reply_timeout=remaining_ms / 1000.0 + 35.0),
                what=f"result(fid {dfid})",
                site="serve.dispatch", plane="serve",
                abort=tr.handle.done)
        except Exception as e:  # noqa: BLE001 — decode death: the
            # ladder exhausted against a gone replica
            return ("lost", f"decode {drep.id} unreachable: {e}")
        if kind == "ctrl":
            return ("lost", f"decode {drep.id}: "
                            f"{payload.get('ack', 'bad ack')}")
        # decode-side spans (migrate_install/decode) ride the result
        if self.tracer is not None and tr.trace is not None \
                and payload.get("spans"):
            self.tracer.add_spans(tr.trace, payload["spans"])
        tr.handle._resolve(
            payload.get("status", "error"),
            tokens=payload.get("tokens") or (),
            latency_ms=(time.monotonic() - tr.submitted_at) * 1000.0,
            error=payload.get("error"), replica=drep.id)
        return ("resolved", None)

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
            backlog = sum(1 for tr in self._inflight.values()
                          if tr.phase == "migrate")
        p, d = self.prefill.stats(), self.decode.stats()
        return {
            "inflight": inflight,
            "migration_backlog": backlog,
            "draining": self.draining,
            "reprefills": int(self._m_reprefills.value),
            "rejected": int(self._m_rejected.value),
            "migrate_bytes": int(self._m_migrate_bytes.value),
            "prefill": p, "decode": d,
            "replicas_up": p["replicas_up"] + d["replicas_up"],
            "failovers": p["failovers"] + d["failovers"],
            "respawns": p.get("respawns", 0) + d.get("respawns", 0),
            "duplicates_suppressed": (p["duplicates_suppressed"]
                                      + d["duplicates_suppressed"]),
            "replicas": {**p["replicas"], **d["replicas"]},
        }

    def metrics_snapshots(self, timeout: float = 2.0) -> List[dict]:
        """Both pools' worker metrics snapshots, for the front door's
        ``/metrics?fleet=1`` merge (worker labels already carry
        ``pool=...`` so the merged series stay distinguishable)."""
        return (self.prefill.metrics_snapshots(timeout=timeout)
                + self.decode.metrics_snapshots(timeout=timeout))

    def healthz(self) -> dict:
        """The front door's aggregate payload with the per-pool
        breakdown: prefill/decode capacity + migration backlog, and
        the 503 decision gated on ADMITTING (prefill) capacity only —
        see ``fleet.aggregate_healthz``."""
        from .fleet import aggregate_healthz
        infos = {}
        infos.update(self.prefill.healthz_infos())
        infos.update(self.decode.healthz_infos())
        pools = {
            "prefill": {"replicas": list(self.prefill.replicas),
                        "admitting": True},
            "decode": {"replicas": list(self.decode.replicas),
                       "admitting": False,
                       "migration_backlog": self.migration_backlog()},
        }
        return aggregate_healthz(
            infos, draining=self.draining,
            retry_after_ms=SHED_BASE_MS * self._capacity_scale(),
            pools=pools)
