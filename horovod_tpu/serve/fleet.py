"""Health-aware fleet router: N replicas, one front door, no lost
requests.

The serve plane's answer to ROADMAP item 5 ("heavy traffic that
survives bad days"): a minimal in-process router in front of N
``ShardedExecutor``/``ContinuousBatcher`` replicas, driven by the SAME
accrual heartbeat semantics the training plane's failure detector uses
(chaos/detector.py ``AccrualTracker``):

* **Detection in O(heartbeat), not O(request timeout).** Every replica
  batcher calls its heartbeat hook once per scheduling iteration; the
  router sweeps the sequence numbers on its health thread and ejects a
  replica the moment its heartbeat age crosses ``suspect_s`` (or its
  scheduler thread is observably dead). Clients never wait out a
  30-second deadline to learn a replica died 200 ms in.
* **At-most-once completion.** Every request the router accepts is
  either answered exactly once or rejected with ``retry_after_ms`` —
  never silently dropped, never answered twice. An ejected replica's
  in-flight requests are re-enqueued onto a healthy sibling exactly
  once; a late answer from a slow (not dead) replica that already
  failed over is suppressed (``duplicates_suppressed``), because the
  ``FleetHandle`` is one-shot.
* **Ejection is not the end.** A crashed replica is rebuilt (fresh
  batcher over the surviving executor), re-warmed (every launchable
  shape recompiled — a no-op when the jit cache is hot), re-adopts the
  NEWEST streamed weight version (redist/stream.py
  ``WeightSubscriber.peek_version``), and only then re-admitted; a
  slow replica that resumes heartbeating is re-admitted through the
  same weight gate without a rebuild — in both cases with its radix
  prefix cache flushed first, so KV computed under the pre-ejection
  weights can never be matched by a post-re-admission prompt.
* **Drain on SIGTERM.** ``drain()`` (or the installed SIGTERM handler)
  stops admitting — new submits are shed with retry-after — waits out
  the in-flight tail, then resolves any stragglers as rejected; the
  process can die without a request ever going unanswered.

Chaos crosses this layer at ``serve.route`` (partition the router from
one replica: its dispatches are refused for the window and the router
fails over) and ``serve.admit`` (queue-door delay/drop, absorbed by
re-dispatch); ``serve.step``/``serve.kv`` land inside the replicas
(serve/batcher.py). All guards are byte-identical pass-throughs when
disarmed.

Metrics: ``hvd_serve_replica_up{replica}``,
``hvd_serve_failovers_total``, ``hvd_serve_requeued_total``,
``hvd_serve_fleet_rejected_total``, router-leg latency histograms
``hvd_serve_router_ms{leg="dispatch"|"e2e"}`` and
``hvd_serve_failover_ms`` (replica death -> ejection+re-enqueue done).
"""
from __future__ import annotations

import itertools
import logging
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..chaos import inject as _chaos
from ..chaos.detector import AccrualTracker
from ..obs import metrics as obs_metrics

#: metric help strings shared with the multi-process router
#: (proc_fleet.py) — single-sourced so the copies cannot drift
#: (metric-help lint; the Retry-After rounding drifted between copies
#: once already, same failure mode).
REPLICA_UP_HELP = "1 while this replica is admitted to the fleet"
FAILOVERS_HELP = ("replicas ejected (heartbeat suspicion or dead "
                  "scheduler)")
REQUEUED_HELP = "in-flight requests re-enqueued off an ejected replica"
FLEET_REJECTED_HELP = ("requests rejected fleet-wide (always with "
                       "retry_after_ms)")
ROUTER_MS_HELP = ("router leg latency: dispatch (pick+enqueue) and e2e "
                  "(submit -> resolution)")
FAILOVER_MS_HELP = ("replica death -> ejection + in-flight re-enqueued "
                    "(ms)")

from ..trace.spans import get_recorder as _trace_recorder
from .batcher import ContinuousBatcher
from .kv_migrate import MigrateCorrupt, unpack_blocks
from .kvtier import FleetRadixIndex, prefer_holders
from .kvtier.tier import PULLS_HELP, ROUTED_HELP
from .queue import AdmissionQueue, AdmitDropped, Rejected, ServeHandle

logger = logging.getLogger("horovod_tpu")


class FleetHandle:
    """Client-side completion handle for a fleet request. One-shot:
    ``status`` is "pending" | "ok" | "expired" | "error" | "rejected"
    (rejected always carries ``retry_after_ms``). ``resolutions``
    counts ACCEPTED resolutions and can only ever reach 1 — the
    at-most-once evidence the soak verdict audits."""

    def __init__(self, fid: int):
        self.fid = fid
        self.status = "pending"
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.latency_ms: Optional[float] = None
        self.retry_after_ms: Optional[float] = None
        #: replica that produced the accepted answer
        self.replica: Optional[int] = None
        #: times this request was (re)dispatched to a replica
        self.attempts = 0
        self.resolutions = 0
        #: optional router hook invoked exactly once, AFTER the
        #: accepted resolution (the process fleet releases its
        #: in-flight reservation here); never called for suppressed
        #: duplicates
        self.on_done: Optional[Callable[[], None]] = None
        self._event = threading.Event()
        self._rlock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _resolve(self, status: str, tokens: Sequence[int] = (),
                 latency_ms: Optional[float] = None,
                 error: Optional[str] = None,
                 retry_after_ms: Optional[float] = None,
                 replica: Optional[int] = None) -> bool:
        """One-shot; returns False when already resolved (the caller
        counts that as a suppressed duplicate)."""
        with self._rlock:
            if self._event.is_set():
                return False
            self.status = status
            self.tokens = list(tokens)
            self.error = error
            self.latency_ms = latency_ms
            self.retry_after_ms = retry_after_ms
            self.replica = replica
            self.resolutions += 1
            self._event.set()
        cb = self.on_done
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a hook must not mask
                pass           # the resolution it observes
        return True


class _Tracked:
    """Router-side bookkeeping for one in-flight fleet request.

    Sampling state (temperature/top-p/seed) rides along because
    failover RE-SUBMITS from this record: per-row seeded streams are
    deterministic across re-dispatch (the rng counter replays from 0
    on a re-prefill and reproduces the original stream), so a sampled
    request fails over with the same at-most-once bookkeeping as a
    greedy one."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "deadline",
                 "submitted_at", "handle", "rid", "inner",
                 "temperature", "top_p", "seed", "trace")

    def __init__(self, fid, prompt, max_new_tokens, deadline,
                 submitted_at, handle, temperature=0.0, top_p=1.0,
                 seed=0):
        self.fid = fid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline            # absolute monotonic seconds
        self.submitted_at = submitted_at
        self.handle = handle
        self.rid: Optional[int] = None      # current replica
        self.inner: Optional[ServeHandle] = None
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.seed = int(seed)
        #: wire-form trace context (None = untraced) — survives
        #: failover so the re-dispatch joins the same trace tree
        self.trace: Optional[dict] = None


class Replica:
    """One serving replica: an executor plus the queue/batcher pair the
    router (re)builds around it. The executor — params, device KV
    cache, jit cache — survives restarts; the scheduler state does not
    (its in-flight work was already failed over)."""

    def __init__(self, rid: int, executor, *,
                 buckets: Sequence[int] = (32, 128, 512),
                 eos_id: Optional[int] = None,
                 max_queue: int = 64,
                 deadline_ms: float = 30000.0,
                 kv_crc: Optional[bool] = None,
                 on_kv_corrupt: str = "reprefill",
                 subscriber=None,
                 weights_interval_s: float = 0.25,
                 draft_executor=None,
                 spec_k: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_tier: Optional[bool] = None,
                 kvtier_host_mb: Optional[int] = None,
                 kvtier_dir: Optional[str] = None):
        if getattr(executor, "replica_id", None) != rid:
            raise ValueError(
                f"replica {rid}: its executor must be constructed with "
                f"replica_id={rid} (got "
                f"{getattr(executor, 'replica_id', None)!r}) so metric "
                f"series are labeled per replica, not clobbered "
                f"fleet-wide")
        self.id = int(rid)
        self.executor = executor
        self.buckets = tuple(buckets)
        self.eos_id = eos_id
        self.max_queue = int(max_queue)
        self.deadline_ms = float(deadline_ms)
        self.kv_crc = kv_crc   # None defers to HOROVOD_SERVE_KV_CRC
        self.on_kv_corrupt = on_kv_corrupt
        #: speculative decoding pair: the draft executor survives
        #: rebuilds exactly like the target (its params and jit cache
        #: are device state; its throwaway KV re-syncs per sequence)
        self.draft_executor = draft_executor
        self.spec_k = spec_k         # None defers to HOROVOD_SERVE_SPEC_K
        self.prefix_cache = prefix_cache   # None defers to env knob
        # fleet KV tier passthrough (None defers to the env knobs)
        self.kv_tier = kv_tier
        self.kvtier_host_mb = kvtier_host_mb
        self.kvtier_dir = kvtier_dir
        #: optional WeightSubscriber (redist/stream.py): polled by the
        #: live batcher, and the router's re-admission gate
        self.subscriber = subscriber
        self.weights_interval_s = float(weights_interval_s)
        self.queue: Optional[AdmissionQueue] = None
        self.batcher: Optional[ContinuousBatcher] = None
        #: "init" | "up" | "down" | "warming"
        self.state = "init"
        self.restarts = 0
        #: heartbeat ledger the router's AccrualTracker sweeps
        self.hb_seq = 0
        self.hb_time = time.monotonic()
        self._iters_base = 0    # cumulative iterations across rebuilds
        self._submits_base = 0  # cumulative queue submits, same reason

    def _heartbeat(self) -> None:
        self.hb_seq += 1
        self.hb_time = time.monotonic()

    def build(self) -> None:
        """(Re)create the queue/batcher pair. Iteration numbering
        CONTINUES across rebuilds, so chaos faults addressed at an
        iteration fire at most once per address even through a
        crash/restart cycle."""
        if self.batcher is not None:
            self._iters_base = self.batcher.iterations + 1
            self._submits_base = self.queue._submits
        self.queue = AdmissionQueue(
            max_queue=self.max_queue,
            default_deadline_ms=self.deadline_ms,
            replica_id=self.id)
        # the serve.admit chaos counter continues across rebuilds just
        # like the iteration counter: an exact-'at' admit fault fires at
        # most once per address even through a crash/restart cycle
        self.queue._submits = self._submits_base
        self.batcher = ContinuousBatcher(
            self.executor, self.queue, buckets=self.buckets,
            eos_id=self.eos_id, replica_id=self.id,
            kv_crc=self.kv_crc, on_kv_corrupt=self.on_kv_corrupt,
            draft_executor=self.draft_executor, spec_k=self.spec_k,
            prefix_cache=self.prefix_cache, kv_tier=self.kv_tier,
            kvtier_host_mb=self.kvtier_host_mb,
            kvtier_dir=self.kvtier_dir)
        self.batcher.iterations = self._iters_base
        self.batcher.heartbeat = self._heartbeat
        if self.subscriber is not None:
            self.batcher.attach_weights(
                self.subscriber, min_interval_s=self.weights_interval_s)


def aggregate_healthz(replicas_info: Dict[int, dict], *,
                      draining: bool,
                      retry_after_ms: float,
                      pools: Optional[Dict[str, dict]] = None) -> dict:
    """Build the aggregate fleet ``/healthz`` payload every router
    flavor serves through ``make_fleet_server`` — one place for the
    contract (per-replica state + live capacity, ``ok`` False at zero
    capacity), so the in-process, multi-process and disaggregated
    faces cannot drift.

    ``replicas_info[rid]`` supplies ``state``/``up``/``draining``/
    ``queue_depth``/``weights_version``/``restarts``/``queue_free``
    and, when paged, ``kv_blocks_total``/``kv_blocks_in_use`` plus the
    prefix cache's ``prefix_tokens_resident``/
    ``prefix_tokens_evictable`` TOKEN counts (the fleet KV tier's and
    the autoscale signals' shared definition of cacheable capacity —
    blocks are a pool-shape detail, tokens are the unit prompts are
    measured in); each router sources those from what it actually has
    (live batchers vs the health-poll cache).

    ``pools`` (disaggregated serving, serve/disagg.py) names the
    per-pool breakdown: ``pools[name]`` carries ``replicas`` (the rids
    belonging to that pool), ``admitting`` (True for the pool whose
    capacity gates ADMISSION — prefill) and any extra facts to surface
    (``migration_backlog``). The payload then grows a ``pools``
    section with each pool's own capacity rollup, and ``ok`` goes
    False ONLY when an admitting pool's live capacity is zero: a
    saturated decode pool degrades honestly (``degraded`` names it)
    but the front door keeps answering 200 — new prompts can still be
    admitted, parked and migrated once decode capacity frees.

    PENDING capacity counts toward liveness: a replica mid-spawn or
    mid-warmup (state ``spawning``/``respawning`` — a scale-up
    newcomer or a respawn in flight) is capacity that is seconds away,
    so the front door answers 200 with the pool listed in
    ``degraded`` rather than 503 — a scale event must never flap the
    front door into telling clients the fleet is gone.
    """
    reps: Dict[str, dict] = {}
    q_free = blocks_free = 0
    pend_n = 0
    tok_resident = tok_evictable = 0
    per_rid: Dict[int, Tuple[int, int, int]] = {}
    for rid, info in replicas_info.items():
        entry = {k: info.get(k) for k in
                 ("state", "up", "draining", "queue_depth",
                  "weights_version", "restarts")}
        rq = rb = 0
        pending = 1 if str(info.get("state")) in (
            "spawning", "respawning") else 0
        pend_n += pending
        if info.get("up"):
            rq = max(int(info.get("queue_free") or 0), 0)
            q_free += rq
            if info.get("kv_blocks_total") is not None:
                rb = (int(info["kv_blocks_total"])
                      - int(info.get("kv_blocks_in_use") or 0))
                blocks_free += rb
                entry["kv_blocks_in_use"] = info.get("kv_blocks_in_use")
            if info.get("prefix_tokens_resident") is not None:
                entry["prefix_tokens_resident"] = \
                    int(info["prefix_tokens_resident"])
                entry["prefix_tokens_evictable"] = \
                    int(info.get("prefix_tokens_evictable") or 0)
                tok_resident += entry["prefix_tokens_resident"]
                tok_evictable += entry["prefix_tokens_evictable"]
        per_rid[rid] = (rq, rb, pending)
        reps[str(rid)] = entry
    up_n = sum(1 for r in reps.values() if r["up"])
    out = {
        "ok": ((up_n > 0 and q_free > 0) or pend_n > 0)
        and not draining,
        "draining": draining,
        "replicas": reps,
        "capacity": {"replicas_up": up_n,
                     "replicas_total": len(reps),
                     "replicas_pending": pend_n,
                     "queue_free": q_free,
                     "kv_blocks_free": blocks_free,
                     "prefix_tokens_resident": tok_resident,
                     "prefix_tokens_evictable": tok_evictable},
        "retry_after_ms": retry_after_ms,
    }
    if pools:
        out["pools"] = {}
        admit_free = 0
        admit_pending = 0
        any_admitting = False
        degraded = []
        for name, spec in pools.items():
            rids = list(spec.get("replicas", ()))
            pq = sum(per_rid.get(r, (0, 0, 0))[0] for r in rids)
            pb = sum(per_rid.get(r, (0, 0, 0))[1] for r in rids)
            ppend = sum(per_rid.get(r, (0, 0, 0))[2] for r in rids)
            pup = sum(1 for r in rids
                      if reps.get(str(r), {}).get("up"))
            entry = {"replicas": [str(r) for r in rids],
                     "replicas_up": pup,
                     "replicas_pending": ppend,
                     "queue_free": pq, "kv_blocks_free": pb,
                     "admitting": bool(spec.get("admitting", False))}
            for k, v in spec.items():
                if k not in ("replicas", "admitting"):
                    entry[k] = v
            out["pools"][name] = entry
            if entry["admitting"]:
                any_admitting = True
                admit_free += pq
                admit_pending += ppend
            if pup == 0 or pq == 0:
                degraded.append(name)
        if any_admitting:
            # 503 only when ADMITTING capacity (prefill) is zero AND
            # none is pending — a saturated/down decode pool degrades,
            # and a pool mid-scale-up keeps answering 200, never lies
            out["ok"] = (admit_free > 0 or admit_pending > 0) \
                and not draining
        if degraded:
            out["degraded"] = sorted(degraded)
    return out


class FleetRouter:
    """Routes requests across replicas, ejects the sick, re-admits the
    recovered. See the module docstring for the contract."""

    def __init__(self, replicas: Sequence[Replica], *,
                 interval_s: float = 0.25, suspect_s: float = 1.0,
                 auto_restart: bool = True, max_attempts: int = 2,
                 rewarm_timeout_s: float = 30.0,
                 drain_retry_after_ms: float = 1000.0):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        if suspect_s <= interval_s:
            raise ValueError(
                f"suspect_s ({suspect_s}) must exceed the heartbeat "
                f"interval ({interval_s}) — a threshold under one "
                f"period suspects every healthy replica")
        ids = [r.id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas: Dict[int, Replica] = {r.id: r for r in replicas}
        self.interval_s = float(interval_s)
        self.suspect_s = float(suspect_s)
        self.auto_restart = bool(auto_restart)
        self.max_attempts = int(max_attempts)
        self.rewarm_timeout_s = float(rewarm_timeout_s)
        self.drain_retry_after_ms = float(drain_retry_after_ms)
        self._tracker = AccrualTracker(
            ids, interval_s=interval_s, suspect_s=suspect_s)
        self._lock = threading.Lock()
        self._inflight: Dict[int, _Tracked] = {}
        self._fids = itertools.count()
        self._dispatches: Dict[int, int] = {r: 0 for r in ids}
        self._restarting: set = set()
        self._listeners: List[Callable[[dict], None]] = []
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self.draining = False
        self.started = False
        # -- bookkeeping the soak verdict audits
        self.duplicates_suppressed = 0
        self.last_failover_ms: Optional[float] = None
        #: fleet radix index (serve/kvtier/): created at start() when
        #: any replica runs a KV tier; None keeps every kvtier branch
        #: on the dispatch path dead
        self.kvtier_index: Optional[FleetRadixIndex] = None
        self.kvtier_pull_corrupt = 0
        # -- metrics (claimed fresh: one router per serving process)
        R = obs_metrics.get_registry()
        for fam in ("hvd_serve_replica_up", "hvd_serve_failovers_total",
                    "hvd_serve_requeued_total",
                    "hvd_serve_fleet_rejected_total",
                    "hvd_serve_router_ms", "hvd_serve_failover_ms",
                    "hvd_serve_kvtier_routed_total",
                    "hvd_serve_kvtier_pulls_total"):
            R.unregister(fam)
        self._m_kvtier_routed = R.counter(
            "hvd_serve_kvtier_routed_total", ROUTED_HELP)
        self._m_kvtier_pulls = R.counter(
            "hvd_serve_kvtier_pulls_total", PULLS_HELP)
        self._m_up = {
            r: R.gauge("hvd_serve_replica_up", REPLICA_UP_HELP,
                       {"replica": str(r)}) for r in ids}
        self._m_failovers = R.counter(
            "hvd_serve_failovers_total", FAILOVERS_HELP)
        self._m_requeued = R.counter(
            "hvd_serve_requeued_total", REQUEUED_HELP)
        self._m_rejected = R.counter(
            "hvd_serve_fleet_rejected_total", FLEET_REJECTED_HELP)
        self._m_router = {
            leg: R.histogram(
                "hvd_serve_router_ms", ROUTER_MS_HELP, {"leg": leg})
            for leg in ("dispatch", "e2e")}
        self._m_failover_ms = R.histogram(
            "hvd_serve_failover_ms", FAILOVER_MS_HELP)

    # -- events --------------------------------------------------------------
    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """``fn(event)`` on eject / readmit / restart-failed; events
        carry ``{"event", "replica", "t", ...}`` (the soak's ledger)."""
        with self._lock:
            self._listeners.append(fn)

    def _emit(self, event: str, rid: int, **kw) -> None:
        ev = dict(kw, event=event, replica=rid, t=time.time())
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(ev)
            except Exception:  # noqa: BLE001
                pass

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self.started:
            return self
        for rep in self.replicas.values():
            rep.build()
            rep.batcher.warmup()
        # warmup all replicas BEFORE any takes traffic (first compile
        # behind the door, never under a request), then open together
        for rep in self.replicas.values():
            rep.batcher.start()
            rep.state = "up"
            self._m_up[rep.id].set(1)
        # fleet radix index over whatever block size the tiered
        # replicas share (one model config per fleet)
        for rep in self.replicas.values():
            kt = rep.batcher.kvtier
            if kt is not None:
                self.kvtier_index = FleetRadixIndex(kt.block_size)
                break
        self._stop.clear()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="hvd-fleet-health")
        self._health_thread.start()
        self.started = True
        return self

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        for rep in self.replicas.values():
            if rep.batcher is not None:
                rep.batcher.stop()
        self.started = False

    def install_sigterm(self, drain_timeout_s: float = 30.0) -> None:
        """SIGTERM -> drain: stop admitting, finish the in-flight tail,
        answer stragglers with retry-after — the orderly-shutdown leg
        of the no-silent-drop contract. Main thread only."""
        def _handler(signum, frame):
            logger.info("fleet: SIGTERM — draining")
            self.drain(timeout_s=drain_timeout_s)
        signal.signal(signal.SIGTERM, _handler)

    def drain(self, timeout_s: float = 30.0) -> None:
        """Stop admitting (submits shed with retry-after), wait for the
        in-flight tail, resolve leftovers as rejected, stop replicas."""
        with self._lock:
            # under the lock so it serializes against _dispatch's
            # insertion check: every in-flight request is either in the
            # snapshot below or was rejected with retry-after
            self.draining = True
        for rep in self.replicas.values():
            if rep.batcher is not None:
                rep.batcher.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._inflight:
                    break
            time.sleep(0.02)
        with self._lock:
            leftovers = list(self._inflight.values())
            self._inflight.clear()
        for tr in leftovers:
            if tr.handle._resolve(
                    "rejected", retry_after_ms=self.drain_retry_after_ms):
                self._m_rejected.inc()
        self._drained.set()
        self.close()

    # -- request path --------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int = 0) -> FleetHandle:
        """Route a request to a healthy replica; returns a
        :class:`FleetHandle`. Raises :class:`Rejected` (with
        ``retry_after_ms``) when no replica can take it — the
        fleet-level load-shed contract. Sampling controls
        (``temperature``/``top_p``/``seed``) ride the at-most-once
        bookkeeping: per-row seeded streams are deterministic across
        re-dispatch, so a mid-request failover reproduces the same
        sampled tokens."""
        if not self.started:
            raise RuntimeError("FleetRouter.start() first")
        t0 = time.monotonic()
        if self.draining:
            self._m_rejected.inc()
            raise Rejected("fleet draining",
                           retry_after_ms=self.drain_retry_after_ms)
        if deadline_ms is None:
            deadline_ms = min(r.deadline_ms
                              for r in self.replicas.values())
        fid = next(self._fids)
        handle = FleetHandle(fid)
        tr = _Tracked(fid, [int(t) for t in prompt], int(max_new_tokens),
                      t0 + deadline_ms / 1000.0, t0, handle,
                      temperature=temperature, top_p=top_p, seed=seed)
        err = self._dispatch(tr)
        if err is not None:
            self._m_rejected.inc()
            raise err
        self._m_router["dispatch"].observe(
            (time.monotonic() - t0) * 1000.0)
        return handle

    def _candidates(self, exclude: Optional[int] = None) -> List[Replica]:
        """Healthy replicas, least-loaded first — load is waiting PLUS
        in-flight, so a replica that drains its queue into the batch
        instantly doesn't look idle. The in-flight unit is whatever
        actually limits the replica's capacity: live KV slots when
        slotted, BLOCKS in use (tokens resident, row-normalized) when
        paged — see ``ContinuousBatcher.load``. Ties break to the
        lowest id (deterministic)."""
        out = [r for r in self.replicas.values()
               if r.state == "up" and r.id != exclude
               and r.batcher is not None and r.batcher.alive()]
        return sorted(out, key=lambda r: (r.batcher.load(), r.id))

    def _dispatch(self, tr: _Tracked,
                  exclude: Optional[int] = None) -> Optional[Rejected]:
        """Place ``tr`` on a healthy replica; returns None on success
        or the Rejected the CALLER must deliver (submit raises it; the
        failover path resolves the handle with it). Never both."""
        retry_hint: Optional[float] = None
        remaining_ms = (tr.deadline - time.monotonic()) * 1000.0
        if remaining_ms <= 0:
            # the deadline passed while failing over: a structured
            # deadline answer, not a silent drop
            if tr.handle._resolve(
                    "expired",
                    latency_ms=(time.monotonic() - tr.submitted_at)
                    * 1000.0):
                pass
            return None
        # KV tier (serve/kvtier/): stable-reorder the least-loaded
        # candidate list so replicas holding the longest cached prefix
        # run of this prompt are tried first — advisory (the index lags
        # by one sweep), so a stale preference just costs nothing
        cands = self._candidates(exclude=exclude)
        matched: Dict[int, int] = {}
        if self.kvtier_index is not None:
            cands, matched = prefer_holders(
                cands, tr.prompt, self.kvtier_index,
                versions={r.id: r.executor.params_version
                          for r in cands})
        for rep in cands:
            # chaos serve.route: the router's own wire to this replica.
            # An active partition refuses the dispatch; the router
            # fails over to the next candidate — that IS the handling.
            if _chaos._INJ is not None:
                with self._lock:
                    n = self._dispatches[rep.id]
                    self._dispatches[rep.id] = n + 1
                f = _chaos.fire("serve.route", peer=rep.id, step=n)
                if f is not None and f.kind == "partition":
                    retry_hint = retry_hint or 100.0
                    continue
            tr.handle.attempts += 1
            # track BEFORE the enqueue: the inner handle can resolve on
            # the batcher thread arbitrarily soon after submit returns
            # (a 1-token request, a GIL hiccup here), and the resolve
            # hook must find tr already owned by this replica — or a
            # legitimate first answer would be suppressed as a ghost
            # and the request silently dropped
            with self._lock:
                # re-checked HERE, under the lock drain() snapshots
                # _inflight with: a submit that passed the unlocked
                # draining check could otherwise insert after drain's
                # final sweep and never be resolved — a silent drop
                if self.draining:
                    return Rejected(
                        "fleet draining",
                        retry_after_ms=self.drain_retry_after_ms)
                tr.rid = rep.id
                tr.inner = None
                self._inflight[tr.fid] = tr
            try:
                inner = rep.queue.submit(
                    tr.prompt, max_new_tokens=tr.max_new_tokens,
                    deadline_ms=remaining_ms,
                    temperature=tr.temperature, top_p=tr.top_p,
                    seed=tr.seed,
                    on_resolve=self._make_on_resolve(tr, rep.id))
            except AdmitDropped as e:
                # the queue door ate the request: absorb by trying the
                # next replica (the drop is never the client's problem)
                with self._lock:
                    tr.rid = None
                    self._inflight.pop(tr.fid, None)
                retry_hint = e.retry_after_ms or retry_hint
                continue
            except Rejected as e:
                with self._lock:
                    tr.rid = None
                    self._inflight.pop(tr.fid, None)
                if e.retry_after_ms is None:
                    # unservable (prompt cannot fit any bucket):
                    # retrying elsewhere cannot help — propagate
                    return e
                retry_hint = (e.retry_after_ms if retry_hint is None
                              else min(retry_hint, e.retry_after_ms))
                continue
            with self._lock:
                if tr.rid == rep.id:   # not already resolved + cleaned
                    tr.inner = inner
            if matched:
                if matched.get(rep.id):
                    self._m_kvtier_routed.inc()
                self._maybe_pull_run(rep, tr.prompt, matched)
            return None
        return Rejected("no healthy replica available",
                        retry_after_ms=retry_hint or 250.0)

    def _maybe_pull_run(self, rep: Replica, prompt,
                        matched: Dict[int, int]) -> None:
        """The cross-replica leg: when a DIFFERENT replica's ladder
        holds a deeper run of ``prompt`` than the replica this request
        just landed on, pull it over the kv_migrate wire shape — pack
        on the source (locked ladder reads only), crc-verify HERE via
        ``unpack_blocks`` (a corrupted payload never reaches the
        destination's install queue), graft on the destination's
        scheduler thread through the verified install path. Only
        ladder-held (host/disk) runs are exportable; HBM-resident runs
        attract ROUTING preference instead, which is what ``matched``
        already encoded. Best-effort and advisory: any miss here means
        the request re-prefills — the normal path."""
        best_rid, best = None, matched.get(rep.id, 0)
        for rid, depth in matched.items():
            if rid != rep.id and depth > best:
                best_rid, best = rid, depth
        if best_rid is None:
            return
        src = self.replicas.get(best_rid)
        dst_tier = rep.batcher.kvtier if rep.batcher is not None \
            else None
        if src is None or src.batcher is None or dst_tier is None \
                or src.batcher.kvtier is None:
            return
        t0 = time.time()
        packed = src.batcher.kvtier.export_run(
            prompt, rep.executor.params_version)
        if packed is None:
            return                    # shallow blocks still HBM-held
        header, payload = packed
        try:
            blocks = unpack_blocks(header, payload)
        except MigrateCorrupt as e:
            self.kvtier_pull_corrupt += 1
            logger.warning(
                "fleet: kvtier pull %d -> %d failed its crc gate "
                "(%s) — dropped, destination re-prefills",
                best_rid, rep.id, e)
            return
        dst_tier.submit_graft(header, blocks)
        self._m_kvtier_pulls.inc()
        _trace_recorder().record_process(
            "kvtier_pull", t0, time.time(), blocks=len(blocks),
            src=best_rid, dst=rep.id)

    def _make_on_resolve(self, tr: _Tracked, rid: int):
        def hook(inner: ServeHandle) -> None:
            self._on_inner(tr, rid, inner)
        return hook

    def _on_inner(self, tr: _Tracked, rid: int,
                  inner: ServeHandle) -> None:
        """A replica finished (or expired/errored) a request. Runs on
        the resolving replica's batcher thread, never under a queue
        lock (queue.py's callback discipline)."""
        with self._lock:
            if tr.rid != rid or tr.handle.done():
                # the request failed over to another replica (or was
                # resolved by drain) and this is the ghost answer from
                # the original owner — suppressed: at-most-once means
                # the client saw exactly one resolution
                self.duplicates_suppressed += 1
                return
            self._inflight.pop(tr.fid, None)
        accepted = tr.handle._resolve(
            inner.status, tokens=inner.tokens,
            latency_ms=(time.monotonic() - tr.submitted_at) * 1000.0,
            error=inner.error, replica=rid)
        if not accepted:
            with self._lock:
                self.duplicates_suppressed += 1
        elif tr.handle.latency_ms is not None:
            self._m_router["e2e"].observe(tr.handle.latency_ms)

    # -- health / failover ---------------------------------------------------
    def _health_loop(self) -> None:
        period = max(self.interval_s / 2.0, 0.02)
        while not self._stop.wait(period):
            try:
                self._sweep()
            except Exception as e:  # noqa: BLE001 — health must not die
                logger.error("fleet health sweep error: %s", e)

    def _sweep(self) -> None:
        for rid, rep in list(self.replicas.items()):
            if rep.state == "up":
                # kvtier event drain rides the health sweep — the
                # heartbeat channel the index protocol piggybacks on
                if self.kvtier_index is not None \
                        and rep.batcher is not None \
                        and rep.batcher.kvtier is not None:
                    evs = rep.batcher.kvtier.drain_events()
                    if evs:
                        self.kvtier_index.apply_events(rid, evs)
                if not rep.batcher.alive():
                    self._eject(rid, "scheduler thread dead")
                    continue
                event, age = self._tracker.observe(rid, rep.hb_seq)
                if event == "suspect":
                    self._eject(
                        rid, f"heartbeat age {age:.2f}s > "
                        f"suspect {self.suspect_s:.2f}s")
            elif rep.state == "down" and self.auto_restart:
                with self._lock:
                    if rid in self._restarting:
                        continue
                    self._restarting.add(rid)
                threading.Thread(
                    target=self._recover, args=(rep,), daemon=True,
                    name=f"hvd-fleet-recover-{rid}").start()

    def _eject(self, rid: int, reason: str) -> None:
        """Remove a replica from rotation and fail its in-flight work
        over — the whole point of detecting in O(heartbeat)."""
        rep = self.replicas[rid]
        t0 = time.monotonic()
        dead_ms = (t0 - rep.hb_time) * 1000.0
        rep.state = "down"
        self._m_up[rid].set(0)
        self._m_failovers.inc()
        if self.kvtier_index is not None:
            # its cache state is about to be rebuilt/flushed — stop
            # steering prefix traffic at a corpse
            self.kvtier_index.drop_replica(rid)
        logger.error("fleet: EJECTING replica %d (%s) — re-enqueueing "
                     "its in-flight requests", rid, reason)
        with self._lock:
            victims = [tr for tr in self._inflight.values()
                       if tr.rid == rid and not tr.handle.done()]
        requeued = rejected = 0
        for tr in victims:
            with self._lock:
                if tr.handle.done() or tr.rid != rid:
                    continue       # resolved while we swept
                tr.rid = None      # detach: the ghost answer suppresses
                self._inflight.pop(tr.fid, None)
            if tr.handle.attempts >= self.max_attempts:
                if tr.handle._resolve(
                        "rejected",
                        retry_after_ms=self.drain_retry_after_ms):
                    self._m_rejected.inc()
                    rejected += 1
                continue
            err = self._dispatch(tr, exclude=rid)
            if err is None:
                if not tr.handle.done():
                    requeued += 1
                    self._m_requeued.inc()
            else:
                if tr.handle._resolve(
                        "rejected", retry_after_ms=err.retry_after_ms):
                    self._m_rejected.inc()
                    rejected += 1
        failover_ms = (time.monotonic() - t0) * 1000.0 + dead_ms
        self.last_failover_ms = failover_ms
        self._m_failover_ms.observe(failover_ms)
        self._emit("eject", rid, reason=reason, requeued=requeued,
                   rejected=rejected, failover_ms=round(failover_ms, 2))

    def _newest_weight_version(self, rep: Replica) -> Optional[int]:
        """The version a re-admitted replica must reach: the newest the
        stream has published, floored at what any sibling already
        serves (the stream may briefly trail a sibling's adoption)."""
        versions = [r.executor.params_version
                    for r in self.replicas.values()
                    if r.executor.params_version is not None]
        if rep.subscriber is not None:
            v = rep.subscriber.peek_version()
            if v is not None:
                versions.append(v)
        return max(versions) if versions else None

    def _recover(self, rep: Replica) -> None:
        """Bring an ejected replica back: rebuild if its scheduler died
        (a slow-but-alive one just needs its heartbeats back), re-warm,
        re-adopt the newest streamed weights, re-admit."""
        rid = rep.id
        try:
            if self.draining or self._stop.is_set():
                return   # drain owns every in-flight handle from here
            rebuilt = False
            if not rep.batcher.alive():
                rep.build()
                rep.restarts += 1
                rebuilt = True
                rep.state = "warming"
                rep.batcher.warmup()
            else:
                # alive but ejected (slow / stopped heartbeating): wait
                # for its heartbeats to resume before trusting it again
                rep.state = "warming"
                seq0 = rep.hb_seq
                deadline = time.monotonic() + self.rewarm_timeout_s
                while rep.hb_seq == seq0:
                    if time.monotonic() > deadline or self._stop.is_set():
                        rep.state = "down"
                        return      # still wedged; next sweep retries
                    time.sleep(self.interval_s / 4.0)
            target = self._newest_weight_version(rep)
            if rep.subscriber is not None and target is not None:
                deadline = time.monotonic() + self.rewarm_timeout_s
                while (rep.executor.params_version or 0) < target:
                    try:
                        got = rep.subscriber.poll()
                        if got is not None:
                            rep.executor.swap_params(got[1],
                                                     version=got[0])
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "fleet: replica %d weight re-adoption "
                            "attempt failed (%s); retrying", rid, e)
                    if (rep.executor.params_version or 0) >= target:
                        break
                    if time.monotonic() > deadline or self._stop.is_set():
                        rep.state = "down"
                        logger.error(
                            "fleet: replica %d could not re-adopt "
                            "weight version %s in %.1fs — NOT "
                            "re-admitted", rid, target,
                            self.rewarm_timeout_s)
                        return      # next sweep retries recovery
                    time.sleep(self.interval_s / 4.0)
            # the re-admission WEIGHT gate must also be a KV gate: a
            # slow-but-alive replica kept its batcher — and with it a
            # prefix cache (and block pool contents) computed under the
            # version it served BEFORE ejection. Re-warming on v2 while
            # v1 prefix blocks remain matchable would serve
            # stale-weight KV; the batcher's own version fence covers
            # the swap-observed path, this covers every other way back
            # in (the flush runs on the scheduler thread at the top of
            # its next iteration, before any admission can match).
            rep.batcher.request_prefix_flush()
            # a drain that started while this recovery ran owns every
            # in-flight handle and is stopping the fleet: re-admitting
            # (and restarting a batcher drain just stopped) would leave
            # a replica running after drain() returned — abort instead;
            # drain's final sweep resolves any leftovers
            if self.draining or self._stop.is_set():
                rep.state = "down"
                return
            if rebuilt:
                rep.batcher.start()
            # fresh accrual history: a re-admitted replica re-enters
            # the never-seen state and cannot be insta-suspected
            self._tracker.reset(rid)
            rep.state = "up"
            self._m_up[rid].set(1)
            logger.info("fleet: replica %d re-admitted (%s, weights v%s)",
                        rid, "rebuilt" if rebuilt else "recovered",
                        rep.executor.params_version)
            self._emit("readmit", rid, rebuilt=rebuilt,
                       weights_version=rep.executor.params_version)
        except Exception as e:  # noqa: BLE001
            rep.state = "down"  # next sweep retries
            logger.error("fleet: replica %d recovery failed: %s", rid, e)
            self._emit("restart_failed", rid, error=str(e)[:200])
        finally:
            with self._lock:
                self._restarting.discard(rid)

    # -- introspection -------------------------------------------------------
    def healthz(self) -> dict:
        """Aggregate fleet liveness — the front door's ``/healthz``
        payload (serve/http.py ``make_fleet_server``), same contract as
        the per-replica endpoint: per-replica up/draining/warming state
        plus LIVE capacity (free queue depth and free KV blocks summed
        over admitted replicas). ``ok`` goes False — the HTTP face
        answers 503 — once live capacity is zero. Shape built by the
        shared :func:`aggregate_healthz`."""
        infos = {}
        for rid, rep in self.replicas.items():
            b = rep.batcher
            up = rep.state == "up" and b is not None and b.alive()
            depth = rep.queue.depth() if rep.queue is not None else 0
            info = {
                "state": rep.state, "up": up,
                "draining": bool(getattr(b, "draining", False))
                if b is not None else False,
                "queue_depth": depth,
                "weights_version": rep.executor.params_version,
                "restarts": rep.restarts,
                "queue_free": max(rep.max_queue - depth, 0),
            }
            if up and getattr(b, "paged", False):
                info["kv_blocks_total"] = b.kv.pool.num_blocks
                info["kv_blocks_in_use"] = b.kv.pool.in_use()
                if b.prefix is not None:
                    # TOKEN counts, the fleet-wide definition of
                    # cacheable capacity (the index and autoscale
                    # signals must agree; docs/serving.md). Simple
                    # cross-thread reads, same discipline as the
                    # worker's evictable-blocks healthz read.
                    info["prefix_tokens_resident"] = \
                        b.prefix.resident_tokens()
                    info["prefix_tokens_evictable"] = \
                        b.prefix.evictable_tokens()
            infos[rid] = info
        return aggregate_healthz(
            infos, draining=self.draining,
            retry_after_ms=self.drain_retry_after_ms)

    def stats(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
        reps = {}
        for rid, rep in self.replicas.items():
            reps[rid] = {
                "state": rep.state,
                "restarts": rep.restarts,
                "queue_depth": (rep.queue.depth()
                                if rep.queue is not None else 0),
                "weights_version": rep.executor.params_version,
            }
        return {
            "replicas_up": sum(1 for r in self.replicas.values()
                               if r.state == "up"),
            "replicas": reps,
            "inflight": inflight,
            "draining": self.draining,
            "duplicates_suppressed": self.duplicates_suppressed,
            "failovers": int(self._m_failovers.value),
            "requeued": int(self._m_requeued.value),
            "rejected": int(self._m_rejected.value),
            "last_failover_ms": self.last_failover_ms,
        }
