"""Admission-controlled request queue: backpressure instead of collapse.

The serving front door. Three properties the ROADMAP's "heavy traffic
from millions of users" target demands of it:

* **Bounded**: at most HOROVOD_SERVE_MAX_QUEUE requests wait; past that
  the queue *sheds load* — `submit` raises a structured `Rejected`
  carrying a `retry_after_ms` estimate (depth x observed per-request
  service time / batch width) so clients back off instead of piling on.
  Shedding is an accounting event (`shed_count`), never a crash.
* **Deadlined**: every request carries an absolute deadline
  (HOROVOD_SERVE_DEADLINE_MS default). The batcher resolves expired
  requests with status "expired" and whatever tokens were produced —
  a late answer is a wasted decode slot.
* **Handle-based**: `submit` returns a `ServeHandle` the caller waits
  on; resolution happens on the batcher thread (serve/batcher.py), the
  same one-writer discipline the engine uses for collective handles.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from ..chaos import inject as _chaos
from ..obs import metrics as obs_metrics


class Rejected(Exception):
    """Structured load-shed rejection (the HTTP 429 analog).

    `retry_after_ms` is the backoff hint (None when retrying cannot
    help, e.g. a prompt that can never fit the configured buckets).
    """

    def __init__(self, reason: str, retry_after_ms: Optional[float] = None):
        self.reason = reason
        self.retry_after_ms = retry_after_ms
        hint = "" if retry_after_ms is None \
            else f" (retry after {retry_after_ms:.0f} ms)"
        super().__init__(f"request rejected: {reason}{hint}")


class AdmitDropped(Rejected):
    """A chaos ``serve.admit`` drop: the request was lost at the queue
    door, as if the connection died mid-admission. A Rejected subclass
    so a standalone replica still answers it structurally (429 +
    retry-after — never a silent loss); the fleet router additionally
    distinguishes it to retry the request on another replica
    (serve/fleet.py)."""


@dataclass
class ServeRequest:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    #: absolute monotonic deadline (seconds)
    deadline: float
    submitted_at: float
    handle: "ServeHandle" = field(repr=False, default=None)
    #: on-device sampling controls (serve/executor.py): temperature 0
    #: is greedy (the default — argmax semantics, deterministic, and
    #: bit-identical across kernels/configs WITHIN a version; exact
    #: float values may shift across code versions as program shapes
    #: change); top_p restricts to the smallest nucleus covering that
    #: probability mass; seed makes the request's token stream
    #: deterministic independent of batch placement and restarts
    temperature: float = 0.0
    top_p: float = 1.0
    seed: int = 0
    #: disaggregated prefill (serve/disagg.py): when True the batcher
    #: PARKS the sequence's KV (row + blocks stay allocated) at clean
    #: retirement instead of freeing it, so the endpoint can migrate
    #: the blocks to a decode replica (serve/kv_migrate.py). Parked
    #: rows are released by release_parked() or reaped past deadline.
    hold_kv: bool = False
    #: distributed-tracing context (horovod_tpu/trace): the wire-form
    #: ``{"trace", "span", "parent"}`` dict the router minted at
    #: admission, or None (untraced — the back-compat default). The
    #: batcher records queue_wait/prefill/decode spans against it and
    #: migration packets carry it forward (docs/tracing.md).
    trace: Optional[dict] = None

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) > self.deadline


class ServeHandle:
    """Caller-side completion handle; resolved exactly once by the
    batcher. `status` is "pending" | "ok" | "expired" | "error".

    ``on_resolve`` (optional, set via ``submit``) is invoked exactly
    once with the handle AFTER resolution — the fleet router's
    completion hook. It runs on the resolving thread and must never be
    called while a queue/batcher lock is held (lock-order discipline
    with the router's own lock)."""

    def __init__(self, rid: int,
                 on_resolve: Optional[Callable[["ServeHandle"],
                                               None]] = None):
        self.rid = rid
        self.status = "pending"
        self.tokens: List[int] = []
        self.error: Optional[str] = None
        self.latency_ms: Optional[float] = None
        self.on_resolve = on_resolve
        self._event = threading.Event()
        self._rlock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def _resolve(self, tokens: Sequence[int], status: str,
                 latency_ms: Optional[float] = None,
                 error: Optional[str] = None) -> None:
        with self._rlock:   # one-shot; late expiry races are no-ops
            if self._event.is_set():
                return
            self.tokens = list(tokens)
            self.status = status
            self.error = error
            self.latency_ms = latency_ms
            self._event.set()
        cb = self.on_resolve
        if cb is not None:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a hook must not mask
                pass           # the resolution it observes


class AdmissionQueue:
    """Bounded FIFO with load shedding and service-time-based backoff.

    Thread-safe: HTTP handler threads submit; the batcher thread pops.
    """

    def __init__(self, max_queue: int = 64,
                 default_deadline_ms: float = 30000.0,
                 max_prompt_len: Optional[int] = None,
                 replica_id: Optional[int] = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1; got {max_queue}")
        if default_deadline_ms <= 0:
            raise ValueError("default_deadline_ms must be > 0; got "
                             f"{default_deadline_ms}")
        self.max_queue = max_queue
        self.default_deadline_ms = default_deadline_ms
        #: longest admissible prompt (the batcher sets this to its
        #: largest prefill bucket so an unservable prompt is rejected at
        #: the door, not discovered holding a decode slot)
        self.max_prompt_len = max_prompt_len
        #: fleet replica this queue fronts (None = standalone): labels
        #: the metric series and addresses chaos serve.admit faults
        self.replica_id = replica_id
        self._dq: "deque[ServeRequest]" = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._ids = itertools.count()
        self._submits = 0      # serve.admit chaos site counter
        # -- counters: registry-backed (horovod_tpu.obs); the legacy
        # attributes (shed_count & co) are properties over these, so the
        # SERVE timeline row / healthz keep their numbers while /metrics
        # exposes the same series fleet-wide. Standalone queues claim
        # their families fresh (one serving stack per process, and a new
        # queue's views must count from zero); a FLEET replica's queue
        # instead get-or-creates {replica=...}-labeled children, so one
        # replica's restart neither clobbers its siblings nor resets its
        # own fleet-visible counts.
        rl = {} if replica_id is None else {"replica": str(replica_id)}
        R = obs_metrics.get_registry()
        if replica_id is None:
            for fam in ("hvd_serve_admitted_total", "hvd_serve_shed_total",
                        "hvd_serve_completed_total",
                        "hvd_serve_expired_total", "hvd_serve_queue_depth"):
                R.unregister(fam)
        self._m_admitted = R.counter(
            "hvd_serve_admitted_total", "requests admitted to the queue",
            rl or None)
        self._m_shed = R.counter(
            "hvd_serve_shed_total",
            "requests load-shed at admission (queue full / unservable)",
            rl or None)
        self._m_completed = R.counter(
            "hvd_serve_completed_total", "requests retired ok", rl or None)
        self._m_expired = R.counter(
            "hvd_serve_expired_total", "requests expired past deadline",
            rl or None)
        self._m_depth = R.gauge(
            "hvd_serve_queue_depth", "requests waiting for a decode slot",
            rl or None)
        #: EWMA of per-request service time, fed back by the batcher on
        #: retirement; drives the retry_after_ms hint
        self._service_ms_ewma: Optional[float] = None

    # -- back-compat views over the registry counters ------------------------
    shed_count = property(
        lambda self: int(self._m_shed.value),
        lambda self, v: self._m_shed._set(v))
    admitted_count = property(
        lambda self: int(self._m_admitted.value),
        lambda self, v: self._m_admitted._set(v))
    completed_count = property(
        lambda self: int(self._m_completed.value),
        lambda self, v: self._m_completed._set(v))
    expired_count = property(
        lambda self: int(self._m_expired.value),
        lambda self, v: self._m_expired._set(v))

    # -- producer side ------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               on_resolve: Optional[Callable[[ServeHandle],
                                             None]] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int = 0, hold_kv: bool = False,
               trace: Optional[dict] = None) -> ServeHandle:
        """Admit a request or raise `Rejected` (load shed / unservable).

        ``temperature`` / ``top_p`` / ``seed`` ride the request into
        the executor's on-device sampler (temperature 0 = greedy, the
        default); validation is fail-fast here at the door.
        ``trace`` is the wire-form tracing context (or None —
        untraced); it rides the request so the batcher can record its
        queue_wait/prefill/decode spans (docs/tracing.md).

        ``on_resolve`` is attached to the handle BEFORE it becomes
        poppable, so a completion can never race past the hook."""
        prompt = [int(t) for t in prompt]
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}")
        temperature = float(temperature)
        top_p = float(top_p)
        seed = int(seed)
        if not (temperature >= 0.0):
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy); got "
                f"{temperature!r}")
        if not (0.0 < top_p <= 1.0):
            raise ValueError(
                f"top_p must be in (0, 1]; got {top_p!r}")
        # chaos serve.admit: the queue-door fault site. Disarmed cost is
        # one attribute read; delay sleeps inside the injector; drop
        # surfaces as AdmitDropped (a structured loss, never a silent
        # one — the fleet router absorbs it by retrying elsewhere).
        if _chaos._INJ is not None:
            with self._lock:
                n = self._submits
                self._submits += 1
            f = _chaos.fire("serve.admit", peer=self.replica_id, step=n)
            if f is not None and f.kind == "drop":
                self._m_shed.inc()
                raise AdmitDropped("chaos: admission dropped",
                                   retry_after_ms=self._retry_after_ms())
        with self._lock:
            if self.max_prompt_len is not None and \
                    (not prompt or len(prompt) > self.max_prompt_len):
                self._m_shed.inc()
                raise Rejected(
                    f"prompt length {len(prompt)} outside servable range "
                    f"[1, {self.max_prompt_len}]", retry_after_ms=None)
            if len(self._dq) >= self.max_queue:
                self._m_shed.inc()
                raise Rejected("queue full",
                               retry_after_ms=self._retry_after_ms_locked())
            now = time.monotonic()
            dl = (deadline_ms if deadline_ms is not None
                  else self.default_deadline_ms)
            rid = next(self._ids)
            req = ServeRequest(rid=rid, prompt=prompt,
                               max_new_tokens=max_new_tokens,
                               deadline=now + dl / 1000.0,
                               submitted_at=now,
                               temperature=temperature, top_p=top_p,
                               seed=seed, hold_kv=bool(hold_kv),
                               trace=trace)
            req.handle = ServeHandle(rid, on_resolve=on_resolve)
            self._dq.append(req)
            self._m_admitted.inc()
            self._m_depth.set(len(self._dq))
            self._work.set()
            return req.handle

    def _retry_after_ms_locked(self) -> float:
        # depth x EWMA service time is the expected drain time of the
        # queue ahead of the retrying client; 100 ms floor before the
        # first completion calibrates the estimator
        est = self._service_ms_ewma if self._service_ms_ewma else 100.0
        return max(1.0, len(self._dq) * est)

    def _retry_after_ms(self) -> float:
        with self._lock:
            return self._retry_after_ms_locked()

    # -- consumer (batcher) side -------------------------------------------
    def pop(self, n: int) -> List[ServeRequest]:
        """Take up to `n` requests FIFO. Already-expired requests are
        resolved "expired" here and do not count against `n`."""
        return self.pop_fitting(n, lambda req: True)

    def pop_fitting(self, n: int,
                    fits: Callable[[ServeRequest], bool]
                    ) -> List[ServeRequest]:
        """Take up to `n` unexpired requests FIFO, stopping at the
        FIRST one ``fits`` rejects — the paged-KV admission gate:
        capacity is measured in free BLOCKS (can this prompt + its
        generation budget be allocated without starving a running
        sequence?), not free slots, and a too-big head request is never
        queue-jumped (FIFO fairness; it admits once blocks free up).
        Already-expired requests are resolved "expired" and count
        against nothing.

        ``fits`` runs under the queue lock and must not take locks of
        its own. Handle resolution (and therefore any ``on_resolve``
        hook) runs AFTER the queue lock is released: the fleet
        router's hook takes its own lock and may submit back into a
        queue, so resolving under this lock would invert the
        router->queue lock order."""
        out: List[ServeRequest] = []
        dead: List[ServeRequest] = []
        with self._lock:
            now = time.monotonic()
            while self._dq and len(out) < n:
                req = self._dq[0]
                if req.expired(now):
                    self._dq.popleft()
                    self._m_expired.inc()
                    dead.append(req)
                    continue
                if not fits(req):
                    break
                self._dq.popleft()
                out.append(req)
            self._m_depth.set(len(self._dq))
            if not self._dq:
                self._work.clear()
        for req in dead:
            req.handle._resolve(
                [], "expired",
                latency_ms=(now - req.submitted_at) * 1000.0)
        return out

    def reap_expired(self) -> int:
        """Resolve every expired request still WAITING in the queue —
        called by the batcher once per scheduling iteration, so a
        client whose deadline passes while the fleet is saturated gets
        its structured deadline completion (HTTP 504, serve/http.py)
        within one iteration instead of discovering it by socket
        timeout. Returns the number reaped."""
        dead: List[ServeRequest] = []
        with self._lock:
            now = time.monotonic()
            if self._dq:
                keep: "deque[ServeRequest]" = deque()
                for req in self._dq:
                    (dead if req.expired(now) else keep).append(req)
                if dead:
                    self._dq = keep
                    self._m_expired.inc(len(dead))
                    self._m_depth.set(len(keep))
                    if not keep:
                        self._work.clear()
        for req in dead:
            req.handle._resolve(
                [], "expired",
                latency_ms=(now - req.submitted_at) * 1000.0)
        return len(dead)

    def note_service_ms(self, ms: float) -> None:
        """Batcher feedback on request retirement (EWMA, alpha=0.2)."""
        with self._lock:
            self._m_completed.inc()
            if self._service_ms_ewma is None:
                self._service_ms_ewma = ms
            else:
                self._service_ms_ewma += 0.2 * (ms - self._service_ms_ewma)

    def peek_prompts(self, n: int) -> List[Sequence[int]]:
        """Snapshot the first ``n`` waiting prompts (no pop, no
        resolution) — the KV tier's pre-admission promotion scan
        (serve/kvtier/): the batcher promotes ladder-held prefix runs
        for queued prompts BEFORE the admission wave matches against
        the tree, outside the queue lock."""
        with self._lock:
            return [req.prompt for _, req in
                    zip(range(n), self._dq)]

    def depth(self) -> int:
        with self._lock:
            return len(self._dq)

    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Block until at least one request is queued (batcher idle loop)."""
        return self._work.wait(timeout)

    def counters(self) -> dict:
        with self._lock:
            return {"queue_depth": len(self._dq),
                    "admitted": self.admitted_count,
                    "shed": self.shed_count,
                    "completed": self.completed_count,
                    "expired": self.expired_count,
                    "service_ms_ewma": self._service_ms_ewma}
