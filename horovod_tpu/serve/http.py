"""Thin stdlib HTTP front end for the serve stack (optional).

Two endpoints, JSON in/out, zero dependencies beyond `http.server`:

* ``POST /generate``  body ``{"tokens": [...], "max_new_tokens": N,
  "deadline_ms": M?, "temperature": T?, "top_p": P?, "seed": S?}``
  (sampling keys optional; temperature 0 = greedy)
  -> ``200 {"tokens": [...], "status": "ok",
  "latency_ms": ...}``. Over capacity the admission queue sheds and the
  reply is ``429 {"error": "rejected", "reason": ...,
  "retry_after_ms": ...}`` with a standard ``Retry-After`` header —
  the structured load-shed contract (docs/serving.md).
* ``GET /healthz`` -> ``200`` with the queue/batcher/executor counters
  (queue depth, occupancy, shed count, tokens/s) plus ``replica_up`` /
  ``draining``; ``503`` (same payload) once the batcher thread has died
  or ``stop()`` ran — a real liveness signal a load balancer / the
  fleet router can route on, not a bare reachability ping.
* ``GET /metrics`` -> Prometheus text exposition of the process-global
  registry (horovod_tpu.obs) — serve latency histograms next to the
  engine's wire-byte counters, no second scrape port needed.

:func:`make_fleet_server` lifts the same contract fleet-wide: one
front door over a ``FleetRouter``/``ProcessFleetRouter`` whose
``/healthz`` aggregates per-replica state + live capacity (503 at zero
capacity) and whose ``/generate`` rides the failover/at-most-once/
capacity-scaled-shed machinery.

Production serving would sit behind a real frontend; this exists so the
whole vertical slice — socket to TPU decode step — is drivable from
curl and coverable by a loopback test.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from ..obs import metrics as obs_metrics
from ..obs.exporter import PROMETHEUS_CONTENT_TYPE
from .queue import Rejected


def retry_after_seconds(ms: float) -> int:
    """``Retry-After`` is whole seconds; round UP with a true ceiling
    so clients never come back early — and an exact 2000 ms maps to
    2 s, not 3 (the old ``int(ms/1000)+1`` overshot every
    exact-second hint by a full second). Floor of 1: a sub-second hint
    must not round to an immediate retry."""
    return max(1, int(-(-float(ms) // 1000.0)))


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared plumbing for the per-replica and fleet front doors —
    one place for the reply/metrics/429 mechanics, so the two handlers
    cannot drift (the Retry-After rounding already did once)."""

    def log_message(self, *a):  # quiet: counters replace access logs
        pass

    def _reply(self, code: int, payload: dict,
               headers: Optional[Tuple[Tuple[str, str], ...]] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers or ():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_metrics(self):
        body = obs_metrics.get_registry().to_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_fleet_metrics(self, router):
        """``/metrics?fleet=1``: the router process's own registry
        snapshot merged with every live worker's (scraped over the
        ctrl socket, ``{"op": "metrics"}``) — one exposition for the
        whole fleet, HELP text borrowed from the local registry."""
        R = obs_metrics.get_registry()
        snaps = [R.snapshot()]
        snaps.extend(router.metrics_snapshots())
        body = obs_metrics.snapshot_to_prometheus(
            obs_metrics.merge_snapshots(snaps), help_from=R).encode()
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_rejected(self, reason, retry_after_ms):
        """The structured 429: payload always carries the ms hint, the
        header its true-ceiling whole-second rendering."""
        hdrs = ()
        if retry_after_ms is not None:
            hdrs = (("Retry-After",
                     str(retry_after_seconds(retry_after_ms))),)
        self._reply(429, {"error": "rejected", "reason": reason,
                          "retry_after_ms": retry_after_ms}, hdrs)

    def _read_generate_request(self):
        """Parse a /generate body -> (prompt, max_new, deadline_ms,
        sampling kwargs); raises the (KeyError, ValueError, TypeError)
        family the caller maps to a structured 400. ``temperature`` /
        ``top_p`` / ``seed`` are optional (greedy default); their
        range validation is the queue's (fail-fast at submit)."""
        n = int(self.headers.get("Content-Length", "0"))
        req = json.loads(self.rfile.read(n) or b"{}")
        prompt = req["tokens"]
        max_new = int(req.get("max_new_tokens", 16))
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
        sampling = {"temperature": float(req.get("temperature", 0.0)),
                    "top_p": float(req.get("top_p", 1.0)),
                    "seed": int(req.get("seed", 0))}
        return prompt, max_new, deadline_ms, sampling


def make_server(batcher, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Build (not start) an HTTP server bound to `batcher`'s queue.
    `port=0` picks a free port (see ``server.server_address``)."""
    queue = batcher.queue

    class Handler(_JsonHandler):
        # requests are held open while the batcher generates; the
        # threading server gives each its own thread

        def do_GET(self):
            # query-string tolerant, like the standalone exporter
            if self.path.split("?", 1)[0] == "/metrics":
                self._reply_metrics()
                return
            if self.path != "/healthz":
                self._reply(404, {"error": "not found"})
                return
            ex = batcher.executor
            # Liveness, not just reachability: once the batcher thread
            # has died (chaos crash, unhandled error) or stop() ran, no
            # queued request will ever be served again — a 200 here
            # would keep a load balancer routing traffic into a black
            # hole. 503 is what lets the router/LB actually use this
            # endpoint as its health probe (docs/serving.md).
            up = batcher.alive()
            draining = bool(getattr(batcher, "draining", False))
            info = {"ok": up and not draining,
                    "replica_up": up,
                    "draining": draining,
                    "occupancy": round(batcher.kv.occupancy(), 3),
                    "tokens_per_s": round(ex.tokens_per_s(), 1),
                    "iterations": batcher.iterations}
            if getattr(batcher, "paged", False):
                # paged occupancy above is tokens-resident (pool
                # blocks); surface the raw block counts and the prefix
                # cache's sharing yield next to it
                info["kv_blocks_in_use"] = batcher.kv.pool.in_use()
                info["kv_blocks_total"] = batcher.kv.pool.num_blocks
                if batcher.prefix is not None:
                    info["prefix_hits"] = batcher.prefix.hits
                    info["prefix_tokens_saved"] = \
                        batcher.prefix.tokens_saved
            info.update(queue.counters())
            self._reply(200 if up else 503, info)

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": "not found"})
                return
            try:
                prompt, max_new, deadline_ms, sampling = \
                    self._read_generate_request()
                handle = queue.submit(prompt, max_new_tokens=max_new,
                                      deadline_ms=deadline_ms,
                                      **sampling)
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                # covers submit's own validation too (bad token values,
                # max_new_tokens < 1, non-dict body): malformed input is
                # always a structured 400, never a dropped socket
                self._reply(400, {"error": "bad request", "detail": str(e)})
                return
            except Rejected as e:
                self._reply_rejected(e.reason, e.retry_after_ms)
                return
            # wait past the request's own deadline: the batcher resolves
            # expiry itself and this must not race it
            handle.wait(timeout=(deadline_ms or
                                 queue.default_deadline_ms) / 1000.0 + 30.0)
            if not handle.done():
                self._reply(504, {"error": "timeout"})
                return
            if handle.status == "expired":
                # the deadline completion is STRUCTURED: the batcher
                # resolves expiry within one scheduling iteration
                # (queue.reap_expired) and the client learns here, not
                # by its own socket timeout
                self._reply(504, {"error": "deadline",
                                  "tokens": handle.tokens,
                                  "latency_ms": handle.latency_ms})
                return
            if handle.status == "error":
                self._reply(500, {"error": handle.error or "error",
                                  "latency_ms": handle.latency_ms})
                return
            self._reply(200, {"tokens": handle.tokens,
                              "status": handle.status,
                              "latency_ms": handle.latency_ms})

    return ThreadingHTTPServer((host, port), Handler)


def serve_http(batcher, host: str = "127.0.0.1", port: int = 0):
    """Start the batcher thread + HTTP server; returns (server, thread).
    Call ``server.shutdown()`` then ``batcher.stop()`` to tear down."""
    batcher.start()
    srv = make_server(batcher, host, port)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="hvd-serve-http")
    t.start()
    return srv, t


def make_fleet_server(router, host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    """The FLEET front door: one HTTP face over a ``FleetRouter`` or
    ``ProcessFleetRouter`` (anything with ``submit``/``healthz``).

    * ``POST /generate`` routes through the router — failover,
      at-most-once and capacity-scaled shedding all apply; a shed
      answers ``429`` with ``Retry-After`` (true-ceiling seconds) and
      ``retry_after_ms``, never a dropped socket.
    * ``GET /healthz`` serves the router's AGGREGATE liveness: per-
      replica up/draining/respawning plus live capacity (free queue
      depth + free KV blocks) — ``503`` once live capacity is zero,
      the same contract as the per-replica endpoint, lifted fleet-wide
      so a load balancer can front the whole fleet on one probe.
    * ``GET /metrics`` — the process-global Prometheus registry
      (router legs, failovers, respawns, net retries).
      ``GET /metrics?fleet=1`` additionally scrapes every live worker
      process's snapshot over the ctrl socket and serves the MERGED
      exposition (obs ``merge_snapshots``) — batcher/executor series
      from inside the workers next to the router's own, one scrape
      for the whole fleet (docs/metrics.md).
    """

    class Handler(_JsonHandler):
        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                if ("fleet=1" in query.split("&")
                        and hasattr(router, "metrics_snapshots")):
                    self._reply_fleet_metrics(router)
                else:
                    self._reply_metrics()
                return
            if self.path != "/healthz":
                self._reply(404, {"error": "not found"})
                return
            info = router.healthz()
            self._reply(200 if info.get("ok") else 503, info)

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": "not found"})
                return
            try:
                prompt, max_new, deadline_ms, sampling = \
                    self._read_generate_request()
                # sampling rides the fleet path since the routers
                # track (prompt, sampling) for failover re-submit:
                # per-row seeded streams are deterministic across
                # re-dispatch, so a sampled request fails over with
                # the same at-most-once bookkeeping as a greedy one
                handle = router.submit(prompt, max_new_tokens=max_new,
                                       deadline_ms=deadline_ms,
                                       **sampling)
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": "bad request",
                                  "detail": str(e)})
                return
            except Rejected as e:
                self._reply_rejected(e.reason, e.retry_after_ms)
                return
            handle.wait(timeout=(deadline_ms or 30000.0) / 1000.0 + 60.0)
            if not handle.done():
                self._reply(504, {"error": "timeout"})
                return
            if handle.status == "rejected":
                # async fleet-level shed (every worker's queue door
                # said no): same 429 + Retry-After contract as the
                # synchronous path
                self._reply_rejected(handle.error or "shed",
                                     handle.retry_after_ms)
                return
            if handle.status == "expired":
                self._reply(504, {"error": "deadline",
                                  "tokens": handle.tokens,
                                  "latency_ms": handle.latency_ms})
                return
            if handle.status == "error":
                self._reply(500, {"error": handle.error or "error",
                                  "latency_ms": handle.latency_ms})
                return
            self._reply(200, {"tokens": handle.tokens,
                              "status": handle.status,
                              "latency_ms": handle.latency_ms,
                              "replica": handle.replica})

    return ThreadingHTTPServer((host, port), Handler)
