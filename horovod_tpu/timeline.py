"""Chrome-trace timeline profiler.

Re-design of the reference Timeline (horovod/common/timeline.cc, states at
timeline.h:102): per-tensor phase events (QUEUED -> NEGOTIATING -> fused-op
activities -> done) written as Chrome trace JSON by a dedicated writer thread
fed through a queue (the reference uses a boost lockfree SPSC queue,
timeline.h:48-70). Enable via HOROVOD_TIMELINE=<file> or dynamically with
hvd.start_timeline/stop_timeline (basics.py:159-185).

On TPU the per-collective phases inside a fused XLA program are not separately
host-visible; the engine emits ENQUEUE / CYCLE / FUSE / EXECUTE / DONE phases,
and users combine this with the JAX profiler (xplane) for on-device detail —
the NVTX-range analog (horovod/common/nvtx_op_range.cc).
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional


class Timeline:
    """Chrome trace (catapult) event writer.

    Two paths: the native writer (csrc/timeline.cc — the reference's
    writer-thread design, timeline.cc) when the toolchain is available, and
    a pure-Python queue+thread fallback. Both produce the same trace schema.
    Disable the native path with HOROVOD_TIMELINE_NATIVE=0.
    """

    def __init__(self, filename: str, mark_cycles: bool = False):
        self.filename = filename
        self.mark_cycles = mark_cycles
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._start_us = time.monotonic_ns() // 1000
        self._native = None
        self._native_lib = None
        # serializes native emits against stop()'s destroy (use-after-free
        # otherwise: an emitter could pass the None-check while stop frees
        # the writer)
        self._native_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        if os.environ.get("HOROVOD_TIMELINE_NATIVE", "1") != "0":
            try:
                from . import native
                lib = native.lib()
                handle = lib.hvd_timeline_create(self.filename.encode())
                if handle:
                    self._native_lib = lib
                    self._native = handle
                    return
            except Exception:  # noqa: BLE001 — fall back to Python writer
                self._native = None
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="hvd-timeline-writer")
        self._thread.start()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        if self._native is not None:
            with self._native_lock:
                self._native_lib.hvd_timeline_destroy(self._native)
                self._native = None
            return
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- event emission (engine-facing) ------------------------------------
    def _now_us(self) -> int:
        return time.monotonic_ns() // 1000 - self._start_us

    def _emit(self, ev: dict) -> None:
        if not self._running:
            return
        if self._native is not None:
            with self._native_lock:
                if self._native is None:  # stopped concurrently
                    return
                args = ev.get("args")
                self._native_lib.hvd_timeline_emit(
                    self._native, ev["name"].encode(),
                    ev.get("cat", "").encode(), ev["ph"].encode(), ev["ts"],
                    ev.get("pid", 0), ev.get("tid", 0),
                    json.dumps(args).encode() if args is not None else None)
            return
        self._q.put(ev)

    def begin(self, tensor_name: str, phase: str) -> None:
        self._emit({"name": phase, "cat": phase, "ph": "B",
                    "ts": self._now_us(), "pid": 0,
                    "tid": hash(tensor_name) % (1 << 31),
                    "args": {"tensor": tensor_name}})

    def end(self, tensor_name: str, phase: str) -> None:
        self._emit({"name": phase, "cat": phase, "ph": "E",
                    "ts": self._now_us(), "pid": 0,
                    "tid": hash(tensor_name) % (1 << 31)})

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._emit({"name": name, "ph": "i", "s": "g",
                    "ts": self._now_us(), "pid": 0, "tid": 0,
                    "args": args or {}})

    def mark_cycle(self) -> None:
        # reference: HOROVOD_TIMELINE_MARK_CYCLES (operations.cc:506)
        if self.mark_cycles:
            self.instant("CYCLE")

    # -- writer thread ------------------------------------------------------
    def _writer(self) -> None:
        events = []
        while True:
            ev = self._q.get()
            if ev is None:
                break
            events.append(ev)
            # Drain opportunistically to batch writes.
            try:
                while True:
                    nxt = self._q.get_nowait()
                    if nxt is None:
                        self._flush(events)
                        return
                    events.append(nxt)
            except queue.Empty:
                pass
            if len(events) >= 4096:
                self._flush(events)
                events = []
        self._flush(events)

    def _flush(self, events) -> None:
        # Rewrite the whole file each flush so it is always valid JSON
        # (the reference streams and leaves the array unterminated; valid
        # files are friendlier to tooling).
        path = self.filename
        existing = []
        if os.path.exists(path):
            try:
                with open(path) as f:
                    existing = json.load(f).get("traceEvents", [])
            except Exception:
                existing = []
        with open(path, "w") as f:
            json.dump({"traceEvents": existing + events}, f)
