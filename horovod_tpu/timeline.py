"""Chrome-trace timeline profiler.

Re-design of the reference Timeline (horovod/common/timeline.cc, states at
timeline.h:102): per-tensor phase events (QUEUED -> NEGOTIATING -> fused-op
activities -> done) written as Chrome trace JSON by a dedicated writer thread
fed through a queue (the reference uses a boost lockfree SPSC queue,
timeline.h:48-70). Enable via HOROVOD_TIMELINE=<file> or dynamically with
hvd.start_timeline/stop_timeline (basics.py:159-185).

On TPU the per-collective phases inside a fused XLA program are not separately
host-visible; the engine emits ENQUEUE / CYCLE / FUSE / EXECUTE / DONE phases,
and users combine this with the JAX profiler (xplane) for on-device detail —
the NVTX-range analog (horovod/common/nvtx_op_range.cc).
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import zlib
from typing import Optional


def _tid(tensor_name: str) -> int:
    """Stable per-tensor viewer row id. crc32, NOT Python hash():
    hash(str) is salted per process (PYTHONHASHSEED), so tids would
    differ across ranks and runs and multi-rank traces could never be
    lined up event-by-event."""
    return zlib.crc32(tensor_name.encode()) % (1 << 31)


class Timeline:
    """Chrome trace (catapult) event writer.

    Two paths: the native writer (csrc/timeline.cc — the reference's
    writer-thread design, timeline.cc) when the toolchain is available, and
    a pure-Python queue+thread fallback. Both produce the same trace schema.
    Disable the native path with HOROVOD_TIMELINE_NATIVE=0.
    """

    def __init__(self, filename: str, mark_cycles: bool = False):
        self.filename = filename
        self.mark_cycles = mark_cycles
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._start_us = time.monotonic_ns() // 1000
        self._native = None
        self._native_lib = None
        # serializes native emits against stop()'s destroy (use-after-free
        # otherwise: an emitter could pass the None-check while stop frees
        # the writer)
        self._native_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        from .core.config import _env_bool
        # knob: exempt (read at writer start — timelines outlive and
        # predate Config instances (interop plane); declared in
        # core/config.py as timeline_native and parsed with config's
        # own _env_bool so the spellings cannot drift)
        if _env_bool("HOROVOD_TIMELINE_NATIVE", True):
            try:
                from . import native
                lib = native.lib()
                handle = lib.hvd_timeline_create(self.filename.encode())
                if handle:
                    self._native_lib = lib
                    self._native = handle
                    return
            except Exception:  # noqa: BLE001 — fall back to Python writer
                self._native = None
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="hvd-timeline-writer")
        self._thread.start()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        if self._native is not None:
            with self._native_lock:
                self._native_lib.hvd_timeline_destroy(self._native)
                self._native = None
            return
        self._q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- event emission (engine-facing) ------------------------------------
    def _now_us(self) -> int:
        return time.monotonic_ns() // 1000 - self._start_us

    def _emit(self, ev: dict) -> None:
        if not self._running:
            return
        if self._native is not None:
            with self._native_lock:
                if self._native is None:  # stopped concurrently
                    return
                args = ev.get("args")
                self._native_lib.hvd_timeline_emit(
                    self._native, ev["name"].encode(),
                    ev.get("cat", "").encode(), ev["ph"].encode(), ev["ts"],
                    ev.get("pid", 0), ev.get("tid", 0),
                    json.dumps(args).encode() if args is not None else None)
            return
        self._q.put(ev)

    def begin(self, tensor_name: str, phase: str) -> None:
        self._emit({"name": phase, "cat": phase, "ph": "B",
                    "ts": self._now_us(), "pid": 0,
                    "tid": _tid(tensor_name),
                    "args": {"tensor": tensor_name}})

    def end(self, tensor_name: str, phase: str) -> None:
        self._emit({"name": phase, "cat": phase, "ph": "E",
                    "ts": self._now_us(), "pid": 0,
                    "tid": _tid(tensor_name)})

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._emit({"name": name, "ph": "i", "s": "g",
                    "ts": self._now_us(), "pid": 0, "tid": 0,
                    "args": args or {}})

    def mark_cycle(self) -> None:
        # reference: HOROVOD_TIMELINE_MARK_CYCLES (operations.cc:506)
        if self.mark_cycles:
            self.instant("CYCLE")

    # -- writer thread ------------------------------------------------------
    def _writer(self) -> None:
        # Stream-append with a valid-JSON finalize: the file is opened
        # ONCE and each flush appends only the new events, then writes
        # the "]}" terminator; the next flush seeks back over the
        # terminator and continues with a comma. The file is valid JSON
        # after every flush (friendlier to tooling than the reference's
        # unterminated stream, timeline.cc) and a trace of n events
        # costs O(n) I/O total — the old rewrite-the-whole-file scheme
        # re-READ and re-wrote the entire JSON document every flush,
        # O(n^2) for long traces.
        #
        # A previous writer's events on the same path (elastic restart,
        # dynamic stop_timeline -> start_timeline) are carried forward
        # by ONE read here at open — the append-across-restarts behavior
        # the rewrite scheme provided, without its per-flush cost.
        existing = []
        if os.path.exists(self.filename):
            try:
                with open(self.filename) as f:
                    existing = json.load(f).get("traceEvents", [])
            except Exception:  # noqa: BLE001 — corrupt/foreign file:
                existing = []  # start a fresh trace
        events = []
        with open(self.filename, "w") as f:
            f.write('{"traceEvents": [')
            self._wrote_any = False
            self._finalize(f)
            if existing:
                self._flush(f, existing)
            while True:
                ev = self._q.get()
                if ev is None:
                    break
                events.append(ev)
                # Drain opportunistically to batch writes.
                try:
                    while True:
                        nxt = self._q.get_nowait()
                        if nxt is None:
                            self._flush(f, events)
                            return
                        events.append(nxt)
                except queue.Empty:
                    pass
                if len(events) >= 4096:
                    self._flush(f, events)
                    events = []
            self._flush(f, events)

    def _flush(self, f, events) -> None:
        if not events:
            return
        # rewind over the previous flush's "]}" terminator
        f.seek(self._tail_pos)
        for ev in events:
            if self._wrote_any:
                f.write(",")
            f.write(json.dumps(ev))
            self._wrote_any = True
        self._finalize(f)

    def _finalize(self, f) -> None:
        self._tail_pos = f.tell()
        f.write("]}")
        f.flush()
