"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no sequence parallelism (SURVEY §5.7) — its building block
is the user-level `alltoall` with uneven splits (horovod/common/operations.cc
:1904, torch/mpi_ops.py:960), the core primitive of DeepSpeed-Ulysses-style
SP. This module provides both first-class schemes the TPU way:

* **Ring attention** (`ring_attention`): KV blocks rotate around the mesh
  axis with `lax.ppermute` (ICI-neighbor transfers) while each device
  accumulates flash-attention-style online-softmax partial results for its
  local queries. Communication overlaps compute; memory stays O(local_seq).
* **Ulysses attention** (`ulysses_attention`): `lax.all_to_all` reshards
  [seq-sharded, all heads] -> [head-sharded, full seq], runs dense local
  attention, and reshards back — two all-to-alls per call, best when
  heads >= axis size.

Both are pure lax programs usable inside any shard_map/pjit region, testable
on a CPU mesh, and lower to native ICI collectives on TPU.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _online_softmax_step(o, m, l, s, v):
    """One flash-attention accumulation step in float32.

    o: [B,H,Sq,D] accumulator, m: [B,H,Sq] running max, l: [B,H,Sq] running
    denominator, s: [B,H,Sq,Skv] scores, v: [B,H,Skv,D] values.
    """
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard: fully-masked blocks keep m at NEG_INF; exp(NEG_INF-NEG_INF)
    # must not produce NaN
    safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    corr = jnp.exp(jnp.minimum(m - safe_m, 0.0))
    corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o_new, m_new, l_new


def sp_impl_for(attention_impl):
    """Map a model config's attention_impl to (sp impl, check_vma).

    None = auto — flash kernels on TPU, lax elsewhere (the same
    contract as ops/pallas_attention.fused_attention); "pallas" ->
    flash; "interpret" -> the same kernels in interpret mode with
    shard_map vma checking off (jax's HLO interpreter cannot yet
    propagate vma through pallas calls); anything else -> the lax
    einsum path."""
    if attention_impl is None:
        attention_impl = ("pallas"
                          if jax.devices()[0].platform == "tpu"
                          else "lax")
    if attention_impl == "pallas":
        return "flash", True
    if attention_impl == "interpret":
        return "flash_interpret", False
    return "lax", True


def expand_kv_heads(k: jax.Array, v: jax.Array, groups: int):
    """[B, H_kv, S, D] -> [B, H_kv*groups, S, D] by head repetition; the
    canonical GQA head layout (query head h uses kv head h // groups)
    shared by the dense, ring and Ulysses attention paths."""
    if groups == 1:
        return k, v
    return (jnp.repeat(k, groups, axis=1), jnp.repeat(v, groups, axis=1))


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, *, causal: bool = True,
                   scale: Optional[float] = None,
                   impl: str = "lax") -> jax.Array:
    """Exact attention over a sequence sharded along `axis_name`.

    Inputs are the device-local blocks [B, H, S_local, D] (inside
    shard_map). Returns the local attention output [B, H, S_local, D].
    Sequence positions follow the axis order: device i holds positions
    [i*S_local, (i+1)*S_local).

    GQA: k/v may carry fewer heads (H_kv dividing H). The ring then
    circulates the kv-width blocks — H/H_kv times less ICI traffic —
    and the GQA group is folded into the query sequence dim so every
    local einsum also stays at kv head width (no full-width K/V is
    ever materialized).

    impl: "lax" (default) computes each ring step with masked einsums
    and an online-softmax carry; "flash" computes each step with the
    Pallas flash kernel (ops/pallas_attention.flash_attention_lse) and
    merges per-step partials by their log-sum-exp (flash-decoding-style
    combination) — O(S_local*D) HBM per step instead of the einsum
    path's O(S_local^2) f32 score block. "flash_interpret" runs the
    same kernels in interpret mode (CPU tests).
    """
    if impl in ("flash", "flash_interpret"):
        return _ring_attention_flash(q, k, v, axis_name, causal=causal,
                                     scale=scale,
                                     interpret=impl == "flash_interpret")
    if impl != "lax":
        raise ValueError(f"unknown ring attention impl {impl!r}")
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    Skv = k.shape[2]
    groups = H // k.shape[1]
    scale = scale if scale is not None else 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32) * scale
    if groups > 1:
        # q head h attends kv head h // groups (the expand_kv_heads
        # layout), so [B, H, Sq, D] -> [B, H_kv, groups*Sq, D] folds the
        # group into the row dim of the same kv-width einsums
        qf = qf.reshape(B, H // groups, groups * Sq, D)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o, m, l, kc, vc = carry
        kv_idx = (idx - step) % n

        def active(o, m, l, kc, vc):
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
            if causal:
                q_pos = idx * Sq + jnp.arange(Sq)
                k_pos = kv_idx * Skv + jnp.arange(Skv)
                mask = q_pos[:, None] >= k_pos[None, :]
                if groups > 1:
                    mask = jnp.tile(mask, (groups, 1))
                s = jnp.where(mask[None, None], s, NEG_INF)
            return _online_softmax_step(o, m, l, s, vc)

        if causal:
            # skip fully-masked future blocks (the diagonal block at
            # kv_idx == idx is partially visible and must run)
            o, m, l = lax.cond(kv_idx <= idx, active,
                               lambda o, m, l, kc, vc: (o, m, l),
                               o, m, l, kc, vc)
        else:
            o, m, l = active(o, m, l, kc, vc)
        # rotate KV to the next neighbor (ICI ring)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    # derive initial carries from qf so they are device-varying under
    # shard_map (a plain jnp.zeros would be 'unvarying' and trip the scan
    # carry vma check)
    o0 = qf * 0.0
    m0 = qf[..., 0] * 0.0 + NEG_INF
    l0 = qf[..., 0] * 0.0
    (o, m, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    if groups > 1:
        out = out.reshape(B, H, Sq, D)
    return out.astype(q.dtype)


def _ring_attention_flash(q: jax.Array, k: jax.Array, v: jax.Array,
                          axis_name: str, *, causal: bool,
                          scale: Optional[float], interpret: bool
                          ) -> jax.Array:
    """Ring attention with the Pallas flash kernel per step.

    Each step runs flash_attention_lse on (local q, visiting kv block):
    the diagonal step (kv_idx == idx) uses the causal kernel, earlier
    blocks use the full kernel, later blocks are masked out via
    lse = -inf. Per-step (o_i, lse_i) partials merge with the standard
    online max/sum-exp combination; gradients flow through the kernels'
    custom VJP (live lse cotangent) and the scan.
    """
    from ..ops.pallas_attention import flash_attention_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step_fn(carry, step):
        o_w, m, l, kc, vc = carry
        kv_idx = (idx - step) % n

        def diag(q, kc, vc):
            return flash_attention_lse(q, kc, vc, causal=True,
                                       scale=scale, interpret=interpret)

        def offdiag(q, kc, vc):
            return flash_attention_lse(q, kc, vc, causal=False,
                                       scale=scale, interpret=interpret)

        def skipped(q, kc, vc):
            # future block under causality: zero weight, no kernel run.
            # Derived from q so the outputs carry the same varying-mesh-
            # axes type as the kernel branches (cond requires matching
            # vma; a plain jnp.zeros would be unvarying).
            return (q * 0.0,
                    q[..., 0].astype(jnp.float32) * 0.0 + NEG_INF)

        if causal:
            # three-way branch so causally-masked steps cost nothing
            # (the lax path computes and discards them; here lax.cond
            # runs only the selected branch)
            o_i, lse_i = lax.cond(
                kv_idx == idx, diag,
                lambda q, kc, vc: lax.cond(kv_idx < idx, offdiag,
                                           skipped, q, kc, vc),
                q, kc, vc)
        else:   # non-causal: every block (incl. the diagonal) is full
            o_i, lse_i = offdiag(q, kc, vc)
        o_i = o_i.astype(jnp.float32)
        m_new = jnp.maximum(m, lse_i)
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        corr = jnp.exp(jnp.minimum(m - safe_m, 0.0))
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        w_i = jnp.exp(lse_i - safe_m)
        w_i = jnp.where(lse_i <= NEG_INF / 2, 0.0, w_i)
        o_w = o_w * corr[..., None] + o_i * w_i[..., None]
        l = l * corr + w_i
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o_w, m_new, l, kc, vc), None

    qf32 = q.astype(jnp.float32)
    o0 = qf32 * 0.0
    m0 = qf32[..., 0] * 0.0 + NEG_INF
    l0 = qf32[..., 0] * 0.0
    (o_w, m, l, _, _), _ = lax.scan(step_fn, (o0, m0, l0, k, v),
                                    jnp.arange(n))
    out = o_w / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def zigzag_order(n: int) -> list:
    """Chunk order of the zigzag layout: device i holds sequence chunks
    (i, 2n-1-i) of 2n equal chunks — the balanced-causal sharding."""
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return order


def zigzag_shard(x: jax.Array, n: int, seq_axis: int = 2) -> jax.Array:
    """Permute the global sequence so standard equal sharding over the
    mesh axis hands device i chunks (i, 2n-1-i). Inverse:
    zigzag_unshard. S must divide by 2n."""
    S = x.shape[seq_axis]
    if S % (2 * n):
        raise ValueError(f"seq {S} must divide by 2n={2 * n}")
    c = S // (2 * n)
    shape = x.shape
    split = shape[:seq_axis] + (2 * n, c) + shape[seq_axis + 1:]
    return jnp.take(x.reshape(split), jnp.asarray(zigzag_order(n)),
                    axis=seq_axis).reshape(shape)


def zigzag_unshard(x: jax.Array, n: int, seq_axis: int = 2) -> jax.Array:
    """Inverse permutation of zigzag_shard."""
    S = x.shape[seq_axis]
    c = S // (2 * n)
    inv = [0] * (2 * n)
    for pos, chunk in enumerate(zigzag_order(n)):
        inv[chunk] = pos
    shape = x.shape
    split = shape[:seq_axis] + (2 * n, c) + shape[seq_axis + 1:]
    return jnp.take(x.reshape(split), jnp.asarray(inv),
                    axis=seq_axis).reshape(shape)


def zigzag_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          axis_name: str, *, causal: bool = True,
                          scale: Optional[float] = None,
                          impl: str = "lax") -> jax.Array:
    """Causally load-balanced ring attention over the zigzag layout.

    Plain causal ring attention makes device i compute i+1 of n KV
    blocks — wall clock is the last device's n blocks, ~2x the useful
    work. In the zigzag layout (device i holds sequence chunks i and
    2n-1-i of 2n; see zigzag_shard) every device sees ~2 visible
    half-blocks per ring step, so causal wall clock halves at large n.
    Inputs/outputs are device-local zigzag blocks [B, H, S_local, D]
    (inside shard_map); GQA kv-width blocks circulate like
    ring_attention. Non-causal zigzag is the plain ring (no imbalance
    to fix) and is delegated.

    impl: "lax" masks by true positions inside the einsum;
    "flash"/"flash_interpret" decompose each step into per-chunk-pair
    Pallas kernels (full / diagonal / skipped) merged by LSE, so the
    kernel only runs on visible areas.
    """
    if not causal:
        return ring_attention(q, k, v, axis_name, causal=False,
                              scale=scale, impl=impl)
    if impl in ("flash", "flash_interpret"):
        return _zigzag_flash(q, k, v, axis_name, scale=scale,
                             interpret=impl == "flash_interpret")
    if impl != "lax":
        raise ValueError(f"unknown zigzag attention impl {impl!r}")
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    Skv = k.shape[2]
    groups = H // k.shape[1]
    c = Sq // 2
    ckv = Skv // 2
    scale_ = scale if scale is not None else 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32) * scale_
    if groups > 1:
        qf = qf.reshape(B, H // groups, groups * Sq, D)

    def positions(dev, half_len):
        # local rows -> true positions: first half chunk `dev`, second
        # half chunk 2n-1-dev
        head = dev * half_len + jnp.arange(half_len)
        tail = (2 * n - 1 - dev) * half_len + jnp.arange(half_len)
        return jnp.concatenate([head, tail])

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o, m, l, kc, vc = carry
        src = (idx - step) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
        q_pos = positions(idx, c)
        k_pos = positions(src, ckv)
        mask = q_pos[:, None] >= k_pos[None, :]
        if groups > 1:
            mask = jnp.tile(mask, (groups, 1))
        s = jnp.where(mask[None, None], s, NEG_INF)
        o, m, l = _online_softmax_step(o, m, l, s, vc)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    o0 = qf * 0.0
    m0 = qf[..., 0] * 0.0 + NEG_INF
    l0 = qf[..., 0] * 0.0
    (o, m, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v),
                                  jnp.arange(n))
    out = o / jnp.maximum(l, 1e-20)[..., None]
    if groups > 1:
        out = out.reshape(B, H, Sq, D)
    return out.astype(q.dtype)


def _merge_lse(o_a, lse_a, o_b, lse_b):
    """Combine two flash partials by log-sum-exp (flash-decoding merge).
    Returns (o_weighted_sum, m, l) — caller divides by l at the end."""
    m = jnp.maximum(lse_a, lse_b)
    safe_m = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w_a = jnp.where(lse_a <= NEG_INF / 2, 0.0, jnp.exp(lse_a - safe_m))
    w_b = jnp.where(lse_b <= NEG_INF / 2, 0.0, jnp.exp(lse_b - safe_m))
    o = o_a.astype(jnp.float32) * w_a[..., None] \
        + o_b.astype(jnp.float32) * w_b[..., None]
    return o, m, w_a + w_b


def _zigzag_flash(q, k, v, axis_name, *, scale, interpret):
    """Zigzag causal ring with per-chunk-pair Pallas kernels.

    Each ring step splits the visiting KV block into its (head, tail)
    chunks and the local queries likewise; each of the four chunk pairs
    is exactly full, diagonal, or empty under causality, so the flash
    kernel runs only on visible areas — the balanced schedule that makes
    zigzag ~2x plain causal ring at large n.
    """
    from ..ops.pallas_attention import flash_attention_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sq, D = q.shape
    c = Sq // 2
    q_head, q_tail = q[:, :, :c], q[:, :, c:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def full(qq, kk, vv):
        o, lse = flash_attention_lse(qq, kk, vv, causal=False,
                                     scale=scale, interpret=interpret)
        return o.astype(jnp.float32), lse

    def diag(qq, kk, vv):
        o, lse = flash_attention_lse(qq, kk, vv, causal=True,
                                     scale=scale, interpret=interpret)
        return o.astype(jnp.float32), lse

    def skip(qq, kk, vv):
        return (qq.astype(jnp.float32) * 0.0,
                qq[..., 0].astype(jnp.float32) * 0.0 + NEG_INF)

    def body(carry, step):
        ow_h, m_h, l_h, ow_t, m_t, l_t, kc, vc = carry
        src = (idx - step) % n
        ckv = kc.shape[2] // 2
        k_head, k_tail = kc[:, :, :ckv], kc[:, :, ckv:]
        v_head, v_tail = vc[:, :, :ckv], vc[:, :, ckv:]

        # q_head (chunk idx) vs k_head (chunk src):
        #   src < idx -> full, src == idx -> diagonal, src > idx -> none
        # q_head vs k_tail (chunk 2n-1-src >= n > idx): never visible
        o1, lse1 = lax.cond(
            src == idx, diag,
            lambda a, b, cc: lax.cond(src < idx, full, skip, a, b, cc),
            q_head, k_head, v_head)
        # q_tail (chunk 2n-1-idx) vs k_head (chunk src < n): always full
        o2, lse2 = full(q_tail, k_head, v_head)
        # q_tail vs k_tail (chunk 2n-1-src):
        #   src > idx -> full, src == idx -> diagonal, src < idx -> none
        o3, lse3 = lax.cond(
            src == idx, diag,
            lambda a, b, cc: lax.cond(src > idx, full, skip, a, b, cc),
            q_tail, k_tail, v_tail)

        def merge_into(ow, m, l, o_i, lse_i):
            m_new = jnp.maximum(m, lse_i)
            safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
            corr = jnp.where(m <= NEG_INF / 2, 0.0,
                             jnp.exp(jnp.minimum(m - safe_m, 0.0)))
            w = jnp.where(lse_i <= NEG_INF / 2, 0.0,
                          jnp.exp(lse_i - safe_m))
            return (ow * corr[..., None] + o_i * w[..., None],
                    m_new, l * corr + w)

        ow_h, m_h, l_h = merge_into(ow_h, m_h, l_h, o1, lse1)
        o23, m23, l23 = _merge_lse(o2, lse2, o3, lse3)
        # o23 is weight-summed with denominator l23 at reference max
        # m23: fold as a partial with lse = m23 + log(l23)
        lse23 = jnp.where(l23 > 0.0, m23 + jnp.log(jnp.maximum(l23,
                                                               1e-38)),
                          NEG_INF)
        o23 = o23 / jnp.maximum(l23, 1e-38)[..., None]
        ow_t, m_t, l_t = merge_into(ow_t, m_t, l_t, o23, lse23)

        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (ow_h, m_h, l_h, ow_t, m_t, l_t, kc, vc), None

    def zeros_like_q(qq):
        f = qq.astype(jnp.float32)
        return f * 0.0, f[..., 0] * 0.0 + NEG_INF, f[..., 0] * 0.0

    oh0, mh0, lh0 = zeros_like_q(q_head)
    ot0, mt0, lt0 = zeros_like_q(q_tail)
    (ow_h, _, l_h, ow_t, _, l_t, _, _), _ = lax.scan(
        body, (oh0, mh0, lh0, ot0, mt0, lt0, k, v), jnp.arange(n))
    out_h = ow_h / jnp.maximum(l_h, 1e-20)[..., None]
    out_t = ow_t / jnp.maximum(l_t, 1e-20)[..., None]
    return jnp.concatenate([out_h, out_t], axis=2).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, *, causal: bool = True,
                      scale: Optional[float] = None,
                      impl: str = "lax") -> jax.Array:
    """DeepSpeed-Ulysses-style SP: all_to_all heads<->sequence reshard.

    Local blocks [B, H, S_local, D] with H divisible by the axis size.
    Internally each device sees [B, H/n, S_full, D], computes local
    attention, and reshards back. The all_to_all is the same primitive the
    reference exposes as hvd.alltoall (torch/mpi_ops.py:960).

    GQA: k/v may carry H_kv < H heads. When H_kv divides the axis size
    the kv all_to_all moves only the kv-width tensors and heads are
    broadcast locally (chunk alignment: q chunk d covers global heads
    [d*H/n, (d+1)*H/n), whose kv heads are exactly kv chunk d);
    otherwise k/v are pre-broadcast to full width.

    impl: "lax" computes the local attention densely; "flash" /
    "flash_interpret" run it through the Pallas flash kernel (GQA-aware,
    so the local head broadcast is skipped too).
    """
    n = lax.psum(1, axis_name)
    B, H, S_local, D = q.shape
    H_kv = k.shape[1]
    groups = H // H_kv
    if groups > 1 and H_kv % n:
        # kv heads don't split across the axis: fall back to full width
        k, v = expand_kv_heads(k, v, groups)
        groups = 1

    def to_headsharded(x):
        # split heads across the axis, gather the sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seqsharded(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_headsharded(q), to_headsharded(k), to_headsharded(v)
    if impl in ("flash", "flash_interpret"):
        from ..ops.pallas_attention import flash_attention
        oh = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                             interpret=impl == "flash_interpret")
        return to_seqsharded(oh.astype(q.dtype))
    if impl != "lax":
        raise ValueError(f"unknown ulysses attention impl {impl!r}")
    if groups > 1:  # local head broadcast after the kv-width reshard
        kh, vh = expand_kv_heads(kh, vh, groups)
    S = qh.shape[2]
    scale_ = scale if scale is not None else 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale_
    if causal:
        pos = jnp.arange(S)
        s = jnp.where((pos[:, None] >= pos[None, :])[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    oh = jnp.einsum("bhqk,bhkd->bhqd", p, vh.astype(jnp.float32))
    return to_seqsharded(oh.astype(q.dtype))


def attention_reference(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Single-device dense attention (test oracle)."""
    D = q.shape[-1]
    scale_ = scale if scale is not None else 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale_
    if causal:
        S, Skv = s.shape[-2], s.shape[-1]
        mask = jnp.arange(S)[:, None] >= jnp.arange(Skv)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
