"""Multi-axis mesh construction for hybrid parallelism.

The reference composes hybrid schemes from process sets (SURVEY §2.6); the
TPU-native equivalent is one global Mesh with named axes, each axis playing
the role of one process-set family: 'dp' (data), 'tp' (tensor), 'sp'
(sequence/context), 'ep' (expert), 'pp' (pipeline). XLA maps the leading
axes onto ICI rings of the physical topology.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1, ep: int = 1,
              pp: int = 1, *, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh with only the axes of size > 1 (plus 'dp' always).

    Axis order is (pp, dp, ep, sp, tp): tp innermost so tensor-parallel
    collectives ride the fastest ICI hops; pp outermost so stage transfers
    cross the slowest links only once per microbatch.
    """
    devs = list(devices) if devices is not None else sorted(
        jax.devices(), key=lambda d: d.id)
    sizes = {"pp": pp, "dp": dp, "ep": ep, "sp": sp, "tp": tp}
    total = 1
    for v in sizes.values():
        total *= v
    if total != len(devs):
        raise ValueError(
            f"mesh axes product {total} != device count {len(devs)} "
            f"(axes {sizes})")
    names = [k for k, v in sizes.items() if v > 1]
    if not names:
        names = ["dp"]
    shape = tuple(sizes[k] for k in names)
    arr = np.array(devs, dtype=object).reshape(shape)
    return Mesh(arr, tuple(names))
