"""Pipeline parallelism: SPMD GPipe + 1F1B over a 'pp' mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6 "PP — absent"). The
TPU-native design runs all stages as ONE SPMD program: every device holds its
stage's parameters; activations advance stage-to-stage with `lax.ppermute`
(neighbor ICI transfers) inside a `lax.scan` over clock ticks — the
collective-permute pipeline pattern. Two schedules:

* `gpipe` — forward fill-drain (M + S - 1 ticks); training via jax autodiff
  through the scan (holds all M microbatch activations).
* `pipeline_1f1b` — explicit one-forward-one-backward training step: live
  activations bounded at 2S-1 per stage, parameter grads accumulate online,
  with hooks for non-uniform first/last stages (embedding input grads,
  head/loss parameters) so real LMs pipeline end to end.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pvary(x, axes):
    """Mark `x` device-varying over `axes` (pcast on new jax, pvary on
    old, identity on pre-vma 0.4.x where there is no varying/unvarying
    distinction) — the one copy of the compatibility shim."""
    if isinstance(axes, str):
        axes = (axes,)
    for ax in axes:
        if hasattr(lax, "pcast"):
            x = lax.pcast(x, ax, to="varying")
        elif hasattr(lax, "pvary"):
            x = lax.pvary(x, ax)
    return x


def _masked_add(acc, new, valid):
    """acc + new where `valid`, leafwise over a pytree."""
    return jax.tree_util.tree_map(
        lambda a, g: a + jnp.where(valid, g, jnp.zeros_like(g)),
        acc, new)


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stage_params: Any,
          microbatches: jax.Array,
          axis_name: str = "pp") -> jax.Array:
    """Run a GPipe forward pass inside shard_map.

    stage_fn(params, x) -> y: one stage's computation (same shape in/out).
    stage_params: this device's stage parameters.
    microbatches: [M, mb, ...] — the full input on stage 0 (other stages
    ignore their copy).
    Returns [M, mb, ...]: the pipeline output, valid on the LAST stage
    (zeros elsewhere); callers typically ppermute/psum it home.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        state, outputs = carry            # state: [mb, ...] in-flight act
        # stage 0 injects microbatch t (when one remains); others use the
        # activation received from their left neighbor
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, state)
        y = stage_fn(stage_params, x)
        # last stage records finished microbatch t - (n - 1); a negative
        # slot matches no index, masked update keeps vma types uniform
        out_slot = t - (n - 1)
        sel = (jnp.arange(M) == out_slot) & (idx == n - 1)
        bcast = sel.reshape((M,) + (1,) * len(mb_shape))
        outputs = jnp.where(bcast, y[None], outputs)
        # advance activations around the ring
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outputs), None

    # mark as device-varying along the pp axis so scan carry types are
    # stable (see jax shard_map scan-vma docs)
    state0 = _pvary(jnp.zeros(mb_shape, microbatches.dtype), axis_name)
    out0 = _pvary(jnp.zeros((M,) + mb_shape, microbatches.dtype),
                  axis_name)
    (_, outputs), _ = lax.scan(tick, (state0, out0),
                               jnp.arange(M + n - 1))
    return outputs


def gpipe_and_return(stage_fn, stage_params, microbatches,
                     axis_name: str = "pp") -> jax.Array:
    """gpipe + broadcast of the final output from the last stage to all
    stages (masked psum), so every device returns the result."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    out = gpipe(stage_fn, stage_params, microbatches, axis_name)
    masked = jnp.where(idx == n - 1, out, jnp.zeros_like(out))
    return lax.psum(masked, axis_name)


def pipeline_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  targets: jax.Array,
                  loss_fn: Callable[..., jax.Array],
                  axis_name: str = "pp",
                  *,
                  head_params: Optional[Any] = None,
                  return_input_grads: bool = False,
                  vary_axes: tuple = ()):
    """One-forward-one-backward pipeline training step inside shard_map.

    The memory-bound schedule (beyond the reference; GPipe + jax.grad
    holds all M microbatch activations, 1F1B holds at most 2S-1 per
    stage): each clock tick every stage runs one forward (microbatch
    ``t - s``) and one backward (microbatch ``t - (2S-1-s)``), forward
    activations ppermute right while cotangents ppermute left, and
    parameter gradients accumulate online. Backward recomputes the stage
    forward from the saved input (rematerialization — FLOPs for HBM, the
    TPU trade).

    stage_fn(params, x) -> y: one stage, same shape in/out.
    microbatches: [M, mb, ...] (read on stage 0); targets: [M, ...]
    (read on the last stage). The step optimizes the MEAN over
    microbatches of ``loss_fn(y, target)`` — or, with `head_params`
    given, ``loss_fn(head_params, y, target)``, so an LM head / final
    projection lives inside the loss and its parameter grads come back
    too (they conceptually belong to the last stage; returned replicated
    via psum).

    `return_input_grads=True` additionally returns dL/d(microbatches)
    ([M, mb, ...], replicated) — the hook for a pre-pipeline embedding
    computed outside: embed tokens, pipeline the blocks, backprop the
    returned input grads into the embedding table.

    `vary_axes`: further mesh axes the inputs are device-varying over
    (e.g. a dp axis whose shards carry different microbatches) — the
    scan carries are initialized varying over them too. The caller owns
    any reduction over those axes (e.g. pmean the grads over dp).

    Returns ``(loss, grads)`` — or ``(loss, grads, aux)`` when
    `head_params` or `return_input_grads` is set, with
    ``aux = {"head_grads": ..., "input_grads": ...}`` (absent hooks are
    None). `loss` is the scalar mean loss, identical on every stage;
    `grads` is this stage's parameter-gradient pytree.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    is_last = idx == n - 1
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    B = 2 * n - 1                     # ring-buffer depth = max live acts
    right = [(i, (i + 1) % n) for i in range(n)]
    left = [(i, (i - 1) % n) for i in range(n)]
    inv_m = 1.0 / M
    with_head = head_params is not None
    all_axes = (axis_name,) + tuple(vary_axes)
    # _vary_pp: pp only (for values already varying over vary_axes);
    # _varying: fresh zero-init carries, varying over pp + extra axes
    _vary_pp = lambda x: _pvary(x, axis_name)        # noqa: E731
    _varying = lambda x: _pvary(x, all_axes)         # noqa: E731

    def tick(carry, t):
        (fwd_in, bwd_in, buf, gseed, gacc, hacc, dxs, loss_acc) = carry
        # read the backward half's saved input FIRST: at stage 0 the
        # live-activation window equals the ring depth, so this tick's
        # forward write lands in the same slot
        # (written at tick t_f = t - (2(S-s) - 1))
        bwd_slot = jnp.mod(t - (2 * (n - idx) - 1), B)
        x_saved = lax.dynamic_index_in_dim(buf, bwd_slot, axis=0,
                                           keepdims=False)
        # ---- forward: microbatch t - s -------------------------------
        m_f = t - idx
        f_valid = (m_f >= 0) & (m_f < M)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(m_f, 0, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, fwd_in)
        # zero invalid lanes BEFORE compute so junk can't make NaNs that
        # survive multiplicative masking
        x = jnp.where(f_valid, x, jnp.zeros_like(x))
        y = stage_fn(stage_params, x)
        buf = lax.dynamic_update_index_in_dim(buf, x, jnp.mod(t, B),
                                              axis=0)
        # last stage: per-microbatch loss + the backward seed dL/dy,
        # consumed by the backward half exactly one tick later
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(m_f, 0, M - 1), axis=0, keepdims=False)
        lmask = f_valid & is_last
        if with_head:
            # pvary the head first: a replicated (unvarying) primal
            # makes vma-aware AD insert an implicit psum inside the vjp,
            # folding OTHER stages' mid-pipeline activations into dhead
            hp = jax.tree_util.tree_map(_vary_pp, head_params)
            lval, loss_vjp = jax.vjp(loss_fn, hp, y, tgt)
            # seed inherits lval's device-varying type via zeros_like
            dhead, gy, _ = loss_vjp(jnp.zeros_like(lval)
                                    + jnp.asarray(inv_m, lval.dtype))
            hacc = _masked_add(hacc, dhead, lmask)
        else:
            lval, loss_vjp = jax.vjp(loss_fn, y, tgt)
            gy = loss_vjp(jnp.zeros_like(lval)
                          + jnp.asarray(inv_m, lval.dtype))[0]
        loss_acc = loss_acc + jnp.where(lmask, lval * inv_m, 0.0)
        new_gseed = jnp.where(lmask, gy, jnp.zeros_like(gy))
        # ---- backward: microbatch t - (2S-1-s) -----------------------
        m_b = t - (2 * n - 1 - idx)
        b_valid = (m_b >= 0) & (m_b < M)
        g_in = jnp.where(is_last, gseed, bwd_in)
        g_in = jnp.where(b_valid, g_in, jnp.zeros_like(g_in))
        _, stage_vjp = jax.vjp(stage_fn, stage_params, x_saved)
        dparams, dx = stage_vjp(g_in)
        gacc = _masked_add(gacc, dparams, b_valid)
        if return_input_grads:
            # stage 0's dx IS dL/d(microbatch m_b)
            written = lax.dynamic_update_index_in_dim(
                dxs, dx, jnp.clip(m_b, 0, M - 1), axis=0)
            dxs = jnp.where(b_valid & (idx == 0), written, dxs)
        # ---- advance the rings ---------------------------------------
        fwd_in = lax.ppermute(y, axis_name, right)
        bwd_in = lax.ppermute(dx, axis_name, left)
        return (fwd_in, bwd_in, buf, new_gseed, gacc, hacc, dxs,
                loss_acc), None

    dt = microbatches.dtype
    zero_act = lambda: _varying(jnp.zeros(mb_shape, dt))  # noqa: E731
    zero_tree = lambda tree: jax.tree_util.tree_map(      # noqa: E731
        lambda p: _varying(jnp.zeros(p.shape, p.dtype)), tree)
    carry0 = (zero_act(),                                # fwd ring
              zero_act(),                                # bwd ring
              _varying(jnp.zeros((B,) + mb_shape, dt)),  # act buffer
              zero_act(),                                # loss seed
              zero_tree(stage_params),
              zero_tree(head_params) if with_head else (),
              _varying(jnp.zeros((M,) + mb_shape, dt))
              if return_input_grads else (),
              _varying(jnp.zeros((), jnp.float32)))
    (_, _, _, _, grads, hacc, dxs, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * n - 1))
    # only the last stage accumulated loss; share it with every stage
    loss = lax.psum(loss_acc, axis_name)
    if not with_head and not return_input_grads:
        return loss, grads
    aux = {"head_grads": None, "input_grads": None}
    if with_head:
        # accumulated on the last stage only; replicate
        aux["head_grads"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), hacc)
    if return_input_grads:
        aux["input_grads"] = lax.psum(dxs, axis_name)  # stage 0's writes
    return loss, grads, aux


def pipeline_interleaved_1f1b(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        stage_params: Any,
        microbatches: jax.Array,
        targets: jax.Array,
        loss_fn: Callable[..., jax.Array],
        axis_name: str = "pp",
        *,
        head_params: Optional[Any] = None,
        return_input_grads: bool = False,
        vary_axes: tuple = ()):
    """Interleaved (virtual-stage) 1F1B: Megatron-style bubble shrink.

    `stage_params` is stacked [V, ...]: this device owns V virtual
    stages — global stage i + j·n for chunk j on device i — so the
    pipeline has S·V stages on S devices and the fill/drain bubble per
    microbatch group shrinks by V (activations just flow around the
    same ppermute ring V times; stage n·j's input arrives from device
    n-1's chunk j-1 via the ordinary wrap). Schedules forward of
    microbatch m on global stage s at tick m+s and backward at tick
    m+2nV−s; each device still runs at most one forward and one
    backward per tick.

    Constraint: M ≤ n (one microbatch group — the Megatron group size).
    For more microbatches, run waves of n and combine (losses average,
    gradients add).

    Same hooks and return convention as pipeline_1f1b; grads come back
    stacked [V, ...] matching `stage_params`.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    if M > n:
        raise ValueError(
            f"interleaved schedule takes one microbatch group at a time "
            f"(M={M} > stages={n}); run waves of {n} and combine")
    V = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    mb_shape = microbatches.shape[1:]
    B = 2 * n * V                     # ring-buffer depth (window max)
    right = [(i, (i + 1) % n) for i in range(n)]
    left = [(i, (i - 1) % n) for i in range(n)]
    inv_m = 1.0 / M
    with_head = head_params is not None
    all_axes = (axis_name,) + tuple(vary_axes)
    _vary_pp = lambda x: _pvary(x, axis_name)        # noqa: E731
    _varying = lambda x: _pvary(x, all_axes)         # noqa: E731

    def _chunk_params(j):
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(
                a, jnp.clip(j, 0, V - 1), axis=0, keepdims=False),
            stage_params)

    def tick(carry, t):
        (fwd_in, bwd_in, buf, gseed, gacc, hacc, dxs, loss_acc) = carry
        # ---- backward indices + saved-input read (before the write:
        # the (i=0, j=0) window equals the ring depth) ----------------
        # bwd of (m, stage s=i+jn) runs at t = m + 2nV - 1 - i - jn,
        # so w := t - (2nV - 1) + i = m - jn
        w = t - 2 * n * V + 1 + idx
        m_b = jnp.mod(w, n)
        j_b = (m_b - w) // n
        b_valid = (w <= m_b) & (j_b < V) & (m_b < M)
        slot_r = jnp.mod(m_b + idx + j_b * n, B)
        x_saved = lax.dynamic_index_in_dim(buf, slot_r, axis=0,
                                           keepdims=False)
        # ---- forward: device i, tick t -> (m, chunk) ----------------
        r = t - idx
        m_f = jnp.mod(r, n)
        j_f = r // n
        f_valid = (r >= 0) & (m_f < M) & (j_f < V)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(m_f, 0, M - 1), axis=0,
            keepdims=False)
        # global stage 0 == device 0 chunk 0 injects; every other
        # (device, chunk) takes the ring value (device 0's chunks j>0
        # receive device n-1 chunk j-1 through the ordinary wrap)
        x = jnp.where((idx == 0) & (j_f == 0), inject, fwd_in)
        x = jnp.where(f_valid, x, jnp.zeros_like(x))
        y = stage_fn(_chunk_params(j_f), x)
        buf = lax.dynamic_update_index_in_dim(buf, x, jnp.mod(t, B),
                                              axis=0)
        tgt = lax.dynamic_index_in_dim(
            targets, jnp.clip(m_f, 0, M - 1), axis=0, keepdims=False)
        lmask = f_valid & (idx == n - 1) & (j_f == V - 1)
        if with_head:
            hp = jax.tree_util.tree_map(_vary_pp, head_params)
            lval, loss_vjp = jax.vjp(loss_fn, hp, y, tgt)
            dhead, gy, _ = loss_vjp(jnp.zeros_like(lval)
                                    + jnp.asarray(inv_m, lval.dtype))
            hacc = _masked_add(hacc, dhead, lmask)
        else:
            lval, loss_vjp = jax.vjp(loss_fn, y, tgt)
            gy = loss_vjp(jnp.zeros_like(lval)
                          + jnp.asarray(inv_m, lval.dtype))[0]
        loss_acc = loss_acc + jnp.where(lmask, lval * inv_m, 0.0)
        new_gseed = jnp.where(lmask, gy, jnp.zeros_like(gy))
        # ---- backward ------------------------------------------------
        g_in = jnp.where((idx == n - 1) & (j_b == V - 1), gseed, bwd_in)
        g_in = jnp.where(b_valid, g_in, jnp.zeros_like(g_in))
        _, stage_vjp = jax.vjp(stage_fn, _chunk_params(j_b), x_saved)
        dparams, dx = stage_vjp(g_in)
        gacc = jax.tree_util.tree_map(
            lambda acc, g: lax.dynamic_update_index_in_dim(
                acc,
                lax.dynamic_index_in_dim(
                    acc, jnp.clip(j_b, 0, V - 1), axis=0,
                    keepdims=False)
                + jnp.where(b_valid, g, jnp.zeros_like(g)),
                jnp.clip(j_b, 0, V - 1), axis=0),
            gacc, dparams)
        if return_input_grads:
            written = lax.dynamic_update_index_in_dim(
                dxs, dx, jnp.clip(m_b, 0, M - 1), axis=0)
            dxs = jnp.where(b_valid & (idx == 0) & (j_b == 0),
                            written, dxs)
        # ---- rings ---------------------------------------------------
        fwd_in = lax.ppermute(y, axis_name, right)
        bwd_in = lax.ppermute(dx, axis_name, left)
        return (fwd_in, bwd_in, buf, new_gseed, gacc, hacc, dxs,
                loss_acc), None

    dt = microbatches.dtype
    zero_act = lambda: _varying(jnp.zeros(mb_shape, dt))  # noqa: E731
    zero_tree = lambda tree: jax.tree_util.tree_map(      # noqa: E731
        lambda p: _varying(jnp.zeros(p.shape, p.dtype)), tree)
    carry0 = (zero_act(),                                # fwd ring
              zero_act(),                                # bwd ring
              _varying(jnp.zeros((B,) + mb_shape, dt)),  # act buffer
              zero_act(),                                # loss seed
              zero_tree(stage_params),                   # [V, ...] gacc
              zero_tree(head_params) if with_head else (),
              _varying(jnp.zeros((M,) + mb_shape, dt))
              if return_input_grads else (),
              _varying(jnp.zeros((), jnp.float32)))
    (_, _, _, _, grads, hacc, dxs, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * n * V - 1))
    loss = lax.psum(loss_acc, axis_name)
    if not with_head and not return_input_grads:
        return loss, grads
    aux = {"head_grads": None, "input_grads": None}
    if with_head:
        aux["head_grads"] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name), hacc)
    if return_input_grads:
        aux["input_grads"] = lax.psum(dxs, axis_name)
    return loss, grads, aux


def pipeline_interleaved_waves(stage_fn, stage_params, microbatches,
                               targets, loss_fn, axis_name: str = "pp",
                               *, head_params: Optional[Any] = None,
                               return_input_grads: bool = False,
                               vary_axes: tuple = ()):
    """Interleaved 1F1B over M > S microbatches: waves of S groups.

    Scans pipeline_interleaved_1f1b over ⌈M/S⌉ groups of S microbatches
    (M must divide by S), averaging losses and every gradient family —
    the exact mean-over-M objective of pipeline_1f1b. Same return
    convention; with `return_input_grads` the per-wave input grads
    reassemble to [M, mb, ...].
    """
    n = lax.psum(1, axis_name)
    M = microbatches.shape[0]
    if M <= n:
        return pipeline_interleaved_1f1b(
            stage_fn, stage_params, microbatches, targets, loss_fn,
            axis_name, head_params=head_params,
            return_input_grads=return_input_grads, vary_axes=vary_axes)
    if M % n:
        raise ValueError(f"microbatch count {M} must divide by the "
                         f"stage count {n} for wave scheduling")
    W = M // n
    xs_w = microbatches.reshape((W, n) + microbatches.shape[1:])
    ts_w = targets.reshape((W, n) + targets.shape[1:])
    with_head = head_params is not None

    def wave(carry, inputs):
        gsum, hsum, lsum = carry
        xw, tw = inputs
        out = pipeline_interleaved_1f1b(
            stage_fn, stage_params, xw, tw, loss_fn, axis_name,
            head_params=head_params,
            return_input_grads=return_input_grads,
            vary_axes=vary_axes)
        if with_head or return_input_grads:
            loss, grads, aux = out
        else:
            loss, grads = out
            aux = {"head_grads": None, "input_grads": None}
        gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
        if with_head:
            hsum = jax.tree_util.tree_map(jnp.add, hsum,
                                          aux["head_grads"])
        return (gsum, hsum, lsum + loss), aux["input_grads"]

    # zero carries derived from the params/inputs so they inherit the
    # same device-varying axes as the per-wave outputs
    zero_g = jax.tree_util.tree_map(lambda p: p * 0, stage_params)
    zero_h = jax.tree_util.tree_map(lambda p: p * 0, head_params) \
        if with_head else ()

    (gsum, hsum, lsum), dxs_w = lax.scan(
        wave, (zero_g, zero_h,
               _pvary(jnp.zeros((), jnp.float32), vary_axes)),
        (xs_w, ts_w))
    inv_w = 1.0 / W
    loss = lsum * inv_w
    grads = jax.tree_util.tree_map(lambda g: g * inv_w, gsum)
    if not with_head and not return_input_grads:
        return loss, grads
    aux = {"head_grads": None, "input_grads": None}
    if with_head:
        aux["head_grads"] = jax.tree_util.tree_map(
            lambda g: g * inv_w, hsum)
    if return_input_grads:
        # [W, n, mb...] -> [M, mb...]; each wave's grads are d(wave
        # mean)/dx — rescale to the global mean
        aux["input_grads"] = dxs_w.reshape(
            (M,) + dxs_w.shape[2:]) * inv_w
    return loss, grads, aux
