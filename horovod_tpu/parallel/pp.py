"""Pipeline parallelism: SPMD GPipe over a 'pp' mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6 "PP — absent"). The
TPU-native design runs all stages as ONE SPMD program: every device holds its
stage's parameters; activations advance stage-to-stage with `lax.ppermute`
(neighbor ICI transfers) inside a `lax.scan` over clock ticks — the
collective-permute pipeline pattern. GPipe fill-drain schedule: with M
microbatches and S stages, M + S - 1 ticks.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stage_params: Any,
          microbatches: jax.Array,
          axis_name: str = "pp") -> jax.Array:
    """Run a GPipe forward pass inside shard_map.

    stage_fn(params, x) -> y: one stage's computation (same shape in/out).
    stage_params: this device's stage parameters.
    microbatches: [M, mb, ...] — the full input on stage 0 (other stages
    ignore their copy).
    Returns [M, mb, ...]: the pipeline output, valid on the LAST stage
    (zeros elsewhere); callers typically ppermute/psum it home.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        state, outputs = carry            # state: [mb, ...] in-flight act
        # stage 0 injects microbatch t (when one remains); others use the
        # activation received from their left neighbor
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, state)
        y = stage_fn(stage_params, x)
        # last stage records finished microbatch t - (n - 1); a negative
        # slot matches no index, masked update keeps vma types uniform
        out_slot = t - (n - 1)
        sel = (jnp.arange(M) == out_slot) & (idx == n - 1)
        bcast = sel.reshape((M,) + (1,) * len(mb_shape))
        outputs = jnp.where(bcast, y[None], outputs)
        # advance activations around the ring
        state = lax.ppermute(y, axis_name, fwd_perm)
        return (state, outputs), None

    def _varying(x):
        # mark as device-varying along the pp axis so scan carry types are
        # stable (see jax shard_map scan-vma docs)
        if hasattr(lax, "pcast"):
            return lax.pcast(x, axis_name, to="varying")
        return lax.pvary(x, axis_name)

    state0 = _varying(jnp.zeros(mb_shape, microbatches.dtype))
    out0 = _varying(jnp.zeros((M,) + mb_shape, microbatches.dtype))
    (_, outputs), _ = lax.scan(tick, (state0, out0),
                               jnp.arange(M + n - 1))
    return outputs


def gpipe_and_return(stage_fn, stage_params, microbatches,
                     axis_name: str = "pp") -> jax.Array:
    """gpipe + broadcast of the final output from the last stage to all
    stages (masked psum), so every device returns the result."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    out = gpipe(stage_fn, stage_params, microbatches, axis_name)
    masked = jnp.where(idx == n - 1, out, jnp.zeros_like(out))
    return lax.psum(masked, axis_name)
