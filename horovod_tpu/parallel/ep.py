"""Expert parallelism: switch-style MoE dispatch over an 'ep' mesh axis.

The reference's accounting (SURVEY §2.6): "EP — absent; alltoall + process
sets are the primitives an MoE implementation would use." This module is that
implementation, TPU-native: priority-ordered top-k routing (k=1 Switch,
k=2 GShard/Mixtral) with fixed expert capacity (static shapes for XLA),
dispatch/combine as einsums against a one-hot dispatch mask,
and `lax.all_to_all` moving token buffers between expert shards — the same
primitive the reference exposes as hvd.alltoall (torch/mpi_ops.py:960).
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def topk_route(logits: jax.Array, num_experts: int, capacity: int,
               k: int = 1, normalize: bool = True
               ) -> Tuple[jax.Array, jax.Array]:
    """Top-k router with capacity dropping.

    k=1 (normalize=False) is Switch-Transformer routing; k=2 with
    normalized gates is the GShard/Mixtral scheme. Choices are placed in
    priority order: every token's 1st choice claims buffer slots before
    any 2nd choice does, so under capacity pressure second choices drop
    first (GShard semantics).

    logits: [T, E]. Returns (dispatch [T, E, C] one-hot, combine
    [T, E, C] gate-weighted), both zero for dropped tokens.
    """
    if not 1 <= k <= num_experts:
        raise ValueError(f"top-k k={k} must be in [1, num_experts="
                         f"{num_experts}]")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    occupancy = jnp.zeros((num_experts,), jnp.float32)  # slots used so far
    masked = probs
    dispatches, gates = [], []
    for _ in range(k):
        expert = jnp.argmax(masked, axis=-1)                  # [T]
        gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
        # position within the expert buffer, offset by earlier choices
        pos = (jnp.cumsum(onehot, axis=0) + occupancy[None, :]) \
            * onehot - 1.0                                     # [T, E]
        in_cap = (pos < capacity) & (pos >= 0)
        pos_cap = jnp.where(in_cap, pos, 0).astype(jnp.int32)
        dispatches.append((onehot * in_cap)[..., None] * jax.nn.one_hot(
            pos_cap, capacity, dtype=jnp.float32))             # [T, E, C]
        gates.append(gate)
        occupancy = occupancy + onehot.sum(axis=0)
        masked = jnp.where(onehot > 0, -jnp.inf, masked)
    dispatch = sum(dispatches)
    if normalize and k > 1:   # Mixtral-style: chosen gates sum to 1
        denom = jnp.maximum(sum(gates), 1e-9)
        gates = [g / denom for g in gates]
    combine = sum(d * g[:, None, None] for d, g in zip(dispatches, gates))
    return dispatch, combine


def top1_route(logits: jax.Array, num_experts: int, capacity: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Top-1 router with capacity dropping (Switch Transformer style)."""
    return topk_route(logits, num_experts, capacity, k=1, normalize=False)


def moe_layer(x: jax.Array, router_w: jax.Array, expert_fn: Callable,
              expert_params, *, axis_name: str = "ep",
              capacity_factor: float = 1.25,
              logits: jax.Array = None, top_k: int = 1) -> jax.Array:
    """Expert-parallel MoE for use inside shard_map.

    x: local tokens [T_local, D]. `expert_params` are the LOCAL experts'
    parameters, stacked on a leading axis [E_local, ...]. Global expert
    count = E_local * ep_size. Dispatch crosses the 'ep' axis via
    all_to_all; combine returns by the reverse all_to_all.

    Pass precomputed fp32 `logits` [T_local, E] to route on exactly the
    values a caller also uses for the load-balancing aux loss (avoids a
    second router matmul and bf16/fp32 divergence on near-tie tokens);
    `router_w` is ignored then and may be None.
    """
    n = lax.psum(1, axis_name)
    T, D = x.shape
    e_local = jax.tree_util.tree_leaves(expert_params)[0].shape[0]
    E = e_local * n
    capacity = max(1, int(capacity_factor * T / E))

    capacity = capacity * top_k  # k choices share the buffer
    if logits is None:
        logits = x @ router_w                                   # [T, E]
    dispatch, combine = topk_route(logits, E, capacity, k=top_k)

    # token buffers per global expert: [E, C, D]
    buffers = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # reshape to [n, E_local, C, D] and all_to_all so shard j receives the
    # buffers for ITS experts from every shard: result [n, E_local, C, D]
    # with axis 0 = source shard
    send = buffers.reshape(n, e_local, capacity, D)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    # merge the per-source buffers: experts process all n*C slots
    expert_in = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, D)
    expert_out = jax.vmap(expert_fn)(expert_params,
                                     expert_in.astype(x.dtype))
    expert_out = expert_out.astype(jnp.float32).reshape(
        e_local, n, capacity, D).transpose(1, 0, 2, 3)          # [n,EL,C,D]
    # return results to the source shards
    back = lax.all_to_all(expert_out, axis_name, split_axis=0,
                          concat_axis=0, tiled=True)            # [n,EL,C,D]
    out_buffers = back.reshape(E, capacity, D)
    y = jnp.einsum("tec,ecd->td", combine, out_buffers)
    return y.astype(x.dtype)


def moe_reference(x, router_w, expert_fn, all_expert_params,
                  capacity_factor: float = 1.25, logits=None,
                  top_k: int = 1):
    """Single-device oracle: same routing/capacity, all experts local."""
    T, D = x.shape
    E = jax.tree_util.tree_leaves(all_expert_params)[0].shape[0]
    capacity = max(1, int(capacity_factor * T / E)) * top_k
    if logits is None:
        logits = x @ router_w
    dispatch, combine = topk_route(logits, E, capacity, k=top_k)
    buffers = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    out = jax.vmap(expert_fn)(all_expert_params, buffers.astype(x.dtype))
    y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
    return y.astype(x.dtype)
