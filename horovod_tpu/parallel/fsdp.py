"""ZeRO/FSDP-style parameter + optimizer-state sharding over the dp axis.

The reference's data parallelism always replicates parameters and
optimizer state on every rank (DistributedOptimizer,
/root/reference/horovod/torch/optimizer.py:36 — each rank holds the full
model and allreduces gradients). On TPU the GSPMD partitioner makes the
fully-sharded variant a pure annotation change: shard each large
parameter along one dimension over the data axis and keep the batch
sharded on the same axis, and XLA emits the all-gather (weights, fwd/bwd)
and reduce-scatter (gradients) schedule — the scaling-book FSDP recipe.
Optimizer state created from the sharded params inherits the shardings,
so Adam moments are sharded N-ways too (ZeRO-2/3 memory scaling).

`FSDPRules` wraps any base `PartitionRules` (e.g. llama/gpt TP rules):
leaves keep their TP axes and additionally shard their largest
still-unsharded dimension over `axis` when the leaf is big enough and
the dimension divides the axis size. It exposes the same `tree_specs`
interface, so `shard_params` / `make_gspmd_train_step` work unchanged.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from .tp import PartitionRules, _restrict_spec, keypath_str


class FSDPRules:
    """Size-aware wrapper: base rules + fully-sharded data parallelism.

    axis: mesh axis to shard parameters over (usually the dp axis).
    min_size: leaves with fewer elements stay replicated over `axis`
        (tiny tensors cost more to gather than to replicate — the same
        threshold idea as the reference's fusion threshold, applied to
        weight sharding).
    """

    def __init__(self, base: Optional[PartitionRules], mesh: Mesh,
                 axis: str = "dp", min_size: int = 2 ** 14):
        self.base = base or PartitionRules([])
        self.mesh = mesh
        self.axis = axis
        self.axis_size = mesh.shape.get(axis, 1)
        self.min_size = min_size

    def _leaf_spec(self, path: str, leaf: Any) -> P:
        spec = _restrict_spec(self.base.spec_for(path), self.mesh)
        shape = getattr(leaf, "shape", ())
        entries = list(spec) + [None] * (len(shape) - len(spec))
        if (self.axis_size <= 1
                or getattr(leaf, "size", 0) < self.min_size):
            return P(*entries)
        # largest unsharded dim that divides the axis: gather volume is
        # the same for any dim, but larger dims keep per-shard blocks
        # lane-aligned
        cands = [d for d, e in enumerate(entries)
                 if e is None and shape[d] % self.axis_size == 0]
        if not cands:
            return P(*entries)
        d = max(cands, key=lambda i: shape[i])
        entries[d] = self.axis
        return P(*entries)

    def tree_specs(self, params: Any) -> Any:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = [self._leaf_spec(keypath_str(kp), leaf)
                 for kp, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    # PartitionRules interface parity (spec_for has no leaf, so it is the
    # base behavior; use tree_specs for FSDP placement)
    def spec_for(self, path: str) -> P:
        return self.base.spec_for(path)
