"""Tensor parallelism: partition rules (GSPMD path) + shard_map primitives.

The reference's building block for hybrid parallelism is the process set
(SURVEY §2.6 "TP — absent; process sets are the primitives"). The TPU-native
design gives TP first-class support two ways:

1. **GSPMD path** (`PartitionRules`, `shard_params`): regex rules map
   parameter pytree paths to PartitionSpecs (Megatron-style: column-parallel
   up-projections sharded on the output dim, row-parallel down-projections
   on the input dim). `jit` then auto-inserts the psums — the scaling-book
   recipe: annotate shardings, let XLA place collectives on ICI.
2. **shard_map path** (`column_parallel_dense` / `row_parallel_dense`):
   explicit local matmuls + psum for hand-rolled layers, mirroring how a
   reference user would compose TP from process-set allreduces
   (docs/process_set.rst).
"""
from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def keypath_str(keypath) -> str:
    """'/'-joined pytree key path, e.g. 'layers_0/attn/qkv/kernel'."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in keypath)


class PartitionRules:
    """Ordered (path-regex -> PartitionSpec) rules; first match wins.

    Paths are '/'-joined pytree key paths, e.g.
    'transformer/layers_0/attn/qkv/kernel'.
    """

    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        return P()  # replicated by default

    def tree_specs(self, params: Any) -> Any:
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        specs = [self.spec_for(keypath_str(kp)) for kp, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)


def _restrict_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes the rule names but this mesh doesn't have, so one
    rule set serves every mesh shape (a dp-only mesh simply replicates the
    tp/ep-sharded dims)."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(keep(e) for e in spec))


def shard_params(params: Any, mesh: Mesh, rules: PartitionRules) -> Any:
    """Place a parameter pytree according to the rules.

    Multi-process safe: when the mesh spans processes, each process
    contributes its addressable shards from its (identical) host copy
    (core.mesh.place_sharded) — the GSPMD analog of the launcher's
    replicated-init convention (every worker initializes with the same
    PRNG key, reference broadcast-of-initial-state semantics)."""
    from ..core.mesh import place_sharded
    specs = rules.tree_specs(params)
    return jax.tree_util.tree_map(
        lambda x, s: place_sharded(
            x, NamedSharding(mesh, _restrict_spec(s, mesh))),
        params, specs)


def param_shardings(params: Any, mesh: Mesh, rules: PartitionRules) -> Any:
    """NamedSharding pytree (for jit in_shardings/out_shardings)."""
    specs = rules.tree_specs(params)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, _restrict_spec(s, mesh)), specs,
        is_leaf=lambda x: isinstance(x, P))


# Megatron-style rules for the GPT model in models/gpt.py: attention QKV and
# MLP up-projection are column-parallel (output dim on 'tp'), attention
# output and MLP down-projection are row-parallel (input dim on 'tp'),
# embeddings shard the vocab/hidden dim.
def gpt_partition_rules(tp_axis: str = "tp") -> PartitionRules:
    return PartitionRules([
        (r"attn/qkv/kernel", P(None, tp_axis)),
        (r"attn/out/kernel", P(tp_axis, None)),
        (r"mlp/up/kernel", P(None, tp_axis)),
        (r"mlp/down/kernel", P(tp_axis, None)),
        (r"embed/embedding", P(None, tp_axis)),
        (r"lm_head/kernel", P(None, tp_axis)),
        # biases of column-parallel layers follow the sharded output dim
        (r"attn/qkv/bias", P(tp_axis)),
        (r"mlp/up/bias", P(tp_axis)),
    ])


# ---- shard_map-level primitives -------------------------------------------

def column_parallel_dense(x: jax.Array, w_local: jax.Array,
                          b_local=None) -> jax.Array:
    """y_local = x @ W_local: output features sharded over tp (no comm)."""
    y = jnp.einsum("...i,io->...o", x, w_local)
    if b_local is not None:
        y = y + b_local
    return y


def row_parallel_dense(x_local: jax.Array, w_local: jax.Array,
                       b=None, axis_name: str = "tp") -> jax.Array:
    """y = psum_tp(x_local @ W_local): input features sharded over tp;
    one psum on the tp ring."""
    y = lax.psum(jnp.einsum("...i,io->...o", x_local, w_local), axis_name)
    if b is not None:
        y = y + b
    return y
