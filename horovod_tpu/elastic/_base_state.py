"""Shared scaffolding for framework-specific elastic states.

The reference gives every framework its own State handler
(horovod/common/elastic.py:60 State, torch/elastic/state.py TorchState,
keras/elastic.py KerasState) that shares one contract: extra kwargs
become named attributes, `commit()` snapshots, `restore()` rolls back
to the last snapshot, and `sync()` broadcasts rank 0's live state THEN
refreshes the snapshot (common/elastic.py ObjectState.sync — without
the save-after-sync, a restore() after a post-join failure would roll
ranks back to pre-sync divergent states).

This base is deliberately jax-free so the torch/keras bindings can
import it without pulling jax into their worker processes; the jax
State in elastic/state.py keeps its own pytree-aware implementation.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List

#: elastic recovery metric help — shared by the driver leg
#: (driver.py) and the worker leg (run.py); single-sourced so the
#: copies cannot drift (metric-help lint).
RECOVERY_MS_HELP = ("elastic recovery: failure caught -> state "
                    "re-synced on the new plane")
LAST_RECOVERY_MS_HELP = "latency of the most recent elastic recovery"



class BaseFrameworkState:
    """Subclasses implement `_save_payload() -> Any`,
    `_restore_payload(snapshot)`, `_sync_payload(root_rank)`, and
    `_broadcast_extras(extras, root_rank) -> extras`."""

    def __init__(self, **extras):
        self._extras: Dict[str, Any] = dict(extras)
        self._saved = None
        self._reset_callbacks: List[Callable] = []
        # same liveness token as elastic/state.py State.commit_serial
        # (the jax State keeps its own implementation — change BOTH)
        self._commit_serial = -1
        self.commit()

    def __getattr__(self, name):
        extras = object.__getattribute__(self, "_extras")
        if name in extras:
            return extras[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._extras[name] = value

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def load_latest(self, target=None) -> bool:
        """Disk-commit restore hook (elastic/state.py State.load_latest
        contract): memory-only framework states have nothing on disk."""
        return False

    def save(self) -> None:
        self._saved = {"extras": copy.deepcopy(self._extras),
                       "payload": self._save_payload()}

    @property
    def commit_serial(self) -> int:
        """Monotone count of commit() calls (0 = construction only) —
        the redist/elastic.py holder-election token."""
        return self._commit_serial

    def commit(self) -> None:
        self.save()
        self._commit_serial += 1

    def restore(self) -> None:
        self._extras = copy.deepcopy(self._saved["extras"])
        self._restore_payload(self._saved["payload"])

    def sync(self, root_rank: int = 0) -> None:
        self._sync_payload(root_rank)
        self._extras = self._broadcast_extras(self._extras, root_rank)
        # refresh the snapshot: a restore() after sync must reproduce
        # the synced state, not each rank's pre-sync one
        self.save()

    # -- subclass hooks ------------------------------------------------

    def _save_payload(self):
        raise NotImplementedError

    def _restore_payload(self, snapshot) -> None:
        raise NotImplementedError

    def _sync_payload(self, root_rank: int) -> None:
        raise NotImplementedError

    def _broadcast_extras(self, extras, root_rank: int):
        # default: pickle-broadcast over the interop CPU plane (late
        # import keeps this module importable without the plane); the
        # plane's object ops already no-op at size 1
        from ..interop import _plane
        if _plane.size() == 1:
            return extras
        return _plane.broadcast_object(extras, root_rank=root_rank)
