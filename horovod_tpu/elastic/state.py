"""Elastic state objects: in-memory checkpoint with commit/restore/sync.

Re-design of the reference's elastic state layer (horovod/common/elastic.py:
60-148 State/ObjectState and horovod/torch/elastic/state.py TorchState):
`commit()` snapshots, `restore()` rolls back to the last commit, `sync()`
broadcasts from the root so re-admitted or new workers converge. Here state
values are pytrees of jax arrays / picklable python objects; sync pins
arrays to the replicated sharding of the current mesh (single-controller) or
broadcasts over DCN (multi-process) via optim.functions.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..optim.functions import broadcast_object, broadcast_parameters


class State:
    """Base elastic state (common/elastic.py:60).

    Subclasses or instances carry named values; `register_reset_callbacks`
    mirrors the reference hook invoked after a topology change.

    The foreign-framework bindings implement the same contract
    (commit/restore/sync-then-save, extras attributes) on
    `elastic/_base_state.py BaseFrameworkState`; this jax State keeps
    its own pytree-aware implementation — change semantics in BOTH.
    """

    def __init__(self, **kwargs):
        self._saved: Dict[str, Any] = {}
        self._reset_callbacks: List[Callable] = []
        self._values: Dict[str, Any] = {}
        # commit() calls since construction (the constructor's initial
        # snapshot counts as 0): the liveness token the in-memory
        # redistribution plane (redist/elastic.py) compares across
        # ranks — a rank at serial 0 holds only initial values, a rank
        # at the fleet-max serial holds the current committed state
        self._commit_serial = -1
        for k, v in kwargs.items():
            self._values[k] = v
        self.commit()

    def __getattr__(self, name):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def save(self) -> None:
        self._saved = {k: self._snapshot(v)
                       for k, v in self._values.items()}

    @staticmethod
    def _snapshot(v):
        if isinstance(v, jax.Array):
            return np.asarray(v).copy()
        return copy.deepcopy(v)

    @property
    def commit_serial(self) -> int:
        """Monotone count of commit() calls (0 = never committed past
        construction). Commits are collective in training loops, so
        equal serials across ranks mean equal committed state — what
        redist/elastic.py keys its holder election on."""
        return self._commit_serial

    def commit(self) -> None:
        """Save + sync point (common/elastic.py commit)."""
        self.save()
        self._commit_serial += 1

    def restore(self) -> None:
        """Roll back to the last commit (common/elastic.py restore)."""
        self._values = {k: copy.deepcopy(v) for k, v in self._saved.items()}

    def load_latest(self, target=None) -> bool:
        """Restore the most recent DISK commit, when this state has one.

        Base states are memory-only, so this is False; disk-backed
        states (checkpoint.FileBackedState and its ckpt-plane backend)
        override it. Declared here so the elastic wrapper's
        HOROVOD_CKPT_AUTO_RESTORE path (elastic/run.py) can call it
        uniformly on any state object."""
        return False

    def sync(self, root_rank: int = 0) -> None:
        """Broadcast state from root so all workers agree
        (common/elastic.py sync)."""
        for k, v in list(self._values.items()):
            if isinstance(v, (jax.Array, np.ndarray)) or _is_pytree_of_arrays(v):
                self._values[k] = broadcast_parameters(v, root_rank)
            else:
                self._values[k] = broadcast_object(v, root_rank)
        self.save()


def _is_pytree_of_arrays(v) -> bool:
    leaves = jax.tree_util.tree_leaves(v)
    return bool(leaves) and all(
        isinstance(l, (jax.Array, np.ndarray)) for l in leaves)


class ObjectState(State):
    """Arbitrary picklable attributes (common/elastic.py ObjectState)."""


class TrainState(State):
    """Convenience: params/opt_state/epoch/batch
    (TorchState analog, torch/elastic/state.py:27)."""

    def __init__(self, params=None, opt_state=None, epoch=0, batch=0,
                 **kwargs):
        super().__init__(params=params, opt_state=opt_state, epoch=epoch,
                         batch=batch, **kwargs)
