"""Elastic fault-tolerant training (reference: horovod/common/elastic.py,
horovod/runner/elastic/)."""
from .state import State, ObjectState, TrainState          # noqa: F401
from .run import run, notification_manager                 # noqa: F401
from .sampler import ElasticSampler                        # noqa: F401
from .discovery import (HostDiscovery, HostDiscoveryScript,  # noqa: F401
                        FixedHostDiscovery, HostManager, HostState)
from .driver import ElasticDriver                          # noqa: F401
from .hybrid import (ElasticMeshSpec, GSPMDState,          # noqa: F401
                     MeshResizeError, host_tree)
from ..checkpoint import FileBackedState                   # noqa: F401
