"""Elastic x hybrid parallelism semantics (VERDICT r3 item 9).

The reference's elastic mode is data-parallel only (its worker state is
replicated, horovod/common/elastic.py:60) — but this framework also
ships TP/PP/SP/EP meshes, so a topology change needs defined semantics:

* The MODEL-parallel factorization (tp, sp, pp, ep) is fixed for the
  job's lifetime; elasticity happens in ``dp`` only. Model-axis extents
  encode weight layouts (a tp=4 checkpoint shards attention heads 4
  ways); silently re-factorizing on a resize would train a different
  program.
* On every (re)initialization the mesh is rebuilt from the LIVE device
  set (``ElasticMeshSpec.build``). A world size that no longer fits the
  fixed axes fails fast with :class:`MeshResizeError` naming the
  factorization and the valid resize unit — never a hang, never a
  silently different layout.
* ``GSPMDState`` re-places its registered pytrees on the rebuilt mesh on
  every ``sync`` (reshard-on-restore: same partition rules, new dp
  extent). Cross-job re-factorization (e.g. tp=4 -> tp=2 on fewer
  chips) is the checkpoint path: ``checkpoint.py`` restores to whatever
  target shardings the new job requests.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax

from ..parallel.mesh_utils import make_mesh
from .state import State, _is_pytree_of_arrays


class MeshResizeError(RuntimeError):
    """An elastic reset produced a world size incompatible with the
    job's fixed model-parallel factorization."""


class ElasticMeshSpec:
    """Fixed model-parallel axes; ``dp`` absorbs elasticity.

    ``build()`` reads the live device set and returns a mesh with
    ``dp = n_devices / (tp*sp*pp*ep)``, raising :class:`MeshResizeError`
    when that does not divide — the clean-early-error contract for
    elastic resets under hybrid parallelism.
    """

    def __init__(self, tp: int = 1, sp: int = 1, pp: int = 1,
                 ep: int = 1):
        if min(tp, sp, pp, ep) < 1:
            raise ValueError("axis sizes must be >= 1")
        self.tp, self.sp, self.pp, self.ep = tp, sp, pp, ep

    @property
    def fixed(self) -> int:
        """Devices consumed by the model-parallel axes — the unit the
        cluster must be resized in."""
        return self.tp * self.sp * self.pp * self.ep

    def build(self, devices: Optional[Sequence] = None):
        devs = list(devices) if devices is not None else jax.devices()
        n = len(devs)
        if n < self.fixed or n % self.fixed:
            raise MeshResizeError(
                f"elastic world has {n} device(s), but the fixed "
                f"model-parallel factorization tp={self.tp} sp={self.sp} "
                f"pp={self.pp} ep={self.ep} needs a multiple of "
                f"{self.fixed}. Elastic resizing is data-parallel only: "
                f"scale the cluster in units of {self.fixed} slots, or "
                f"relaunch with a new factorization and restore from "
                f"checkpoint (checkpoint.py reshards on restore).")
        return make_mesh(dp=n // self.fixed, tp=self.tp, sp=self.sp,
                         pp=self.pp, ep=self.ep, devices=devs)

    def __repr__(self) -> str:  # error messages / logs
        return (f"ElasticMeshSpec(tp={self.tp}, sp={self.sp}, "
                f"pp={self.pp}, ep={self.ep})")


class GSPMDState(State):
    """Elastic state for GSPMD-sharded training under a fixed
    model-parallel factorization.

    Tracked values ALWAYS live as full host trees (the base State
    contract — broadcastable, snapshot-able, checkpoint-ready; device
    trees sharded across processes are neither). ``sync`` — the call
    `@hvd.elastic.run` makes at the top of each incarnation — pulls any
    device values back to host (``host_tree``), agrees across workers,
    and rebuilds the mesh from the spec (raising
    :class:`MeshResizeError` on an incompatible world). Place a tracked
    tree on the current mesh with ``placed(key)`` (reshard-on-restore:
    same rules, new dp extent) and push trained device trees back with
    ``update_from_device(params=...)`` before ``commit``.

    ``state.mesh`` is the current incarnation's mesh — build the train
    step from it after ``sync``.
    """

    def __init__(self, mesh_spec: ElasticMeshSpec, rules,
                 sharded: Tuple[str, ...] = ("params",), **kwargs):
        self._spec = mesh_spec
        self._rules = rules
        self._sharded = tuple(sharded)
        self._mesh = None
        super().__init__(**kwargs)

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = self._spec.build()
        return self._mesh

    def sync(self, root_rank: int = 0) -> None:
        # normalize to host trees BEFORE the base sync: broadcast and
        # snapshot must never see cross-process device arrays
        for k in self._sharded:
            v = self._values.get(k)
            if v is not None and _is_pytree_of_arrays(v):
                self._values[k] = host_tree(v)
        super().sync(root_rank)               # agreement + ONE snapshot
        self._mesh = self._spec.build()       # MeshResizeError on misfit

    def placed(self, key: str) -> Any:
        """The tracked host tree under ``key``, placed on the current
        mesh with this state's rules."""
        return self.place(self._values[key])

    def place(self, tree: Any) -> Any:
        """Place an arbitrary pytree on the current mesh with this
        state's rules (e.g. a freshly initialized optimizer state)."""
        from ..parallel.tp import shard_params
        return shard_params(tree, self.mesh, self._rules)

    def update_from_device(self, **trees: Any) -> None:
        """Store trained device trees (possibly cross-process-sharded)
        back as commit-ready host trees."""
        for k, v in trees.items():
            self._values[k] = host_tree(v)


def host_tree(tree: Any) -> Any:
    """Full GLOBAL host copy of a possibly cross-process-sharded pytree
    — what an elastic commit should store. ``jax.device_get`` raises on
    arrays spanning non-addressable devices (tp/pp shards on other
    processes); this gathers them first."""
    import numpy as np

    def pull(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(a, tiled=True))
        return np.asarray(a)

    return jax.tree_util.tree_map(pull, tree)
