"""Host discovery for elastic training.

Re-design of horovod/runner/elastic/discovery.py: a user-supplied executable
prints the current 'host:slots' set; the driver polls it (~1 s). HostState
tracks blacklisting with cooldown + resurrection (discovery.py:35-110) so a
flapping host is retried with exponential backoff rather than permanently
lost.
"""
from __future__ import annotations

import subprocess
import time
from typing import Dict, List, Optional

from ..runner.hosts import HostInfo


def set_blacklist_cooldown_range(lo: float, hi: float) -> None:
    """Configure the blacklist cooldown bounds (reference
    --blacklist-cooldown-range, launch.py:460: the min/max seconds a
    failing host stays blacklisted; the backoff grows exponentially from
    min to max)."""
    if not (0 < lo <= hi):
        raise ValueError(
            f"cooldown range must satisfy 0 < min <= max, got ({lo}, {hi})")
    HostState.COOLDOWN_BASE = float(lo)
    HostState.COOLDOWN_MAX = float(hi)


class HostState:
    """Blacklist with cooldown (discovery.py:33)."""

    COOLDOWN_BASE = 10.0
    COOLDOWN_MAX = 600.0

    def __init__(self):
        self.blacklisted = False
        self.failures = 0
        self._until = 0.0

    def blacklist(self) -> None:
        self.failures += 1
        self.blacklisted = True
        cooldown = min(self.COOLDOWN_BASE * (2 ** (self.failures - 1)),
                       self.COOLDOWN_MAX)
        self._until = time.monotonic() + cooldown

    def maybe_resurrect(self) -> None:
        if self.blacklisted and time.monotonic() >= self._until:
            self.blacklisted = False


class HostDiscovery:
    """Interface (discovery.py HostDiscovery)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Executable printing one 'hostname:slots' (or bare hostname) per line
    (discovery.py HostDiscoveryScript)."""

    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(self.script, shell=True,
                                      timeout=30).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, slots = line.rsplit(":", 1)
                hosts[name] = int(slots)
            else:
                hosts[line] = self.default_slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current + blacklisted hosts (driver-side state)."""

    def __init__(self, discovery: HostDiscovery):
        self.discovery = discovery
        self.states: Dict[str, HostState] = {}

    def current_hosts(self) -> List[HostInfo]:
        found = self.discovery.find_available_hosts_and_slots()
        for name in found:
            self.states.setdefault(name, HostState())
        for st in self.states.values():
            st.maybe_resurrect()
        return [HostInfo(name, slots) for name, slots in found.items()
                if not self.states[name].blacklisted]

    def blacklist(self, hostname: str) -> None:
        self.states.setdefault(hostname, HostState()).blacklist()
