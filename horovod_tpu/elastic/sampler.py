"""ElasticSampler: shard-aware sampling that survives topology changes.

Re-design of horovod/torch/elastic/sampler.py:9 (ElasticSampler): partitions
the dataset indices across workers; `record_batch` tracks processed indices;
after a reset, `set_epoch`/reset re-partitions only the UNPROCESSED samples
across the new worker set so no sample is lost or duplicated within an epoch.
"""
from __future__ import annotations

import random
from typing import Iterator, List, Optional


class ElasticSampler:
    def __init__(self, dataset_size: int, shuffle: bool = True,
                 seed: int = 0, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None):
        from ..core import basics
        self.dataset_size = dataset_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed: set = set()
        if num_replicas is None:
            num_replicas = basics.size() if basics.is_initialized() else 1
        if rank is None:
            rank = 0
        self.num_replicas = num_replicas
        self.rank = rank
        self._reindex()

    # -- epoch / progress --------------------------------------------------
    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        self.processed.clear()
        self._reindex()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        start = batch_idx * batch_size
        chunk = self.indices[start:start + batch_size]
        self.record_indices(chunk)

    def record_indices(self, indices: List[int]) -> None:
        self.processed.update(indices)

    def reset(self, num_replicas: Optional[int] = None,
              rank: Optional[int] = None) -> None:
        """After a topology change: re-partition unprocessed samples."""
        if num_replicas is not None:
            self.num_replicas = num_replicas
        if rank is not None:
            self.rank = rank
        self._reindex()

    # -- internals ---------------------------------------------------------
    def _reindex(self) -> None:
        remaining = [i for i in range(self.dataset_size)
                     if i not in self.processed]
        if self.shuffle:
            rng = random.Random(self.seed + self.epoch)
            rng.shuffle(remaining)
        # pad so every replica sees the same count (drop-none semantics)
        n = self.num_replicas
        if remaining and len(remaining) % n != 0:
            remaining += remaining[: n - len(remaining) % n]
        self.indices = remaining[self.rank::n]

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices)

    def __len__(self) -> int:
        return len(self.indices)
