"""hvd.elastic.run: the fault-tolerant training wrapper.

Re-design of the reference wrapper (horovod/common/elastic.py:151-175
run_fn): loop { state.sync() -> user function } catching
HorovodInternalError (communication failure -> shutdown/reinit + restore)
and HostsUpdatedInterrupt (topology change -> commit-or-abort + reinit).
`reset_limit` bounds resets (runner/elastic/registration.py analog).

On TPU a topology change means the mesh must be rebuilt (XLA programs are
compiled for a fixed device set), so reset = full shutdown + re-init of the
framework — exactly the driver-level restart path SURVEY §7 prescribes.
"""
from __future__ import annotations

import functools
import logging
import os
import time
from typing import Callable, Optional

from ..core import basics
from ..core.types import HorovodInternalError, HostsUpdatedInterrupt
from .state import State

logger = logging.getLogger("horovod_tpu")


def _recovery_metrics():
    """(histogram, last-gauge) for elastic recovery latency — the
    fleet report's last-recovery view (obs/report.py)."""
    from ..obs import metrics as obs_metrics
    from ._base_state import LAST_RECOVERY_MS_HELP, RECOVERY_MS_HELP
    R = obs_metrics.get_registry()
    return (R.histogram("hvd_elastic_recovery_ms", RECOVERY_MS_HELP),
            R.gauge("hvd_elastic_last_recovery_ms",
                    LAST_RECOVERY_MS_HELP))


def run(func: Callable) -> Callable:
    """Decorator: `@hvd.elastic.run def train(state, ...)`."""

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        reset_limit = kwargs.pop("reset_limit", None)
        resets = 0
        restored_from_disk = False
        recovery_t0 = None          # set when a failure is caught
        notification_manager.init()
        while True:
            try:
                if not basics.is_initialized():
                    basics.init()
                # HOROVOD_CKPT_AUTO_RESTORE: resume from committed
                # state before the first sync on this plane. The
                # in-memory path goes first (HOROVOD_REDIST_ELASTIC):
                # a collective probe elects the ranks still holding the
                # current commit and redistributes it over the wire —
                # zero checkpoint reads (redist/elastic.py). Every rank
                # of every incarnation runs the probe at this same
                # point, so survivors re-entering after a reset and
                # fresh joiners entering for the first time meet in the
                # same collective. Only when no rank holds live state
                # (a full process restart) does the disk fallback run —
                # the ckpt backend reshards N->M automatically, so a
                # topology change resumes instead of aborting; disk is
                # tried once per process (in-process resets roll back
                # via the in-memory snapshot below, already current).
                cfg = basics.get_config()
                if cfg.ckpt_auto_restore:
                    restored_mem = False
                    if cfg.redist_elastic:
                        from ..redist.elastic import elastic_restore
                        restored_mem = elastic_restore(state)
                        if restored_mem:
                            logger.info(
                                "elastic: state restored in memory "
                                "over the redistribution plane (no "
                                "checkpoint reads, reset epoch %s)",
                                os.environ.get(
                                    "HOROVOD_CKPT_RESET_EPOCH", "0"))
                    if restored_mem:
                        restored_from_disk = True
                    elif not restored_from_disk:
                        if state.load_latest():
                            logger.info(
                                "elastic: auto-restored state from "
                                "last disk commit (reset epoch %s)",
                                os.environ.get(
                                    "HOROVOD_CKPT_RESET_EPOCH", "0"))
                        # marked done only AFTER the attempt succeeded:
                        # a collective load_latest interrupted by a
                        # comm failure must retry on the next loop, not
                        # fall through to training from initial state
                        restored_from_disk = True
                state.sync()
                if recovery_t0 is not None:
                    # recovered: the state is consistent on the new
                    # plane again — observe failure -> resync latency
                    ms = (time.perf_counter() - recovery_t0) * 1000.0
                    recovery_t0 = None
                    hist, last = _recovery_metrics()
                    hist.observe(ms)
                    last.set(ms)
                    logger.info("elastic: recovered in %.0f ms "
                                "(reset %d)", ms, resets)
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                logger.warning("elastic: internal error, restoring: %s", e)
                recovery_t0 = time.perf_counter()
                _reinitialize()
                state.restore()
                state.on_reset()
            except HostsUpdatedInterrupt as e:
                logger.info("elastic: hosts updated, re-initializing")
                recovery_t0 = time.perf_counter()
                _reinitialize()
                if not e.skip_sync:
                    state.commit()
                state.on_reset()
            resets += 1
            if reset_limit is not None and resets >= reset_limit:
                raise RuntimeError(
                    f"Elastic training reset limit ({reset_limit}) reached")

    return wrapper


def _reinitialize() -> None:
    basics.shutdown()
    basics.init()


class WorkerNotificationManager:
    """Receives host-change notifications (runner/elastic/worker.py:46).

    The driver pings workers when discovery reports a changed host set;
    workers then raise HostsUpdatedInterrupt at the next step boundary via
    `check()`. In-process, the driver calls `handle_hosts_updated`.
    """

    def __init__(self):
        self._pending = False
        self._initialized = False

    def init(self):
        self._initialized = True

    def handle_hosts_updated(self):
        self._pending = True

    def check(self):
        """Call between steps: raises if the host set changed."""
        if self._pending:
            self._pending = False
            raise HostsUpdatedInterrupt()


notification_manager = WorkerNotificationManager()
