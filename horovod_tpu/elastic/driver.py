"""ElasticDriver: fault-tolerant multi-worker orchestration.

Re-design of horovod/runner/elastic/driver.py: a discovery thread polls the
host set (~1 s, driver.py:188); on change or worker failure the driver
recomputes rank assignments PRESERVING surviving ranks (driver.py:240-283),
re-seeds the rendezvous KV, and (re)spawns workers; failed hosts are
blacklisted with cooldown; `min_np`/`max_np` bound the world size;
`reset_limit` bounds the number of reset events.

On TPU each reset restarts worker processes (mesh rebuild requires process
restart — SURVEY §7 'elastic on TPU slices'), so the driver IS the recovery
path; in-process NCCL-style repair does not apply.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..runner import exec as exec_lib
from ..runner.hosts import HostInfo, SlotInfo, get_host_assignments
from ..runner.http_kv import RendezvousServer, make_secret
from ._base_state import LAST_RECOVERY_MS_HELP, RECOVERY_MS_HELP
from .discovery import HostDiscoveryScript, HostManager

logger = logging.getLogger("horovod_tpu")


class ElasticDriver:
    def __init__(self, discovery, command: List[str], min_np: int,
                 max_np: Optional[int] = None, reset_limit: Optional[int] = None,
                 base_env: Optional[dict] = None,
                 poll_interval: float = 1.0,
                 ssh_port: Optional[int] = None,
                 ssh_identity_file: Optional[str] = None,
                 output_dir: Optional[str] = None,
                 elastic_timeout: Optional[float] = None,
                 prefix_timestamp: bool = False):
        self.manager = HostManager(discovery)
        self.command = command
        self.min_np = min_np
        self.max_np = max_np
        self.reset_limit = reset_limit
        # reference --elastic-timeout (launch.py:452, default 600):
        # bound on waiting for min_np hosts after a re-scale
        self.elastic_timeout = elastic_timeout if elastic_timeout \
            is not None else 600.0
        self.base_env = dict(base_env if base_env is not None else os.environ)
        self.poll_interval = poll_interval
        self.ssh_port = ssh_port
        self.ssh_identity_file = ssh_identity_file
        self.output_dir = output_dir
        self.prefix_timestamp = prefix_timestamp
        self.resets = 0
        self._assignments: Dict[str, List[SlotInfo]] = {}
        self._workers: List[exec_lib.WorkerProcess] = []
        self._server: Optional[RendezvousServer] = None
        self._native_server = None      # native.store.StoreServer
        self._secret = make_secret()
        self._stop = threading.Event()
        self._rc = 0
        # -- co-scheduling (autoscale/cosched.py): a requested world
        # size narrows the next slot computation; the supervise loop
        # converts a pending request into an ordinary elastic reset,
        # so survivors elastic-restore in memory (redist/elastic.py)
        # exactly as they would after a host loss.
        self._requested_np: Optional[int] = None
        self._current_np = 0
        self._resize_lock = threading.Lock()
        # -- metrics: membership churn events, scraped off the driver
        # process's registry (HOROVOD_METRICS_PORT works here too)
        R = obs_metrics.get_registry()
        for fam in ("hvd_elastic_resets_total",
                    "hvd_elastic_host_events_total",
                    "hvd_elastic_worker_failures_total",
                    "hvd_elastic_recovery_ms",
                    "hvd_elastic_last_recovery_ms",
                    "hvd_elastic_resize_requests_total"):
            R.unregister(fam)
        self._m_resets = R.counter(
            "hvd_elastic_resets_total",
            "elastic reset rounds (relaunch + rank reassignment)")
        # driver-side recovery latency: failure observed -> replacement
        # workers launched (workers observe their own leg in
        # elastic/run.py under the same family)
        self._m_recovery = R.histogram(
            "hvd_elastic_recovery_ms", RECOVERY_MS_HELP)
        self._m_last_recovery = R.gauge(
            "hvd_elastic_last_recovery_ms", LAST_RECOVERY_MS_HELP)
        self._reset_t0: Optional[float] = None
        self._m_host_events = {
            k: R.counter("hvd_elastic_host_events_total",
                         "hosts joining/leaving the discovered set",
                         {"event": k}) for k in ("join", "leave")}
        self._m_worker_failures = R.counter(
            "hvd_elastic_worker_failures_total",
            "worker exits with non-zero rc (host blacklisted)")
        self._m_resize = {
            k: R.counter("hvd_elastic_resize_requests_total",
                         "co-scheduler resize requests accepted by the "
                         "elastic driver", {"direction": k})
            for k in ("shrink", "grow")}

    # -- co-scheduling resize surface (autoscale/cosched.py lever) ---------
    def current_np(self) -> int:
        """World size of the running incarnation (0 before the first
        launch)."""
        return self._current_np

    def request_resize(self, target_np: int) -> None:
        """Ask for a world of ``target_np`` at the next supervise poll.

        Clamped into [min_np, max_np]; a no-op request (already at the
        target) clears any pending one.  The actual resize is an
        ordinary elastic reset: workers are torn down and relaunched
        at the new size, and the survivors restore training state IN
        MEMORY through ``redist.elastic_restore`` — no checkpoint
        reads."""
        target = max(int(target_np), self.min_np)
        if self.max_np is not None:
            target = min(target, self.max_np)
        with self._resize_lock:
            cur = self._current_np
            self._requested_np = target
            if target != cur and cur > 0:
                self._m_resize["shrink" if target < cur
                               else "grow"].inc()
        logger.info("elastic: resize requested np=%d (current %d)",
                    target, cur)

    # -- host assignment (driver.py:240 _update_host_assignments) ----------
    def _compute_slots(self, hosts: List[HostInfo],
                       previous: Optional[List[SlotInfo]]) -> List[SlotInfo]:
        np_ = sum(h.slots for h in hosts)
        if self.max_np is not None:
            np_ = min(np_, self.max_np)
        with self._resize_lock:
            req = self._requested_np
        if req is not None:
            # co-scheduler shrink: use fewer slots than discovered
            # (growth stays bounded by what discovery actually offers)
            np_ = min(np_, max(req, self.min_np))
        if np_ < self.min_np:
            raise RuntimeError(
                f"Only {np_} slots available, below min_np={self.min_np}")
        self._current_np = np_
        # order hosts so surviving ones keep their rank blocks
        if previous:
            prev_order = []
            for s in previous:
                if s.hostname not in prev_order:
                    prev_order.append(s.hostname)
            cur = {h.hostname: h for h in hosts}
            ordered = [cur[n] for n in prev_order if n in cur]
            ordered += [h for h in hosts if h.hostname not in prev_order]
        else:
            ordered = hosts
        return get_host_assignments(ordered, np_)

    # -- lifecycle ----------------------------------------------------------
    def run(self) -> int:
        self._server = RendezvousServer(secret=self._secret)
        port = self._server.start()
        slots = None
        try:
            while not self._stop.is_set():
                hosts = self._wait_for_min_hosts()
                slots = self._compute_slots(hosts, slots)
                self._server.init(slots)
                self._launch(slots, port)
                if self._reset_t0 is not None:
                    # driver-side recovery leg: failure observed ->
                    # replacement incarnation launched
                    ms = (time.monotonic() - self._reset_t0) * 1000.0
                    self._reset_t0 = None
                    self._m_recovery.observe(ms)
                    self._m_last_recovery.set(ms)
                    logger.info("elastic: relaunched %d workers %.0f ms "
                                "after the failure (reset %d)",
                                len(self._workers), ms, self.resets)
                outcome = self._supervise(slots)
                if outcome == "done":
                    return self._rc
                self._reset_t0 = time.monotonic()
                self.resets += 1
                self._m_resets.inc()
                if self.reset_limit is not None and \
                        self.resets > self.reset_limit:
                    raise RuntimeError(
                        f"reset_limit ({self.reset_limit}) exceeded")
        finally:
            self._terminate_workers()
            self._server.stop()
            if self._native_server is not None:
                self._native_server.close()
                self._native_server = None
        return self._rc

    def stop(self) -> None:
        self._stop.set()

    def _wait_for_min_hosts(self) -> List[HostInfo]:
        deadline = time.monotonic() + self.elastic_timeout
        while True:
            hosts = self.manager.current_hosts()
            if sum(h.slots for h in hosts) >= self.min_np:
                return hosts
            if self._stop.is_set():
                raise RuntimeError("driver stopped while waiting for hosts")
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"elastic timeout: fewer than min_np={self.min_np} "
                    f"slots available after {self.elastic_timeout}s "
                    "(reference --elastic-timeout semantics)")
            time.sleep(self.poll_interval)

    def _launch(self, slots: List[SlotInfo], kv_port: int) -> None:
        coord = f"127.0.0.1:{_free_port()}"
        # Fresh shm-generation token per launch round so a restarted
        # incarnation can never attach a dead round's stale segment
        # (native/shm.py staleness check).
        from ..native.shm import fresh_shm_gen
        env = dict(self.base_env)
        env["HOROVOD_SHM_GEN"] = fresh_shm_gen()
        # Native control-plane store, ONE per launch round (the static
        # launcher's run_static analog): workers connect their
        # Coordinator / p2p rendezvous / ckpt plane / heartbeat
        # detector to it. Fresh per round — a dead incarnation's tag
        # state and heartbeat keys can never leak into the next one.
        if self._native_server is not None:
            self._native_server.close()
            self._native_server = None
        try:
            from ..native.store import StoreServer
            hostnames = {s.hostname for s in slots}
            kv_addr = "127.0.0.1" if hostnames <= {"localhost"} \
                else os.uname().nodename
            self._native_server = StoreServer()
            env["HOROVOD_NATIVE_KV_ADDR"] = kv_addr
            env["HOROVOD_NATIVE_KV_PORT"] = str(self._native_server.port)
        except Exception:  # noqa: BLE001 — toolchain-less host: the
            self._native_server = None   # Python rendezvous KV only
        # Relaunched workers can tell a post-reset incarnation from the
        # initial launch (epoch 0): the ckpt auto-restore path logs it,
        # chaos plans pin epoch-addressed faults to one incarnation,
        # and user code can key recovery behavior off it.
        env["HOROVOD_CKPT_RESET_EPOCH"] = str(self.resets)
        # Workers know they run under the elastic driver (reference
        # operations.cc:501 HOROVOD_ELASTIC): the failure detector uses
        # this to escalate suspicions by exiting, which this driver
        # converts into a reset at the next poll.
        env["HOROVOD_ELASTIC"] = "1"
        self._workers = exec_lib.launch_slots(
            slots, self.command, coord, kv_port, self._secret, env,
            ssh_port=self.ssh_port,
            ssh_identity_file=self.ssh_identity_file,
            output_dir=self.output_dir,
            prefix_timestamp=self.prefix_timestamp)

    def _supervise(self, slots: List[SlotInfo]) -> str:
        """Watch workers + host set. Returns 'done' or 'reset'."""
        from ..chaos.detector import ESCALATE_EXIT_CODE
        known = {h.hostname: h.slots for h in self.manager.current_hosts()}
        while True:
            # worker exits (driver.py:304 _handle_worker_exit)
            all_done = True
            failed = False
            for w in self._workers:
                rc = w.proc.poll()
                if rc is None:
                    all_done = False
                elif rc == ESCALATE_EXIT_CODE:
                    # the failure detector escalated: this worker is the
                    # MESSENGER, not the failure — its host is healthy
                    # and must NOT be blacklisted (the dead peer's own
                    # exit, observed in this same sweep, is what
                    # blacklists the failed host)
                    logger.warning(
                        "elastic: worker rank %d on %s reported a dead "
                        "peer (detector escalation, rc=%d); resetting "
                        "without blacklisting its host",
                        w.slot.rank, w.slot.hostname, rc)
                    failed = True
                elif rc != 0:
                    logger.warning(
                        "elastic: worker rank %d on %s failed (rc=%d); "
                        "blacklisting host and resetting",
                        w.slot.rank, w.slot.hostname, rc)
                    self._m_worker_failures.inc()
                    self._m_host_events["leave"].inc()
                    self.manager.blacklist(w.slot.hostname)
                    failed = True
            if failed:
                self._terminate_workers()
                return "reset"
            if all_done:
                self._rc = 0
                return "done"
            # discovery poll (driver.py:188 _discover_hosts)
            now = {h.hostname: h.slots
                   for h in self.manager.current_hosts()}
            if now != known:
                logger.info("elastic: host set changed %s -> %s; resetting",
                            known, now)
                joined = len(set(now) - set(known))
                left = len(set(known) - set(now))
                if joined:
                    self._m_host_events["join"].inc(joined)
                if left:
                    self._m_host_events["leave"].inc(left)
                self._terminate_workers()
                return "reset"
            # co-scheduler resize poll: a pending request that changes
            # the ACHIEVABLE world size (bounded by the discovered
            # slots, so an unmeetable grow does not reset-loop) is an
            # ordinary elastic reset at the new size
            with self._resize_lock:
                req = self._requested_np
            if req is not None:
                avail = sum(now.values())
                if self.max_np is not None:
                    avail = min(avail, self.max_np)
                achievable = max(min(req, avail), self.min_np)
                if achievable != self._current_np:
                    logger.info(
                        "elastic: resize %d -> %d (requested %d); "
                        "resetting", self._current_np, achievable, req)
                    self._terminate_workers()
                    return "reset"
            time.sleep(self.poll_interval)

    def _terminate_workers(self) -> None:
        for w in self._workers:
            w.terminate()
        for w in self._workers:
            try:
                w.proc.wait(timeout=10)
            except Exception:
                pass
        self._workers = []


def run_elastic(args) -> int:
    """Entry from the hvdrun CLI (launch.py)."""
    if not args.host_discovery_script:
        raise SystemExit(
            "elastic mode requires --host-discovery-script")
    from ..runner.launch import env_from_args
    base_env = dict(os.environ)
    base_env.update(env_from_args(args))
    cooldown = getattr(args, "blacklist_cooldown_range", None)
    if cooldown:
        from .discovery import set_blacklist_cooldown_range
        set_blacklist_cooldown_range(cooldown[0], cooldown[1])
    discovery = HostDiscoveryScript(
        args.host_discovery_script,
        default_slots=getattr(args, "slots", None) or 1)
    # HOROVOD_ELASTIC_POLL_INTERVAL_S: discovery/worker poll period.
    # The chaos soak harness raises it so surviving workers get a full
    # detection window (name the dead rank, log, escalate) before the
    # driver's reset tears them down.
    from ..core.config import (ELASTIC_POLL_INTERVAL_S_DEFAULT,
                               _env_float_strict)
    # knob: exempt (driver-process launcher leg — the knob is declared
    # + validated in core/config.py; workers inherit it via the env)
    poll_interval = _env_float_strict("HOROVOD_ELASTIC_POLL_INTERVAL_S",
                                      ELASTIC_POLL_INTERVAL_S_DEFAULT)
    driver = ElasticDriver(
        discovery, args.command,
        min_np=args.min_np or 1, max_np=args.max_np,
        poll_interval=poll_interval,
        reset_limit=getattr(args, "reset_limit", None),
        base_env=base_env,
        ssh_port=getattr(args, "ssh_port", None),
        ssh_identity_file=getattr(args, "ssh_identity_file", None),
        output_dir=getattr(args, "output_filename", None),
        elastic_timeout=getattr(args, "elastic_timeout", None),
        prefix_timestamp=bool(getattr(args, "prefix_timestamp", None)))
    return driver.run()


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]
