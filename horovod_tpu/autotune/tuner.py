"""ParameterManager: runtime autotuning of fusion/cycle knobs.

Re-design of horovod/common/parameter_manager.{cc,h}: when HOROVOD_AUTOTUNE=1
the engine reports (bytes, seconds) per scoring window; the manager samples
candidate (fusion_threshold, cycle_time) settings via Bayesian optimization
maximizing bytes/sec (parameter_manager.h:33-41), discards warmup samples,
and after `max_samples` pins the best configuration. Sampled scores go to a
CSV log when HOROVOD_AUTOTUNE_LOG is set (operations.cc:630-637).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from .bayes import BayesianOptimizer

# knob domains: fusion threshold 0..128 MB, cycle time 1..25 ms — the
# reference's tunable ranges (parameter_manager.cc defaults) — plus
# categorical dimensions matching the reference's categorical knobs
# (parameter_manager.h:59-84): the two-level (hierarchical/torus)
# allreduce toggle (hier and torus share one code path, ops/cross.py),
# the int8 wire-format compression toggle (ops/engine.py fused wire
# path), and the per-regime collective-algorithm choices (ops/algo.py):
# one algorithm for latency-bound small buckets, one for bandwidth-bound
# large buckets, split at the alpha-beta crossover — the tuner learns
# the crossover behavior per deployment instead of the static model
# guessing it.
FUSION_MB_RANGE = (0.0, 128.0)
CYCLE_MS_RANGE = (1.0, 25.0)
TWO_LEVEL_RANGE = (0.0, 1.0)
COMPRESSION_RANGE = (0.0, 1.0)

#: default algorithm vocabulary for the per-regime categorical dims; the
#: engine narrows it to what the deployment can run (rhd needs a
#: power-of-two world, two_level a real hierarchy)
DEFAULT_ALGO_CHOICES = ("direct", "rs_ag", "rhd", "two_level")


class ParameterManager:
    def __init__(self, warmup_samples: int = 3, steps_per_sample: int = 10,
                 max_samples: int = 20, log_path: Optional[str] = None,
                 seed: int = 0, tune_two_level: bool = True,
                 gp_noise: Optional[float] = None,
                 tune_compression: bool = False,
                 tune_algo: bool = False,
                 algo_choices: Sequence[str] = DEFAULT_ALGO_CHOICES,
                 clock: Callable[[], float] = time.monotonic):
        #: tune_two_level=False freezes the categorical dim (e.g. when
        #: HOROVOD_TORUS_ALLREDUCE already forces the two-level path and
        #: the knob would be behaviorally inert); tune_compression=False
        #: likewise freezes the wire format (an explicit
        #: HOROVOD_COMPRESSION setting must stand); tune_algo adds TWO
        #: categorical dims — the small-bucket and large-bucket
        #: collective algorithm — frozen when HOROVOD_COLLECTIVE_ALGO is
        #: explicit. The algo dims may be conditionally inert: a sample
        #: whose compression dim lands on int8 rides the gather-based
        #: quantized transport regardless of algo values. That is sound
        #: — the GP scores whole CONFIGURATIONS (the compression dim is
        #: part of x, so the flat direction is conditioned on it) and
        #: the pin picks the best measured config either way — it just
        #: costs some sample efficiency, the same trade the reference
        #: makes tuning hierarchical x cycle-time jointly. `clock` is
        #: the timing source for scoring windows; injectable so a
        #: synthetic (bytes, seconds) trace replays byte-identically
        #: (the deterministic-tuner regression).
        self.algo_choices = tuple(algo_choices)
        if tune_algo and len(self.algo_choices) < 2:
            tune_algo = False             # nothing to choose between
        self.tune_two_level = tune_two_level
        self.tune_compression = tune_compression
        self.tune_algo = tune_algo
        self._clock = clock
        dims = [FUSION_MB_RANGE, CYCLE_MS_RANGE]
        self._two_level_idx = self._compression_idx = None
        self._algo_small_idx = self._algo_large_idx = None
        if tune_two_level:
            self._two_level_idx = len(dims)
            dims.append(TWO_LEVEL_RANGE)
        if tune_compression:
            self._compression_idx = len(dims)
            dims.append(COMPRESSION_RANGE)
        if tune_algo:
            algo_range = (0.0, float(len(self.algo_choices) - 1))
            self._algo_small_idx = len(dims)
            dims.append(algo_range)
            self._algo_large_idx = len(dims)
            dims.append(algo_range)
        self._cat_dims = tuple(
            i for i in (self._two_level_idx, self._compression_idx,
                        self._algo_small_idx, self._algo_large_idx)
            if i is not None)
        self.opt = BayesianOptimizer(dims, seed=seed, noise=gp_noise,
                                     int_dims=self._cat_dims)
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.max_samples = max_samples
        self.log_path = log_path
        self.active = True
        self.samples_taken = 0
        self._steps = 0
        self._bytes = 0.0
        self._t0 = self._clock()
        # categorical dims all start at choice 0 ("off" / "direct")
        self._current = np.array([64.0, 1.0] + [0.0] * (len(dims) - 2))
        self._log_header_written = False

    # -- current knob values ------------------------------------------------
    @property
    def fusion_threshold_bytes(self) -> int:
        return int(self._current[0] * 1024 * 1024)

    @property
    def cycle_time_ms(self) -> float:
        return float(self._current[1])

    @property
    def two_level_allreduce(self) -> bool:
        """Hierarchical/torus two-level allreduce toggle (ops/cross.py)."""
        if self._two_level_idx is None:
            return False
        return bool(self._current[self._two_level_idx])

    @property
    def compression_wire(self) -> str:
        """Sampled wire format for the engine's fused collectives:
        "int8" when the compression dim is on, else "none"."""
        if self._compression_idx is None:
            return "none"
        return "int8" if self._current[self._compression_idx] else "none"

    def _algo_at(self, idx: Optional[int]) -> str:
        if idx is None:
            return ""
        k = int(round(self._current[idx]))
        return self.algo_choices[min(max(k, 0), len(self.algo_choices) - 1)]

    @property
    def algo_small(self) -> str:
        """Sampled allreduce algorithm for latency-bound small buckets
        (below the crossover threshold, ops/algo.py); "" when frozen."""
        return self._algo_at(self._algo_small_idx)

    @property
    def algo_large(self) -> str:
        """Sampled allreduce algorithm for bandwidth-bound large
        buckets; "" when frozen."""
        return self._algo_at(self._algo_large_idx)

    # -- scoring (parameter_manager Update analog) ---------------------------
    def record(self, nbytes: int) -> bool:
        """Report one engine cycle's traffic; returns True when knob values
        changed (caller should re-read the properties)."""
        if not self.active:
            return False
        self._bytes += nbytes
        self._steps += 1
        if self._steps < self.steps_per_sample:
            return False
        elapsed = max(self._clock() - self._t0, 1e-9)
        score = self._bytes / elapsed          # bytes/sec
        self._finish_sample(score)
        return True

    def _finish_sample(self, score: float) -> None:
        self.samples_taken += 1
        if self.samples_taken > self.warmup_samples:
            self.opt.tell(self._current, score)
            self._log(score)
        if self.samples_taken >= self.max_samples + self.warmup_samples \
                and self.opt.ys:
            best, best_score = self.opt.best()
            self._current = self._snap(best)
            self.active = False
            self._log(best_score, final=True)
        else:
            self._current = self._snap(self.opt.suggest())
        self._steps = 0
        self._bytes = 0.0
        self._t0 = self._clock()

    def _snap(self, x: np.ndarray) -> np.ndarray:
        """Round categorical dims so the executed config (and the x later
        told to the GP) matches what was measured — the GP must not
        attribute a measurement of round(0.45)=0 to the point 0.45.
        (BayesianOptimizer.int_dims already snaps suggestions; this is
        the belt-and-braces pass for values from best()/callers.)"""
        x = np.asarray(x, float).copy()
        for idx in self._cat_dims:
            x[idx] = float(round(x[idx]))
        return x

    def _log(self, score: float, final: bool = False) -> None:
        if not self.log_path:
            return
        with open(self.log_path, "a") as f:
            if not self._log_header_written:
                f.write("fusion_mb,cycle_ms,two_level,compression,"
                        "algo_small,algo_large,bytes_per_sec,final\n")
                self._log_header_written = True
            f.write(f"{self._current[0]:.2f},{self._current[1]:.2f},"
                    f"{int(self.two_level_allreduce)},"
                    f"{self.compression_wire},"
                    f"{self.algo_small or '-'},{self.algo_large or '-'},"
                    f"{score:.1f},{int(final)}\n")
