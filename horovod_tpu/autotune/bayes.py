"""Bayesian optimization: Gaussian-process regression + expected improvement.

Re-design of the reference's autotuning math
(horovod/common/optim/gaussian_process.{cc,h} and
bayesian_optimization.{cc,h}): a numpy GP with RBF kernel fit by jittered
Cholesky, EI acquisition maximized by random candidate search (the reference
uses vendored L-BFGS; random search over the small 2-4 dim knob space is
equally effective and dependency-free).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """GP regression with RBF kernel (gaussian_process.cc analog)."""

    def __init__(self, length_scale: float = 1.0, sigma_f: float = 1.0,
                 sigma_n: float = 1e-4):
        self.length_scale = length_scale
        self.sigma_f = sigma_f
        self.sigma_n = sigma_n
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._alpha = None
        self._L = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.sigma_f ** 2 * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        K = self._kernel(x, x) + self.sigma_n ** 2 * np.eye(len(x))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))
        self._x, self._y = x, y

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        Ks = self._kernel(x, self._x)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(self.sigma_f ** 2 - (v ** 2).sum(0), 1e-12, None)
        return mu, np.sqrt(var)


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (bayesian_optimization.cc analog)."""
    from math import erf, sqrt
    z = (mu - best - xi) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
    pdf = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
    return (mu - best - xi) * cdf + sigma * pdf


class BayesianOptimizer:
    """Sequential maximizer over a box domain."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 seed: int = 0, n_candidates: int = 512,
                 noise: Optional[float] = None,
                 int_dims: Sequence[int] = ()):
        self.bounds = np.asarray(bounds, np.float64)
        self.rng = np.random.RandomState(seed)
        self.n_candidates = n_candidates
        # `noise` is the reference's [0, 1] sample-noise regularization
        # (HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
        # bayesian_optimization.cc): the GP's observation sigma
        self.gp = GaussianProcess(
            length_scale=0.3,
            sigma_n=1e-4 if noise is None else float(noise))
        # integer/categorical dimensions: candidates are SNAPPED to the
        # integer lattice before EI evaluation, so the acquisition is
        # computed on realizable points and the GP never has to
        # attribute a measurement of round(0.45)=0 to the point 0.45
        # (the ParameterManager's categorical knobs — two-level, wire
        # format, per-regime collective algorithms — all ride this)
        self.int_dims = tuple(int_dims)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []

    def _norm(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (x - lo) / np.maximum(hi - lo, 1e-12)

    def _denorm(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + u * (hi - lo)

    def _snap_int(self, x: np.ndarray) -> np.ndarray:
        """Round integer dims (denormed space), clipped to bounds."""
        if not self.int_dims:
            return x
        x = np.array(x, np.float64, copy=True)
        for i in self.int_dims:
            x[..., i] = np.clip(np.round(x[..., i]),
                                self.bounds[i, 0], self.bounds[i, 1])
        return x

    def tell(self, x: np.ndarray, y: float) -> None:
        self.xs.append(self._norm(np.asarray(x, np.float64)))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def suggest(self) -> np.ndarray:
        if len(self.xs) < 3:          # bootstrap: random exploration
            u = self.rng.rand(len(self.bounds))
            return self._snap_int(self._denorm(u))
        cand = self._snap_int(
            self._denorm(self.rng.rand(self.n_candidates,
                                       len(self.bounds))))
        mu, sigma = self.gp.predict(self._norm(cand))
        ei = expected_improvement(mu, sigma, max(self.ys))
        return cand[int(np.argmax(ei))]

    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self.ys))
        return self._denorm(self.xs[i]), self.ys[i]
