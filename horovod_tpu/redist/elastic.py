"""The elastic consumer: in-memory state restore after a reset.

Before this plane existed, every elastic (re)entry that wanted its
state back round-tripped through the last committed checkpoint on a
shared filesystem — even when surviving processes still held the exact
committed tree in memory. :func:`elastic_restore` replaces that default
with a collective three-step:

1. **probe** — one coordinator allgather of each rank's
   ``state.commit_serial`` (the liveness token elastic states carry).
   Ranks at the fleet-max serial are *holders* of the current committed
   state; ranks below it (fresh joiners, or survivors that lost their
   snapshot) are receivers.
2. **redistribute** — if any holder exists, receivers get the state
   over the wire (``redistribute(..., Spec.full(holders) ->
   Spec.full(world))``): the p2p ring when the launcher exported a KV
   rendezvous, the coordinator allgather otherwise. Holders move ZERO
   bytes for their own blocks; when every rank is already a current
   holder the whole call is a no-op probe. No checkpoint file is read
   on this path — the np4 acceptance test asserts the
   ``hvd_ckpt_bytes_total{kind="read"}`` counter stays flat across it.
3. **agree** — one coordinator bit-AND round decides success
   COLLECTIVELY: a transport fault on any rank (chaos site
   ``redist.transport``) sends EVERY rank down the ckpt auto-restore
   fallback together — ranks can never split between the in-memory and
   disk paths.

Returns False (try disk) when there is no coordinator, no holder, or
the collective vote failed; the caller (elastic/run.py) then runs the
unchanged ``state.load_latest()`` fallback.

Failure semantics: TRANSPORT faults are caught, rolled back and voted
on (the whole fleet falls back together). A failure of the probe
allgather or the vote itself — the control plane — is deliberately NOT
caught: swallowing it locally would split the collective call sequence
(peers proceed into exchanges this rank never joins), so it propagates
like every other coordinator failure in this codebase
(``load_latest`` has the identical exposure) and the elastic driver
converts the worker exit into a clean reset.
"""
from __future__ import annotations

import logging
import os
import pickle
import struct
from typing import Optional

from .core import redistribute
from .plan import RedistError, Spec
from .transport import CoordTransport, RingTransport, _kv_endpoint

logger = logging.getLogger("horovod_tpu")

#: per-process attempt counter; the fleet round id is the MAX across
#: ranks so survivors (counter ahead) and fresh joiners (counter 0)
#: still derive one shared id for ring prefixes and tags
_attempts = 0

_PROBE = struct.Struct("<qqB")


def _values_dict(state):
    """The state's named values, or None for state types the in-memory
    plane does not cover. Framework states
    (elastic/_base_state.py BaseFrameworkState: torch/keras/tf) keep
    their REAL weights in ``_save_payload()``, not in ``_extras`` —
    moving only the extras and claiming success would let a later
    sync() broadcast a fresh joiner's reinitialized weights over the
    fleet's committed ones. They fall back to the disk path until the
    payload hook grows a redistribution surface."""
    d = getattr(state, "_values", None)
    return d if isinstance(d, dict) else None


def elastic_restore(state, *, coord=None, transport=None,
                    timeout: float = 300.0) -> bool:
    """Collectively restore ``state`` in memory from surviving holders.

    Every rank of the current plane must call this at the same point
    (elastic/run.py does, once per wrapper-loop entry). Returns True
    when the state is current on every rank afterwards (the disk
    fallback must be skipped), False when the caller should fall back
    to ``state.load_latest()``.
    """
    global _attempts
    if coord is None:
        from ..core import basics
        coord = basics.get_coordinator() if basics.is_initialized() \
            else None
    if coord is None or coord.size <= 1:
        return False
    if _values_dict(state) is None:
        # uniform across ranks (one state type per fleet), so skipping
        # BEFORE the probe keeps the collective call sequence intact
        logger.debug(
            "elastic: %s keeps its weights outside _values — "
            "in-memory redistribution skipped, disk path decides",
            type(state).__name__)
        return False
    _attempts += 1
    epoch = int(os.environ.get("HOROVOD_CKPT_RESET_EPOCH", "0"))
    serial = int(getattr(state, "commit_serial", 0))
    has = serial > 0
    blobs = coord.allgather(
        _PROBE.pack(serial, _attempts, 1 if has else 0),
        tag=f"redist.probe.e{epoch}")
    if len(blobs) != coord.size or any(len(b) != _PROBE.size
                                       for b in blobs):
        raise RedistError(
            f"elastic redistribution probe returned {len(blobs)} "
            f"malformed blob(s) for world {coord.size}")
    probes = [_PROBE.unpack(b) for b in blobs]
    rid = max(p[1] for p in probes)
    _attempts = max(_attempts, rid)
    held = [p[0] for p in probes if p[2]]
    if not held:
        return False                      # nobody survived: disk path
    max_serial = max(held)
    holders = tuple(r for r, p in enumerate(probes)
                    if p[2] and p[0] == max_serial)
    if len(holders) == coord.size:
        # every rank already holds the current commit — nothing moves,
        # nothing is read; the probe round IS the restore
        return True
    logger.info(
        "elastic: redistributing committed state (serial %d) from "
        "holders %s to %d rank(s) in memory", max_serial, list(holders),
        coord.size - len(holders))
    values = _values_dict(state)
    owns_transport = False
    ok = True
    mutated = False
    try:
        if transport is None:
            if _kv_endpoint() is not None:
                transport = RingTransport.connect(
                    coord.rank, coord.size,
                    prefix=f"redist.e{epoch}.r{rid}",
                    timeout=timeout, epoch=rid)
            else:
                transport = CoordTransport(coord)
            owns_transport = True
        src = Spec.full(coord.size, holders=holders)
        dst = Spec.full(coord.size)
        from ..elastic.state import _is_pytree_of_arrays
        for k in sorted(values):
            v = values[k]
            if _is_pytree_of_arrays(v):
                moved = redistribute(
                    v, src, dst, transport,
                    tag=f"redist.e{epoch}.r{rid}.{k}")
                mutated = True
                values[k] = moved
            else:
                # small python leaves (epoch/batch counters, tags) ride
                # the control plane whole, pickled from the first holder
                blob = pickle.dumps(v) if coord.rank == holders[0] \
                    else None
                out = coord.broadcast(
                    blob, root=holders[0],
                    tag=f"redist.obj.e{epoch}.r{rid}.{k}")
                mutated = True
                values[k] = pickle.loads(out)
    except Exception as e:  # noqa: BLE001 — vote, then fall back as one
        logger.warning(
            "elastic: in-memory redistribution failed on rank %d "
            "(%s); voting for the checkpoint fallback", coord.rank, e)
        ok = False
        if mutated:
            # a failure mid-loop left a TORN mix (some values at the
            # holders' commit, others stale): roll back to the
            # pre-attempt snapshot so a memory-only state that later
            # syncs from this rank never propagates the mix
            try:
                state.restore()
            except Exception:  # noqa: BLE001 — fallback still decides
                logger.warning(
                    "elastic: post-failure rollback failed on rank %d",
                    coord.rank)
    finally:
        if owns_transport and transport is not None:
            transport.close()
    bits = coord.bitand(bytes([1 if ok else 0]),
                        tag=f"redist.ok.e{epoch}")
    if not bits[0]:
        return False
    # adopt the holders' serial so the NEXT reset counts this rank as
    # a holder too, then refresh the rollback snapshot: restore() after
    # this point must reproduce the redistributed state
    state._commit_serial = max_serial
    state.save()
    return True
