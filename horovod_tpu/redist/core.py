"""``redistribute()`` — move a pytree from one layout/world to another.

The orchestrator that executes a redistribution plan (redist/plan.py)
over an interchangeable transport (redist/transport.py):

1. flatten the local tree (receivers pass their *template* tree — same
   shapes/dtypes, stale contents) and derive the leaf table;
2. compute the pure global plan; ops whose source is this rank and
   whose target is this rank are satisfied by local slicing, never
   touching the wire;
3. execute the wire ops in bounded rounds (``schedule_rounds`` caps
   per-rank send AND receive bytes per round at
   ``HOROVOD_REDIST_CHUNK_BYTES``), each round one transport exchange;
   every frame carries a crc32 verified on receipt;
4. assemble the destination layout and unflatten with the local
   treedef.

``src == dst`` is a true no-copy identity: the input tree object is
returned untouched (no flatten, no exchange). A ``kind == "disk"``
transport (CkptTransport) routes through a sharded-checkpoint
save + reshard-restore round trip instead — same call site, different
data plane, which is what lets elastic fall back from the ring to disk
without a second code path.
"""
from __future__ import annotations

import json
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .plan import (RedistError, Spec, op_nbytes, plan_redistribute,
                   row_bounds, schedule_rounds)

#: per-frame wire header: leaf u32, flags u32, lo i64, hi i64,
#: nbytes i64, crc32 u32 — followed by exactly nbytes of payload
_FRAME = struct.Struct("<IIqqqI")
#: per-destination payload header: magic, plan crc32, frame count
_HDR = struct.Struct("<4sII")
_MAGIC = b"RDX1"
_F_PYOBJ = 1      # payload is a pickled python leaf
_F_WHOLE = 2      # payload is a whole (replicated / 0-d) array leaf

#: measured sweet spot on the CPU container (bench.py --redist / the
#: /tmp chunk sweep behind it): 16MB rounds pipeline frame building
#: against the ring relay ~2x better than one monolithic round, and
#: bound per-rank staging memory tighter
DEFAULT_CHUNK_BYTES = 16 * 1024 * 1024

#: single-sourced help strings (the WIRE_BYTES_HELP discipline): the
#: registry keeps whichever help registers first, so every site —
#: core wire path, disk path, weight stream — must share one literal
REDIST_BYTES_HELP = "redistribution bytes sent over the transport"
REDIST_MS_HELP = "one redistribute() call, plan -> assembled tree"


def _chunk_bytes(override: Optional[int]) -> int:
    if override is not None:
        return int(override)
    try:
        from ..core import basics
        if basics.is_initialized():
            return basics.get_config().redist_chunk_bytes
    except Exception:  # noqa: BLE001 — config must never block a move
        pass
    import os
    # knob: exempt (jax-free standalone fallback — tools/weights_push.py
    # runs this module with no initialized plane; the live path above
    # reads the round-synchronized Config)
    v = os.environ.get("HOROVOD_REDIST_CHUNK_BYTES")
    return int(v) if v else DEFAULT_CHUNK_BYTES


def _obs(transport_name: str):
    """Lazy redist metric handles (shared process registry)."""
    from ..obs import metrics as m
    R = m.get_registry()
    return (R.counter("hvd_redist_bytes_total", REDIST_BYTES_HELP,
                      {"transport": transport_name}),
            R.histogram("hvd_redist_ms", REDIST_MS_HELP))


def _timeline_instant(args: dict) -> None:
    """One REDIST row on the live timeline (no-op without one)."""
    try:
        from ..core import basics
        tl = basics.get_state().timeline
        if tl is not None:
            tl.instant("REDIST", args)
    except Exception:  # noqa: BLE001
        pass


def _is_identity(src: Spec, dst: Spec) -> bool:
    """src == dst with every rank holding its data already — the
    degenerate N==M fast path the caller gets back object-identical."""
    if src.layout != dst.layout or src.world != dst.world:
        return False
    if src.layout == "full":
        return src.holder_list() == dst.holder_list() \
            and len(src.holder_list()) == src.world
    return True


def _plan_crc(entries: List[dict], src: Spec, dst: Spec,
              chunk: int) -> int:
    """Fingerprint of everything the round schedule derives from —
    leaf table, specs, AND the chunk size (a per-host
    HOROVOD_REDIST_CHUNK_BYTES drift would otherwise produce diverging
    round schedules that surface as phantom corruption or a ring
    timeout instead of this clean refusal). pyobj VALUES are excluded —
    receivers hold stale template values by design; only the tree's
    shape (paths/dtypes/shapes/partitions) must agree."""
    canon = [{k: e.get(k) for k in
              ("path", "kind", "dtype", "shape", "partition")}
             for e in entries]
    blob = json.dumps(
        [canon, src.world, src.layout, src.holder_list(),
         dst.world, dst.layout, int(chunk)], sort_keys=True).encode()
    return zlib.crc32(blob)


def _src_base(entry: dict, src: Spec, rank: int) -> int:
    """Global row index of this source rank's first local row."""
    if src.layout == "full":
        return 0
    return row_bounds(entry["shape"][0], src.world)[rank]


def _frame(entries: List[dict], leaves_np: List[Any], src: Spec,
           rank: int, op: dict) -> bytes:
    """Serialize one op's payload from the local leaves."""
    i = op["leaf"]
    e = entries[i]
    if op.get("pyobj"):
        import pickle
        raw = pickle.dumps(leaves_np[i])
        flags = _F_PYOBJ
        lo = hi = 0
    elif op["rows"] is None:
        raw = np.ascontiguousarray(leaves_np[i]).tobytes()
        flags = _F_WHOLE
        lo = hi = 0
    else:
        lo, hi = op["rows"]
        base = _src_base(e, src, rank)
        arr = leaves_np[i][lo - base:hi - base]
        if arr.shape[0] != hi - lo:
            raise RedistError(
                f"local leaf {i} ({e['path']!r}) holds rows "
                f"[{base}, {base + leaves_np[i].shape[0]}) but the plan "
                f"asked this rank for [{lo}, {hi})")
        raw = np.ascontiguousarray(arr).tobytes()
        flags = 0
    return _FRAME.pack(i, flags, lo, hi, len(raw),
                       zlib.crc32(raw)) + raw


def redistribute(tree: Any, src: Spec, dst: Spec, transport=None, *,
                 tag: str = "redist",
                 max_chunk_bytes: Optional[int] = None,
                 entries: Optional[List[dict]] = None) -> Any:
    """Redistribute ``tree`` from layout ``src`` to layout ``dst`` over
    ``transport``; returns the tree in the destination layout (numpy
    leaves), or ``None`` on ranks outside the destination world.

    Every participating rank passes a structurally identical ``tree``
    (receivers: their template — live shapes, stale contents; sources:
    the live data). ``src == dst`` returns the INPUT OBJECT untouched.
    For ``src.layout == "row"`` the local leaves are this rank's
    row-blocks; the GLOBAL leaf table must then be supplied via
    ``entries`` (a manifest-style leaf list) since it is not derivable
    from a local flatten.

    Bounded memory: wire ops are executed in rounds capped at
    ``max_chunk_bytes`` (default ``HOROVOD_REDIST_CHUNK_BYTES``) per
    rank per direction; each frame is crc32-verified on receipt and a
    missing or corrupt frame raises :class:`RedistError` naming the
    leaf — never a silently wrong tree. Leaves that did not move (a
    holder target's full-span self-serve) may ALIAS the input tree's
    arrays in the returned tree.
    """
    if _is_identity(src, dst):
        return tree
    if transport is None:
        raise RedistError(
            "redistribute() needs a transport unless src == dst "
            "(the no-copy identity)")
    t0 = time.perf_counter()
    r, world = transport.rank, transport.world
    # spec-vs-transport validation BEFORE the backend dispatch: a
    # mis-specced disk call must fail fast here, not by a 300s
    # visibility-poll timeout with no writer
    if dst.world > world:
        raise RedistError(
            f"destination world {dst.world} exceeds transport world "
            f"{world}")
    if max(src.holder_list()) >= world:
        raise RedistError(
            f"source ranks {src.holder_list()} exceed transport world "
            f"{world}")
    if getattr(transport, "kind", "wire") == "disk":
        return _redistribute_disk(tree, src, dst, transport, tag, t0)
    from ..ckpt.snapshot import host_snapshot
    from ..ckpt.store import _leaf_entry
    paths, leaves_np, treedef = host_snapshot(tree, copy_np=False)
    if entries is None:
        if src.layout == "row":
            raise RedistError(
                "src layout 'row' needs the GLOBAL leaf table via "
                "entries= (local leaves are row-blocks; global shapes "
                "are not derivable from them)")
        entries = [_leaf_entry(p, l) for p, l in zip(paths, leaves_np)]
    if len(entries) != len(leaves_np):
        raise RedistError(
            f"leaf table has {len(entries)} entries but the local tree "
            f"flattened to {len(leaves_np)} leaves")
    chunk = _chunk_bytes(max_chunk_bytes)
    crc = _plan_crc(entries, src, dst, chunk)
    plans = plan_redistribute(entries, src, dst, include_pyobj=True)
    my_plan = plans.get(r, [])
    is_target = r < dst.world

    # -- destination buffers + local ops (no wire) ------------------------
    out: List[Any] = [None] * len(entries)
    dst_base: Dict[int, int] = {}
    if is_target:
        for i, e in enumerate(entries):
            if e["kind"] != "array":
                out[i] = leaves_np[i]          # template value; a pyobj
                continue                       # frame may overwrite it
            shape = tuple(e["shape"])
            if e["partition"] == "rep":
                # row-layout destinations deliver rep leaves to target
                # 0 only (the ckpt shard convention): other targets
                # keep their template value rather than uninitialized
                # memory
                out[i] = np.asarray(leaves_np[i],
                                    np.dtype(e["dtype"])).copy()
                continue
            if dst.layout == "row":
                b = row_bounds(shape[0], dst.world)
                dst_base[i] = b[r]
                shape = (b[r + 1] - b[r],) + shape[1:]
            out[i] = np.empty(shape, np.dtype(e["dtype"]))
        for op in my_plan:
            if op["src"] != r:
                continue
            i = op["leaf"]
            e = entries[i]
            if op.get("pyobj"):
                out[i] = leaves_np[i]
            elif op["rows"] is None:
                out[i] = np.asarray(leaves_np[i],
                                    np.dtype(e["dtype"])).copy()
            else:
                lo, hi = op["rows"]
                base = _src_base(e, src, r)
                if lo == 0 and base == 0 and hi == e["shape"][0] \
                        and dst_base.get(i, 0) == 0:
                    # full-span self-serve (a holder target): the local
                    # leaf IS the destination block — alias it instead
                    # of a whole-leaf memcpy (multi-GB trees on elastic
                    # holders move zero bytes AND copy zero bytes)
                    out[i] = leaves_np[i]
                    continue
                out[i][lo - dst_base.get(i, 0):
                       hi - dst_base.get(i, 0)] = \
                    leaves_np[i][lo - base:hi - base]

    # -- wire rounds ------------------------------------------------------
    # the expectation ledger is built from the ROUND SCHEDULE (chunked
    # pieces), not the raw plan, so it matches the frames byte-for-byte
    rounds = schedule_rounds(plans, entries, chunk)
    expected: Dict[Tuple[int, int, int, int], int] = {}
    if is_target:
        for rnd in rounds:
            for t, op in rnd:
                if t != r or op["src"] == r:
                    continue
                lo, hi = op["rows"] if op["rows"] is not None else (0, 0)
                key = (op["leaf"], op["src"], lo, hi)
                expected[key] = expected.get(key, 0) + 1
    sent_bytes = recv_bytes = 0
    for k, rnd in enumerate(rounds):
        frames: Dict[int, List[bytes]] = {}
        round_total = 0
        for t, op in rnd:
            round_total += op_nbytes(op, entries)
            if op["src"] != r or t == r:
                continue
            frames.setdefault(t, []).append(
                _frame(entries, leaves_np, src, r, op))
        outgoing = {d: _HDR.pack(_MAGIC, crc, len(fs)) + b"".join(fs)
                    for d, fs in frames.items()}
        sent_bytes += sum(len(p) for p in outgoing.values())
        incoming = transport.exchange(
            outgoing, tag=f"{tag}.r{k}",
            max_bytes_hint=round_total + _FRAME.size * len(rnd)
            + _HDR.size * world)
        for s, payload in sorted(incoming.items()):
            recv_bytes += len(payload)
            _consume(payload, s, crc, entries, src, dst, r, dst_base,
                     out, expected, tag)
    if expected:
        missing = sorted(expected)[:4]
        raise RedistError(
            f"redistribution {tag!r} incomplete on rank {r}: "
            f"{len(expected)} expected block(s) never arrived "
            f"(first: {missing})")

    ms = (time.perf_counter() - t0) * 1000.0
    try:
        ctr, hist = _obs(transport.name)
        ctr.inc(sent_bytes)
        hist.observe(ms)
    except Exception:  # noqa: BLE001 — obs must never block the move
        pass
    _timeline_instant({"transport": transport.name, "rank": r,
                       "ms": round(ms, 3), "bytes_sent": sent_bytes,
                       "bytes_recv": recv_bytes, "rounds": len(rounds),
                       "src": f"{src.layout}/{src.world}",
                       "dst": f"{dst.layout}/{dst.world}"})
    if not is_target:
        return None
    import jax
    return jax.tree_util.tree_unflatten(treedef, out)


def _consume(payload: bytes, src_rank: int, crc: int,
             entries: List[dict], src: Spec, dst: Spec, r: int,
             dst_base: Dict[int, int], out: List[Any],
             expected: Dict[Tuple[int, int, int, int], int],
             tag: str) -> None:
    """Parse + verify one incoming per-source payload into ``out``."""
    if len(payload) < _HDR.size:
        raise RedistError(
            f"truncated redistribution payload from rank {src_rank} "
            f"({tag!r}): {len(payload)} bytes")
    magic, their_crc, _ = _HDR.unpack_from(payload, 0)
    if magic != _MAGIC:
        raise RedistError(
            f"bad redistribution payload magic from rank {src_rank} "
            f"({tag!r})")
    if their_crc != crc:
        raise RedistError(
            f"redistribution plan mismatch with rank {src_rank} "
            f"({tag!r}): the two ranks derived different leaf tables "
            f"or specs — refusing to assemble a torn tree")
    off = _HDR.size
    while off < len(payload):
        if off + _FRAME.size > len(payload):
            raise RedistError(
                f"truncated frame header from rank {src_rank} ({tag!r})")
        leaf, flags, lo, hi, nbytes, fcrc = _FRAME.unpack_from(
            payload, off)
        off += _FRAME.size
        raw = payload[off:off + nbytes]
        off += nbytes
        if len(raw) != nbytes:
            raise RedistError(
                f"short frame for leaf {leaf} from rank {src_rank} "
                f"({tag!r}): {len(raw)} of {nbytes} bytes")
        if zlib.crc32(raw) != fcrc:
            e = entries[leaf] if leaf < len(entries) else {}
            raise RedistError(
                f"crc32 mismatch on leaf {leaf} "
                f"({e.get('path')!r}, rows [{lo}, {hi})) from rank "
                f"{src_rank} ({tag!r}) — transport corrupted the "
                f"payload; refusing to assemble")
        if leaf >= len(entries):
            raise RedistError(
                f"frame names leaf {leaf} beyond the table "
                f"({len(entries)} leaves) from rank {src_rank}")
        e = entries[leaf]
        key = (leaf, src_rank, lo, hi)
        if key not in expected:
            raise RedistError(
                f"unexpected block {key} from rank {src_rank} "
                f"({tag!r}) — not in this rank's plan")
        expected[key] -= 1
        if expected[key] == 0:
            del expected[key]
        if flags & _F_PYOBJ:
            import pickle
            out[leaf] = pickle.loads(raw)
        elif flags & _F_WHOLE:
            out[leaf] = np.frombuffer(
                raw, np.dtype(e["dtype"])).reshape(e["shape"]).copy()
        else:
            trail = tuple(e["shape"][1:])
            block = np.frombuffer(raw, np.dtype(e["dtype"])).reshape(
                (hi - lo,) + trail)
            base = dst_base.get(leaf, 0)
            out[leaf][lo - base:hi - base] = block


def _redistribute_disk(tree: Any, src: Spec, dst: Spec, transport,
                       tag: str, t0: float) -> Any:
    """The CkptTransport path: sources persist through the sharded
    checkpoint store, targets restore through the reshard-overlap plan.
    Slower than the wire (2x disk + fsync) but survives total loss of
    in-memory state — the elastic fallback."""
    from ..ckpt.store import ShardedCheckpointer, list_steps
    if dst.layout != "full":
        raise RedistError(
            "the disk transport restores full trees only "
            "(dst layout 'full')")
    if src.layout == "row":
        raise RedistError(
            "the disk transport moves full-layout sources only — a "
            "row-sharded source already has a manifest; restore it "
            "through the ckpt plane (restore_resharded) instead")
    r = transport.rank
    # the step is derived from (call tag, transport call counter) —
    # both rank-invariant, together unique per logical call even when
    # one transport/directory is reused with the default tag: readers
    # polling for visibility below must wait for THIS call's commit,
    # not find a previous call's step and restore stale state
    seq = transport.next_seq()
    step = zlib.crc32(f"{tag}.{seq}".encode()) % 100_000_000
    if r == src.holder_list()[0]:
        ck = ShardedCheckpointer(
            transport.directory, rank=0, world=1, async_save=False,
            replicate=False, commit_timeout=transport.timeout)
        ck.save(step, tree, force=True)
        ck.close()
    # commit visibility barrier: poll the shared directory (works with
    # or without a coordinator; the committer raised if a writer died)
    deadline = time.monotonic() + transport.timeout
    while step not in list_steps(transport.directory):
        if time.monotonic() >= deadline:
            raise RedistError(
                f"disk redistribution {tag!r}: commit never became "
                f"visible within {transport.timeout:g}s")
        time.sleep(0.005)
    if transport.coordinator is not None:
        transport.coordinator.barrier(tag=f"{tag}.disk")
    if r >= dst.world:
        return None
    ck = ShardedCheckpointer(
        transport.directory, rank=r, world=dst.world, async_save=False,
        replicate=False, commit_timeout=transport.timeout)
    try:
        out = ck.restore(step, target=tree, via="local")
    finally:
        ck.close()
    ms = (time.perf_counter() - t0) * 1000.0
    try:
        # disk BYTES are accounted by the ckpt plane's own counters
        # (hvd_ckpt_bytes_total): only the redistribution latency is
        # recorded here — deliberately no {transport="ckpt"} byte
        # counter child, which would permanently read 0
        from ..obs import metrics as m
        m.get_registry().histogram("hvd_redist_ms",
                                   REDIST_MS_HELP).observe(ms)
    except Exception:  # noqa: BLE001
        pass
    _timeline_instant({"transport": transport.name, "rank": r,
                       "ms": round(ms, 3),
                       "src": f"{src.layout}/{src.world}",
                       "dst": f"{dst.layout}/{dst.world}"})
    return out
