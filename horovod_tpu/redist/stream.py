"""Training -> serving hot weight streaming: no disk hop.

A training job publishes versioned parameter snapshots into the native
KV store's memory; a running serve fleet polls, verifies, and hot-swaps
them between decode iterations. The online-learning path the north star
asks for: fresh weights reach a live fleet without a checkpoint
round-trip through a shared filesystem.

Protocol (``hvdws-v1``), all in KV-server memory:

* ``ws.<channel>.head``          — JSON: version, slot, chunk table
  (nbytes + crc32 each), the manifest-style leaf table (pyobj leaves
  ride here whole, like the ckpt manifest).
* ``ws.<channel>.s<slot>.c<j>``  — raw payload chunks, leaf order.

The publisher alternates between ``slots`` slot prefixes (default 2),
writing every chunk BEFORE flipping the head — a reader always finds a
complete slot behind the head, and server memory is bounded at
``slots`` versions regardless of publish count. A subscriber that races
an overwrite of the slot it is reading detects it by per-chunk crc32,
re-reads the head, and simply skips to the newer version — torn reads
are impossible to adopt by construction.

Version adoption is MONOTONE per subscriber: ``poll()`` never returns a
version <= the one already adopted, so replicas that poll at different
cadences converge on the same latest version and never move backwards.
The executor side of the fence (serve/executor.py ``swap_params``)
guarantees no swap lands mid-step.

Chaos: publish and fetch cross the ``redist.transport`` fault site —
an injected ``corrupt`` is caught by the chunk crc32 exactly like a
wire fault on the elastic path.
"""
from __future__ import annotations

import json
import logging
import socket
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .plan import RedistError
from .transport import chaos_gate

logger = logging.getLogger("horovod_tpu")

FORMAT = "hvdws-v1"


def version_key(channel: str) -> str:
    """The tiny newest-published-version key of a channel — the ONLY
    key external consumers (the serve fleets' re-admission gates) may
    read directly; every other ``ws.*`` key layout is this module's
    private business."""
    return f"ws.{channel}.v"


def _resolve_client(client, kv_addr, kv_port, rank=None):
    """(StoreClient, owns) — explicit client > explicit endpoint >
    the launcher's HOROVOD_NATIVE_KV_ADDR/PORT export."""
    if client is not None:
        return client, False
    import os
    if kv_addr is None or kv_port is None:
        kv_addr = os.environ.get("HOROVOD_NATIVE_KV_ADDR")
        kv_port = os.environ.get("HOROVOD_NATIVE_KV_PORT")
        if not kv_addr or not kv_port:
            raise RedistError(
                "weight streaming needs the native KV store — pass "
                "kv_addr/kv_port (or a client) or export "
                "HOROVOD_NATIVE_KV_ADDR/PORT")
    from ..native.store import StoreClient
    return StoreClient(socket.gethostbyname(kv_addr), int(kv_port),
                       rank=rank), True


def _stream_obs():
    from ..obs import metrics as m
    from .core import REDIST_BYTES_HELP
    R = m.get_registry()
    return R.counter("hvd_redist_bytes_total", REDIST_BYTES_HELP,
                     {"transport": "stream"})


class WeightPublisher:
    """Publishes versioned parameter trees into the KV stream."""

    def __init__(self, channel: str = "default", *,
                 kv_addr: Optional[str] = None,
                 kv_port: Optional[int] = None,
                 client=None, slots: int = 2,
                 chunk_bytes: int = 4 * 1024 * 1024,
                 resume_timeout: float = 1.0):
        if slots < 2:
            raise RedistError(
                f"weight streaming needs >= 2 slots (a reader must "
                f"always have a complete slot behind the head); got "
                f"{slots}")
        if chunk_bytes < 4096:
            raise RedistError(
                f"chunk_bytes must be >= 4096; got {chunk_bytes}")
        self.channel = channel
        self.slots = int(slots)
        self.chunk_bytes = int(chunk_bytes)
        self._kv, self._owns = _resolve_client(client, kv_addr, kv_port)
        # resume the channel's version sequence: a RESTARTED publisher
        # (the elastic reality) must continue above the live head, or
        # every subscriber would silently refuse its publishes forever
        # under the monotone-adoption rule. The KV store cannot
        # distinguish "key absent" from "store slow", so the resume
        # probe waits a generous resume_timeout (a fresh channel pays
        # it exactly once, at construction) rather than a tight poll
        # that a busy store would mistake for a fresh channel.
        self._version = 0
        from ..native.store import NativeTimeout
        try:
            raw = self._kv.get(f"ws.{self.channel}.head",
                               timeout=max(float(resume_timeout),
                                           0.001))
            head = json.loads(raw.decode())
            if head.get("format") == FORMAT:
                self._version = int(head["version"])
        except (NativeTimeout, ValueError, KeyError, TypeError):
            pass                         # fresh channel

    def publish(self, tree: Any, version: Optional[int] = None) -> int:
        """Snapshot ``tree`` to host and publish it; returns the
        version. Versions must be strictly increasing per publisher
        (default: last + 1)."""
        from ..ckpt.snapshot import host_snapshot
        paths, leaves, _ = host_snapshot(tree, copy_np=False)
        return self.publish_flat(paths, leaves, version=version)

    def publish_flat(self, paths: List[str], leaves: List[Any],
                     version: Optional[int] = None) -> int:
        """Publish an already-flattened (paths, leaves) pair — the
        jax-free entry tools/weights_push.py uses."""
        from ..ckpt.store import _leaf_entry
        v = self._version + 1 if version is None else int(version)
        if v <= self._version:
            raise RedistError(
                f"weight-stream versions must be strictly increasing; "
                f"got {v} after {self._version}")
        entries = [_leaf_entry(p, l) for p, l in zip(paths, leaves)]
        slot = v % self.slots
        # STREAM the chunks: leaf bytes flow through one chunk-sized
        # staging buffer instead of a monolithic join of the whole tree
        # (a multi-GB publish must cost ~chunk_bytes extra memory, not
        # 2x the tree — the plane's bounded-memory discipline). crc is
        # computed over the ORIGINAL bytes, THEN the chaos gate, so an
        # injected publish-side corruption lands in the stored chunk
        # but not its checksum and the subscriber's verify catches it.
        table: List[dict] = []
        total = 0

        def emit(raw: bytes) -> None:
            j = len(table)
            table.append({"nbytes": len(raw), "crc32": zlib.crc32(raw)})
            gated = chaos_gate({j: raw})
            self._kv.set(f"ws.{self.channel}.s{slot}.c{j}", gated[j])

        buf = bytearray()
        for e, l in zip(entries, leaves):
            if e["kind"] != "array":
                continue
            arr = np.ascontiguousarray(l)
            if arr.size == 0:
                continue      # zero-size leaf: no bytes in the stream
            mv = memoryview(arr.reshape(-1)).cast("B")
            total += mv.nbytes
            off = 0
            while off < mv.nbytes:
                take = min(self.chunk_bytes - len(buf),
                           mv.nbytes - off)
                buf += mv[off:off + take]
                off += take
                if len(buf) == self.chunk_bytes:
                    emit(bytes(buf))
                    buf.clear()
        if buf or not table:
            emit(bytes(buf))  # tail, or the lone empty chunk of an
        del buf               # array-free tree (poll expects >= 1)
        head = {"format": FORMAT, "version": v, "slot": slot,
                "total": total, "chunks": table,
                "leaves": entries, "t": time.time()}
        self._kv.set(f"ws.{self.channel}.head",
                     json.dumps(head).encode())
        # the tiny version key goes LAST: a subscriber that sees it can
        # rely on the (potentially large) head already carrying >= this
        # version. Polls check this handful of bytes first, so an idle
        # channel costs a few bytes per poll — not a full head fetch +
        # json parse of the leaf/chunk tables per replica per 250ms
        self._kv.set(version_key(self.channel), str(v).encode())
        self._version = v
        try:
            _stream_obs().inc(total)
        except Exception:  # noqa: BLE001
            pass
        logger.info("weight stream %r: published version %d "
                    "(%d bytes, %d chunk(s), slot %d)", self.channel,
                    v, total, len(table), slot)
        return v

    def close(self) -> None:
        if self._owns and self._kv is not None:
            self._kv.close()
            self._kv = None


class WeightSubscriber:
    """Polls a channel and assembles newer versions; adoption is
    monotone and torn reads are structurally impossible to return."""

    def __init__(self, channel: str = "default", *,
                 kv_addr: Optional[str] = None,
                 kv_port: Optional[int] = None,
                 client=None, template: Any = None,
                 poll_timeout: float = 0.05):
        self.channel = channel
        self.template = template
        self.poll_timeout = float(poll_timeout)
        self._kv, self._owns = _resolve_client(client, kv_addr, kv_port)
        self.version = 0
        # poll()/peek_version() share one KV socket and the monotone
        # version cursor: serialize them so a replica's background
        # adoption thread and the fleet router's re-admission gate
        # (serve/fleet.py) can share a subscriber without interleaving
        # requests on the wire
        self._plock = threading.Lock()

    def _head(self) -> Optional[dict]:
        from ..native.store import NativeTimeout
        try:
            raw = self._kv.get(f"ws.{self.channel}.head",
                               timeout=self.poll_timeout)
        except NativeTimeout:
            return None
        head = json.loads(raw.decode())
        if head.get("format") != FORMAT:
            raise RedistError(
                f"weight stream {self.channel!r} head has format "
                f"{head.get('format')!r} (this build reads {FORMAT!r})")
        return head

    def peek_version(self) -> Optional[int]:
        """The channel's newest PUBLISHED version — a few bytes read
        from the version key, no head fetch, no adoption, no side
        effects. None when nothing is published (or the store is
        slow). The fleet router's re-admission gate reads this: a
        recovered replica must re-adopt at least this version before
        it takes traffic again (serve/fleet.py)."""
        from ..native.store import NativeTimeout
        with self._plock:
            try:
                # lock-order: exempt (_plock EXISTS to serialize this
                # one KV socket between the batcher adoption thread and
                # the router's re-admission gate; nothing else is
                # guarded by it, so holding it across the bounded
                # poll_timeout read is its entire job — PR 11)
                raw = self._kv.get(version_key(self.channel),
                                   timeout=self.poll_timeout)
                return int(raw.decode())
            except (NativeTimeout, ValueError):
                return None

    def poll(self) -> Optional[Tuple[int, Any]]:
        """Adopt a newer version if one is published: returns
        ``(version, tree)`` or None (nothing new yet). A slot torn by a
        concurrent overwrite is detected by crc32 and skipped — the
        NEXT poll sees the overwriting version's head. Serialized:
        concurrent callers (a batcher's adoption thread + the fleet
        router's recovery gate) queue, they don't interleave."""
        with self._plock:
            return self._poll_locked()

    def _poll_locked(self) -> Optional[Tuple[int, Any]]:
        from ..native.store import NativeTimeout
        try:
            raw = self._kv.get(version_key(self.channel),
                               timeout=self.poll_timeout)
            if int(raw.decode()) <= self.version:
                return None              # cheap steady-state no-op
        except NativeTimeout:
            return None                  # nothing published yet
        except ValueError:
            pass                         # malformed: let the head decide
        head = self._head()
        if head is None or head["version"] <= self.version:
            return None
        v, slot = head["version"], head["slot"]
        # STREAM the assembly: each fetched chunk is crc-verified and
        # copied straight into the preallocated leaf arrays — peak
        # extra memory is one chunk, never the joined payload (the
        # publish side mirrors this; a multi-GB adoption costs
        # ~chunk_bytes over the tree itself)
        from ..ckpt.store import pyobj_value
        entries = head["leaves"]
        leaves: List[Any] = []
        fill: List[np.ndarray] = []      # flat uint8 views, leaf order
        for e in entries:
            if e["kind"] != "array":
                leaves.append(pyobj_value(e))
                continue
            arr = np.empty(e["shape"], np.dtype(e["dtype"]))
            leaves.append(arr)
            fill.append(arr.reshape(-1).view(np.uint8))
        li = off = got = 0
        for j, c in enumerate(head["chunks"]):
            raw = self._kv.get(f"ws.{self.channel}.s{slot}.c{j}",
                               timeout=self.poll_timeout,
                               max_bytes=max(c["nbytes"], 64) + 64)
            gated = chaos_gate({0: raw})
            raw = gated[0]
            if len(raw) != c["nbytes"] or zlib.crc32(raw) != c["crc32"]:
                again = self._head()
                if again is not None and again["version"] != v:
                    # the publisher lapped this slot mid-read: not
                    # corruption, just a stale version — skip it
                    return None
                raise RedistError(
                    f"weight stream {self.channel!r} version {v} chunk "
                    f"{j} failed crc32 — refusing to adopt a torn or "
                    f"corrupted snapshot")
            got += len(raw)
            mv = memoryview(raw)
            while mv.nbytes:
                if li >= len(fill):
                    raise RedistError(
                        f"weight stream {self.channel!r} version {v}: "
                        f"chunk bytes overflow the leaf table")
                dst = fill[li]
                if dst.nbytes == 0:      # zero-size leaf: nothing to
                    li += 1              # fill, never loop on take=0
                    continue
                take = min(dst.nbytes - off, mv.nbytes)
                dst[off:off + take] = np.frombuffer(mv[:take],
                                                    np.uint8)
                off += take
                mv = mv[take:]
                if off == dst.nbytes:
                    li += 1
                    off = 0
        while li < len(fill) and fill[li].nbytes == 0:
            li += 1                      # trailing zero-size leaves
        if got != head["total"] or li != len(fill) or off:
            raise RedistError(
                f"weight stream {self.channel!r} version {v}: "
                f"{got} payload bytes, head says {head['total']} "
                f"(assembly stopped at leaf {li}/{len(fill)})")
        tree = self._finish_tree(entries, leaves)
        self.version = v
        return v, tree

    def _finish_tree(self, entries: List[dict],
                     leaves: List[Any]) -> Any:
        if self.template is not None:
            import jax
            t_leaves, t_def = jax.tree_util.tree_flatten(self.template)
            if len(t_leaves) != len(leaves):
                raise RedistError(
                    f"weight stream tree has {len(leaves)} leaves; "
                    f"subscriber template has {len(t_leaves)}")
            return jax.tree_util.tree_unflatten(t_def, leaves)
        out: Dict[str, Any] = {}
        for e, v in zip(entries, leaves):
            node = out
            parts = [p for p in e["path"].split("/") if p]
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1] if parts else e["path"]] = v
        return out

    def close(self) -> None:
        if self._owns and self._kv is not None:
            self._kv.close()
            self._kv = None
