"""horovod_tpu.redist: live N->M weight redistribution over the wire.

The plane that moves parameter trees between layouts and worlds WITHOUT
a filesystem round trip, split cleanly into **plan** and **transport**
(PAPERS.md: "Memory-efficient array redistribution through portable
collective communication"):

    plan.py       pure overlap math: Spec (row/full layouts),
                  plan_redistribute, bounded-round scheduling — the
                  layer ckpt/reshard.py now consumes instead of owning
    transport.py  interchangeable data planes: p2p ring alltoall,
                  coordinator allgather, disk-backed ckpt (fallback);
                  chaos fault site ``redist.transport``
    core.py       redistribute(tree, src, dst, transport=...) — chunked
                  bounded-memory transfers, per-frame crc32, no-copy
                  N==M identity
    elastic.py    elastic consumer: survivors of a reset redistribute
                  committed state in memory (zero checkpoint reads);
                  fallback to ckpt auto-restore decided COLLECTIVELY
    stream.py     training->serving hot weight streaming: versioned
                  publisher/subscriber over the native KV, monotone
                  adoption, serve hot-swap between decode iterations

Knobs: ``HOROVOD_REDIST_ELASTIC`` (in-memory elastic restore on/off),
``HOROVOD_REDIST_CHUNK_BYTES`` (per-rank bytes per round).
Observability: ``hvd_redist_bytes_total{transport}``,
``hvd_redist_ms``, ``hvd_weight_swap_ms``, REDIST/SWAP timeline rows.
See docs/redistribution.md.
"""
from .plan import (                                            # noqa: F401
    RedistError, Spec, plan_redistribute, row_bounds, schedule_rounds,
)
from .transport import (                                       # noqa: F401
    CkptTransport, CoordTransport, RingTransport,
)
from .core import redistribute                                 # noqa: F401
from .stream import WeightPublisher, WeightSubscriber          # noqa: F401
from .elastic import elastic_restore                           # noqa: F401
