"""The shared N->M redistribution plan — pure math, no IO, no comm.

This is the plan layer of the redistribution plane (PAPERS.md:
"Memory-efficient array redistribution through portable collective
communication"): given a leaf table (the same entry records the ckpt
manifest carries — path/dtype/shape/partition) and a source and
destination :class:`Spec`, compute which rows of which leaves must move
from which source rank to which target rank. The data plane — ring p2p,
coordinator allgather, or disk (redist/transport.py) — executes the
plan; the checkpoint reshard (ckpt/reshard.py) is one CONSUMER of this
module, not its owner.

Layouts:

* ``row``  — every array leaf with a leading axis is row-partitioned
  across the spec's world by the balanced ``row_bounds`` split (the
  checkpoint shard layout); 0-d ("rep") leaves live whole on rank 0.
* ``full`` — some subset of ranks (``holders``) each hold a COMPLETE
  copy of the tree (the elastic replicated-state layout and the
  training->serving publisher layout).

The plan is a pure function of (leaves, src, dst): every rank computes
the identical global plan, so no negotiation round is needed to agree
on who sends what. Ops are emitted in (leaf, target, source) order —
the same order payloads are framed in — so planner and assembler agree
byte-for-byte. ``src == dst`` is the degenerate identity: callers
(redist/core.py) return the input tree untouched, no copy.

Everything here is stdlib+numpy only; jax never enters the plan layer.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class RedistError(RuntimeError):
    """Redistribution-plane failure (bad spec, missing block, CRC
    mismatch, transport fault). Fail-fast, always attributable."""


def row_bounds(n: int, world: int) -> List[int]:
    """Axis-0 partition bounds: rank i owns rows
    ``[bounds[i], bounds[i+1])`` — the one balanced split every layout
    in this codebase derives from (ckpt shards, the p2p ring's chunk
    walk). ckpt/store.py keeps a standalone copy (it must spec-load
    with no package context for tools/ckpt_inspect.py); the two are
    asserted identical in tests/test_redist.py."""
    return [(i * n) // world for i in range(world + 1)]


_LAYOUTS = ("row", "full")


@dataclass(frozen=True)
class Spec:
    """How a tree is laid out across ``world`` ranks.

    ``layout="row"``: row-partitioned by :func:`row_bounds` (rep leaves
    whole on rank 0). ``layout="full"``: every rank in ``holders``
    (default: all) holds a complete copy.
    """

    world: int
    layout: str = "full"
    holders: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        if not isinstance(self.world, int) or self.world < 1:
            raise RedistError(f"spec world must be >= 1; got {self.world!r}")
        if self.layout not in _LAYOUTS:
            raise RedistError(
                f"spec layout must be one of {_LAYOUTS}; got "
                f"{self.layout!r}")
        if self.holders is not None:
            if self.layout != "full":
                raise RedistError("holders only applies to layout='full'")
            h = tuple(sorted(int(r) for r in self.holders))
            if not h:
                raise RedistError("holders must not be empty")
            if h[0] < 0 or h[-1] >= self.world or len(set(h)) != len(h):
                raise RedistError(
                    f"holders must be distinct ranks in [0, {self.world}); "
                    f"got {self.holders!r}")
            object.__setattr__(self, "holders", h)

    @staticmethod
    def row(world: int) -> "Spec":
        return Spec(world=world, layout="row")

    @staticmethod
    def full(world: int,
             holders: Optional[Sequence[int]] = None) -> "Spec":
        return Spec(world=world, layout="full",
                    holders=tuple(holders) if holders is not None else None)

    def holder_list(self) -> List[int]:
        """Ranks holding a complete copy (full layout) or contributing
        shards (row layout: everyone)."""
        if self.layout == "row" or self.holders is None:
            return list(range(self.world))
        return list(self.holders)


def leaf_nbytes(entry: dict) -> int:
    """Total bytes of an array leaf entry."""
    n = np.dtype(entry["dtype"]).itemsize
    for d in entry["shape"]:
        n *= d
    return int(n)


def row_nbytes(entry: dict) -> int:
    """Bytes per axis-0 row of a row-partitioned array leaf."""
    n = np.dtype(entry["dtype"]).itemsize
    for d in entry["shape"][1:]:
        n *= d
    return int(n)


def op_nbytes(op: dict, leaves: List[dict]) -> int:
    """Wire bytes one op moves (0 for pyobj ops — their pickled size is
    not derivable from the leaf table; they are control-plane small)."""
    e = leaves[op["leaf"]]
    if op.get("pyobj") or e["kind"] != "array":
        return 0
    if op["rows"] is None:
        return leaf_nbytes(e)
    lo, hi = op["rows"]
    return (hi - lo) * row_nbytes(e)


def _span_across(lo: int, hi: int, srcs: List[int]
                 ) -> List[Tuple[int, int, int]]:
    """Split the row span [lo, hi) across ``srcs`` evenly (the
    full-layout fan-out rule): k-th source serves the k-th balanced
    sub-span. Deterministic, gap/overlap-free by construction."""
    n, k = hi - lo, len(srcs)
    out = []
    for j, s in enumerate(srcs):
        a = lo + (n * j) // k
        b = lo + (n * (j + 1)) // k
        if b > a:
            out.append((s, a, b))
    return out


def plan_redistribute(leaves: List[dict], src: Spec, dst: Spec,
                      target_rank: Optional[int] = None,
                      include_pyobj: bool = False
                      ) -> Dict[int, List[dict]]:
    """The redistribution plan: for each target rank of ``dst``, which
    rows of which leaves it must obtain from which source rank of
    ``src``.

    Returns ``{target: [op, ...]}`` (restricted to ``target_rank`` when
    given). Each op is ``{"leaf": i, "src": s, "rows": [lo, hi)}`` in
    GLOBAL row coordinates; ``rows`` is None for whole-leaf transfers
    (replicated 0-d leaves, and pyobj ops when ``include_pyobj`` — those
    additionally carry ``"pyobj": True``). Ops are emitted in (leaf,
    target, source) order so every executor frames bytes identically.

    Source assignment rules:

    * src row  -> overlap of the target's needed rows with the source
      world's ``row_bounds`` blocks (the ckpt reshard-overlap plan).
    * src full -> a target that is itself a holder serves itself (zero
      wire bytes); other targets split their needed span evenly across
      the holders so no single holder uplinks the whole tree.
    """
    if dst.holders is not None and \
            len(dst.holders) != dst.world:
        raise RedistError(
            "destination specs do not support holder subsets — every "
            "rank of dst.world receives its block; restrict the "
            "destination by shrinking dst.world instead")
    targets = range(dst.world) if target_rank is None else [target_rank]
    if target_rank is not None and not (0 <= target_rank < dst.world):
        raise RedistError(
            f"target rank {target_rank} out of range for destination "
            f"world {dst.world}")
    holders = src.holder_list()
    plans: Dict[int, List[dict]] = {t: [] for t in targets}
    for i, e in enumerate(leaves):
        if e["kind"] != "array":
            if include_pyobj:
                s0 = holders[0]
                for t in targets:
                    if dst.layout == "row" and t != 0:
                        continue
                    plans[t].append({"leaf": i, "src": s0, "rows": None,
                                     "pyobj": True})
            continue
        if e["partition"] == "rep":
            # whole 0-d leaves: on rank 0 in row layout (the ckpt shard
            # convention), on every holder in full layout
            for t in targets:
                if dst.layout == "row" and t != 0:
                    continue
                if src.layout == "full" and t in holders:
                    s0 = t
                else:
                    s0 = holders[0] if src.layout == "full" else 0
                plans[t].append({"leaf": i, "src": s0, "rows": None})
            continue
        n = e["shape"][0]
        for t in targets:
            if dst.layout == "row":
                tb = row_bounds(n, dst.world)
                tlo, thi = tb[t], tb[t + 1]
            else:
                tlo, thi = 0, n
            if thi <= tlo:
                continue
            if src.layout == "row":
                sb = row_bounds(n, src.world)
                for s in range(src.world):
                    lo, hi = max(tlo, sb[s]), min(thi, sb[s + 1])
                    if hi > lo:
                        plans[t].append({"leaf": i, "src": s,
                                         "rows": [lo, hi]})
            else:
                if t in holders:
                    # a holder target already owns every row: serve
                    # yourself, move nothing
                    plans[t].append({"leaf": i, "src": t,
                                     "rows": [tlo, thi]})
                    continue
                for s, lo, hi in _span_across(tlo, thi, holders):
                    plans[t].append({"leaf": i, "src": s,
                                     "rows": [lo, hi]})
    return plans


def split_op(op: dict, leaves: List[dict], max_bytes: int) -> List[dict]:
    """Split one row op into pieces of at most ``max_bytes`` (always at
    least one row per piece — a single row wider than the budget moves
    whole). Whole-leaf / pyobj ops are unsplittable."""
    if op["rows"] is None:
        return [op]
    e = leaves[op["leaf"]]
    rb = row_nbytes(e)
    lo, hi = op["rows"]
    step = max(1, max_bytes // max(rb, 1))
    if hi - lo <= step:
        return [op]
    out = []
    a = lo
    while a < hi:
        b = min(a + step, hi)
        out.append(dict(op, rows=[a, b]))
        a = b
    return out


def schedule_rounds(plans: Dict[int, List[dict]], leaves: List[dict],
                    max_bytes: int) -> List[List[Tuple[int, dict]]]:
    """Group the plan's WIRE ops (src != target) into bounded rounds.

    Returns a list of rounds, each a list of ``(target, op)`` pairs, such
    that within one round no source sends more than ~``max_bytes`` and
    no target receives more than ~``max_bytes`` (each round is one
    transport exchange — the bounded-memory contract). Ops larger than
    the budget are split by :func:`split_op` first. The schedule is a
    pure function of the plan, so every rank derives the identical round
    structure with no negotiation."""
    if max_bytes < 1:
        raise RedistError(f"max_bytes must be >= 1; got {max_bytes}")
    flat: List[Tuple[int, dict]] = []
    for t in sorted(plans):
        for op in plans[t]:
            if op["src"] == t:
                continue
            for piece in split_op(op, leaves, max_bytes):
                flat.append((t, piece))
    rounds: List[List[Tuple[int, dict]]] = []
    cur: List[Tuple[int, dict]] = []
    sent: Dict[int, int] = {}
    recv: Dict[int, int] = {}
    for t, op in flat:
        nb = op_nbytes(op, leaves)
        s = op["src"]
        if cur and (sent.get(s, 0) + nb > max_bytes
                    or recv.get(t, 0) + nb > max_bytes):
            rounds.append(cur)
            cur, sent, recv = [], {}, {}
        cur.append((t, op))
        sent[s] = sent.get(s, 0) + nb
        recv[t] = recv.get(t, 0) + nb
    if cur:
        rounds.append(cur)
    return rounds
