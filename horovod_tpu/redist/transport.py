"""Interchangeable data planes for the redistribution primitive.

A transport executes one *round* of the plan: every participating rank
hands in ``{dst_rank: payload_bytes}`` and gets back
``{src_rank: payload_bytes}`` — the alltoall-shaped exchange every
in-memory backend reduces to. Three backends share that surface:

* :class:`RingTransport`  — the native TCP p2p ring (native/p2p.py
  ``RingComm.alltoall``): per-link wire-optimal, no central bottleneck;
  the default whenever the launcher exported a KV rendezvous.
* :class:`CoordTransport` — one coordinator allgather per round
  (native/store.py): every rank sees every payload and picks the frames
  addressed to it. O(P·bytes) through the store server, but needs
  nothing beyond the control plane every multi-process job already has.
* :class:`CkptTransport`  — the disk-backed fallback: not an exchange at
  all; redist/core.py routes it through a sharded-checkpoint
  save + reshard-restore round trip (``kind == "disk"``). This is the
  path elastic falls back to when in-memory state was actually lost.

Chaos: every wire exchange (and the weight-stream's chunk IO,
redist/stream.py) crosses the ``redist.transport`` fault site —
drop/partition surface as :class:`RedistError`, ``corrupt`` bit-flips
one outgoing payload (caught downstream by the per-frame crc32), and
the disarmed pass-through is byte-identical by construction
(tests/test_redist.py).
"""
from __future__ import annotations

import socket
import struct
from typing import Dict, Optional

import numpy as np

from ..chaos import inject as _chaos
from ..native import resilience
from .plan import RedistError

#: the chaos fault site at this boundary (chaos/plan.py FAULT_SITES)
CHAOS_SITE = "redist.transport"


def _wrap(msg: str, cause: Optional[BaseException] = None) -> RedistError:
    """Build a RedistError whose ``retryable`` flag is ROUTED THROUGH
    the resilience classifier (native/resilience.py is_retryable): a
    retryable blip retries in place inside the transport before the
    collective disk-fallback vote ever sees it; everything else keeps
    the PR 7 fallback semantics."""
    e = RedistError(msg)
    e.retryable = cause is not None and resilience.is_retryable(cause)
    return e


def chaos_gate(outgoing: Dict[int, bytes],
               peer: Optional[int] = None) -> Dict[int, bytes]:
    """One injector consultation per exchange/IO call. ``corrupt``
    flips a bit in the largest payload (deterministic pick — the crc
    layer must catch it); drop/partition raise :class:`RedistError`
    (fatal: the collective disk-fallback path); conn_reset/flaky raise
    it flagged ``retryable`` so the transport retries in place;
    delay/jitter/crash are handled inside the injector. Disarmed: one
    attribute read, payloads untouched."""
    if _chaos._INJ is None:
        return outgoing
    f = _chaos.fire(CHAOS_SITE, peer=peer)
    if f is None:
        return outgoing
    if f.kind in ("conn_reset", "flaky"):
        e = RedistError(
            f"chaos: injected {f.kind} at {CHAOS_SITE}")
        e.retryable = True
        raise e
    if f.kind in ("drop", "partition"):
        raise RedistError(
            f"chaos: injected {f.kind} at {CHAOS_SITE}")
    if f.kind == "corrupt" and outgoing:
        victim = max(outgoing, key=lambda d: (len(outgoing[d]), -d))
        if outgoing[victim]:
            out = dict(outgoing)
            out[victim] = _chaos.corrupt_copy(out[victim])
            return out
    return outgoing


class BaseTransport:
    """The exchange surface redist/core.py drives. ``kind == "wire"``
    backends implement :meth:`exchange`; the disk backend advertises
    ``kind == "disk"`` and is special-cased by the orchestrator."""

    name = "base"
    kind = "wire"
    rank: int
    world: int

    def exchange(self, outgoing: Dict[int, bytes], tag: str,
                 max_bytes_hint: int = 0) -> Dict[int, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _kv_endpoint():
    """(host_ip, port) of the native KV store the launcher exported, or
    None — the same rendezvous every ring in this codebase builds from
    (ckpt/replicate.py)."""
    import os
    addr = os.environ.get("HOROVOD_NATIVE_KV_ADDR")
    port = os.environ.get("HOROVOD_NATIVE_KV_PORT")
    if not addr or not port:
        return None
    return socket.gethostbyname(addr), int(port)


class RingTransport(BaseTransport):
    """Redistribution rounds over the native TCP p2p ring.

    One exchange is one ragged ``RingComm.alltoall`` of uint8 payloads:
    per-link traffic is the relay-rotation optimum, and a dead peer
    surfaces as ``P2PError`` within the ring timeout — re-raised as
    :class:`RedistError` after the sockets are abandoned so every
    surviving peer observes a genuine EOF instead of a hang."""

    name = "ring"

    def __init__(self, ring, *, owns: bool = True):
        self._ring = ring
        self._owns = owns
        self.rank = ring.rank
        self.world = ring.size

    @classmethod
    def connect(cls, rank: int, world: int, *, prefix: str,
                timeout: float = 300.0, epoch: int = 0,
                kv_addr: Optional[str] = None,
                kv_port: Optional[int] = None) -> "RingTransport":
        """Build a fresh ring from the launcher's KV rendezvous.
        ``prefix``/``epoch`` must be unique per rebuild (the ckpt
        replica-ring discipline) so a stale address from a previous
        round is never dialed."""
        from ..native.p2p import RingComm
        if kv_addr is None or kv_port is None:
            ep = _kv_endpoint()
            if ep is None:
                raise RedistError(
                    "RingTransport needs the native KV store "
                    "(HOROVOD_NATIVE_KV_ADDR/PORT, exported by the "
                    "hvdrun launcher) to rendezvous — none found")
            kv_addr, kv_port = ep
        else:
            kv_addr = socket.gethostbyname(kv_addr)
        ring = RingComm(kv_addr, int(kv_port), rank, world,
                        prefix=prefix, timeout=timeout, epoch=epoch)
        return cls(ring)

    def exchange(self, outgoing: Dict[int, bytes], tag: str,
                 max_bytes_hint: int = 0) -> Dict[int, bytes]:
        def attempt():
            og = chaos_gate(outgoing)
            if self.world == 1:
                return {}
            chunks = [np.frombuffer(og.get(d, b""), np.uint8)
                      for d in range(self.world)]
            try:
                received = self._ring.alltoall(chunks)
            except Exception as e:
                # transient wire faults were already absorbed INSIDE
                # RingComm's reconnect ladder; anything escaping it is
                # post-ladder fatal — abandon the sockets so peers
                # blocked mid-relay observe EOF and fail into their own
                # fallback, not hang the reset
                self.close()
                raise RedistError(
                    f"ring redistribution exchange {tag!r} failed: "
                    f"{e}") from e
            return {s: received[s].tobytes()
                    for s in range(self.world)
                    if s != self.rank and received[s].size}

        # retryable blips surfacing AT this boundary (the chaos gate's
        # conn_reset/flaky) retry in place before the collective
        # disk-fallback vote ever sees a failure
        return resilience.policy().run(
            attempt, what=f"redist exchange {tag!r}",
            site="redist.transport", plane="p2p")

    def close(self) -> None:
        if self._owns and self._ring is not None:
            self._ring.close()
            self._ring = None


class CoordTransport(BaseTransport):
    """Redistribution rounds over the native coordinator's blob
    allgather — the control-plane fallback when no p2p rendezvous is
    available. Each rank's post frames its per-destination payloads as
    ``(dst u32, len u64)`` records; everyone receives everything and
    keeps the records addressed to it."""

    name = "coord"
    _REC = struct.Struct("<IQ")

    def __init__(self, coord):
        self._c = coord
        self.rank = coord.rank
        self.world = coord.size

    def exchange(self, outgoing: Dict[int, bytes], tag: str,
                 max_bytes_hint: int = 0) -> Dict[int, bytes]:
        def attempt():
            og = chaos_gate(outgoing)
            blob = b"".join(self._REC.pack(d, len(p)) + p
                            for d, p in sorted(og.items()))
            # every rank receives every payload: bound by the global
            # round total (the orchestrator's hint) plus framing slack
            cap = max(max_bytes_hint, len(blob) * self.world) \
                + 16 * self.world * self.world + 1024
            try:
                return self._c.allgather(blob, tag=tag, max_bytes=cap)
            except RedistError:
                raise
            except Exception as e:
                # route the wrap through the resilience classifier: a
                # connection-class cause keeps its retryable flag, so
                # the ladder below replays the allgather (sequence
                # numbers advance only on success; posts are
                # nonce-deduped) instead of voting for disk fallback
                raise _wrap(
                    f"coordinator redistribution exchange {tag!r} "
                    f"failed: {e}", e) from e

        blobs = resilience.policy().run(
            attempt, what=f"redist exchange {tag!r}",
            site="redist.transport", plane="coord")
        out: Dict[int, bytes] = {}
        for s, b in enumerate(blobs):
            if s == self.rank:
                continue
            off = 0
            while off < len(b):
                d, n = self._REC.unpack_from(b, off)
                off += self._REC.size
                if off + n > len(b):
                    raise RedistError(
                        f"malformed exchange record from rank {s} "
                        f"(tag {tag!r}): {n} bytes framed, "
                        f"{len(b) - off} present")
                if d == self.rank:
                    out[s] = out.get(s, b"") + b[off:off + n]
                off += n
        return out


class CkptTransport(BaseTransport):
    """The disk-backed backend: marks ``kind == "disk"`` and carries the
    directory + (optional) coordinator; redist/core.py routes it through
    a sharded-checkpoint save + reshard-restore round trip instead of
    wire exchanges. Interchangeable at the ``redistribute(...,
    transport=)`` call site — the point of the plan/transport split."""

    name = "ckpt"
    kind = "disk"

    def __init__(self, directory: str, rank: int, world: int, *,
                 coordinator=None, timeout: float = 300.0):
        self.directory = directory
        self.rank = int(rank)
        self.world = int(world)
        self.coordinator = coordinator
        self.timeout = float(timeout)
        # per-instance collective call counter: redistribute() folds it
        # into the ckpt step, so reusing one transport (and directory)
        # for several same-tagged moves cannot collide on a step and
        # hand readers a previous call's commit. Ranks call in lockstep
        # (the collective contract), so the counter is rank-invariant.
        self._calls = 0

    def next_seq(self) -> int:
        self._calls += 1
        return self._calls

    def exchange(self, outgoing: Dict[int, bytes], tag: str,
                 max_bytes_hint: int = 0) -> Dict[int, bytes]:
        raise RedistError(
            "CkptTransport moves bytes through the checkpoint store, "
            "not wire exchanges — redistribute() routes kind='disk' "
            "transports down the save+restore path")
