"""Training-loop callbacks: broadcast, metric averaging, LR warmup/schedule.

Re-design of the reference's keras callback family
(horovod/_keras/callbacks.py:23-213: BroadcastGlobalVariablesCallback,
MetricAverageCallback, LearningRateWarmupCallback,
LearningRateScheduleCallback), framework-agnostic for jax training loops.

Protocol: a loop calls `on_train_begin()`, `on_epoch_begin(epoch)`,
`on_batch_begin(batch, epoch)`, `on_batch_end(batch, logs)`,
`on_epoch_end(epoch, logs)`. LR callbacks mutate a `Schedule` object the
optimizer reads (use `optax.inject_hyperparams` or read `.value` in your
own schedule fn).
"""
from __future__ import annotations

import logging
import math
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .core import basics
from .core.types import ReduceOp
from .ops import collective_ops
from .optim.functions import broadcast_parameters

logger = logging.getLogger("horovod_tpu")


class Callback:
    def on_train_begin(self): ...
    def on_epoch_begin(self, epoch: int): ...
    def on_batch_begin(self, batch: int, epoch: int = 0): ...
    def on_batch_end(self, batch: int, logs: Optional[Dict] = None): ...
    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None): ...


class LearningRate:
    """Mutable LR handle shared between callbacks and the optimizer."""

    def __init__(self, value: float):
        self.initial = value
        self.value = value

    def __float__(self):
        return float(self.value)


class BroadcastGlobalVariablesCallback(Callback):
    """Sync state from root at train start
    (_keras/callbacks.py:23 BroadcastGlobalVariablesCallbackImpl)."""

    def __init__(self, state_getter: Callable[[], Any],
                 state_setter: Callable[[Any], None], root_rank: int = 0):
        self.get, self.set, self.root = state_getter, state_setter, root_rank

    def on_train_begin(self):
        self.set(broadcast_parameters(self.get(), self.root))


class MetricAverageCallback(Callback):
    """Allreduce-average metrics across workers at epoch end
    (_keras/callbacks.py:62)."""

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        if not logs or not basics.is_initialized():
            return
        n = basics.size()
        for k, v in list(logs.items()):
            arr = np.asarray(v, np.float32)
            if arr.ndim == 0:
                # replicated scalar metric: already identical under the
                # single controller; stacked [size] vector: average rows
                continue
            if arr.shape[0] == n:
                out = collective_ops.allreduce(arr, ReduceOp.AVERAGE)
                logs[k] = np.asarray(out)[0]


class LearningRateWarmupCallback(Callback):
    """Linear LR ramp initial_lr/size -> initial_lr*size over warmup epochs
    (_keras/callbacks.py:106 — 'gradual warmup' per Goyal et al.)."""

    def __init__(self, lr: LearningRate, warmup_epochs: int = 5,
                 steps_per_epoch: int = 1, momentum_correction: bool = True,
                 verbose: bool = False):
        self.lr = lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose

    def on_batch_begin(self, batch: int, epoch: int = 0):
        if epoch >= self.warmup_epochs:
            self.lr.value = self.lr.initial * basics.size()
            return
        progress = (epoch * self.steps_per_epoch + batch) / float(
            self.warmup_epochs * self.steps_per_epoch)
        size = basics.size()
        self.lr.value = self.lr.initial * (1.0 + progress * (size - 1.0))
        if self.verbose:
            logger.info("warmup lr=%.6f", self.lr.value)


class LearningRateScheduleCallback(Callback):
    """Multiply LR by `multiplier(epoch)` within [start_epoch, end_epoch)
    (_keras/callbacks.py:160)."""

    def __init__(self, lr: LearningRate, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.lr = lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if not callable(multiplier):
            mult = float(multiplier)
            self.multiplier = lambda epoch: mult
        else:
            self.multiplier = multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int):
        if self.staircase and self._in_range(epoch):
            self.lr.value = self.lr.initial * basics.size() * \
                self.multiplier(epoch)

    def on_batch_begin(self, batch: int, epoch: int = 0):
        if not self.staircase and self._in_range(epoch):
            frac = epoch + batch / 1000.0
            self.lr.value = self.lr.initial * basics.size() * \
                self.multiplier(frac)
