"""GPT-style decoder transformer, built for hybrid dp/tp/sp meshes.

The long-context / distributed flagship: parameters follow Megatron-style
tensor-parallel partition rules (parallel/tp.py:gpt_partition_rules), the
batch shards over 'dp', and attention can run as ring attention or Ulysses
over an 'sp' axis (parallel/sp.py) for sequences longer than one device's
memory. Everything is standard flax under jit+GSPMD; the sp attention drops
into shard_map over the same mesh.

bfloat16 compute, float32 params; pre-LN blocks; learned positions.
"""
from __future__ import annotations

from dataclasses import field
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import sp as sp_lib


class GPTConfig:
    def __init__(self, vocab_size=256, num_layers=2, num_heads=4,
                 head_dim=16, mlp_ratio=4, max_seq_len=512,
                 attention: str = "dense", mesh: Optional[Mesh] = None,
                 sp_axis: str = "sp", dp_axis: str = "dp",
                 tp_axis: str = "tp", dtype=jnp.bfloat16,
                 attention_impl: Optional[str] = None,
                 remat: bool = False,
                 logits_dtype=jnp.float32,
                 decode: bool = False,
                 kv_block_size: int = 0,
                 kv_pool_blocks: int = 0,
                 decode_kernel: Optional[str] = None):
        if decode and attention != "dense":
            raise ValueError(
                f"decode mode supports attention='dense' only (got "
                f"{attention!r}); sequence parallelism shards the axis "
                "the KV cache grows along")
        if kv_block_size and not decode:
            raise ValueError("kv_block_size is a decode-mode knob")
        if kv_block_size and kv_pool_blocks < 1:
            raise ValueError(
                "paged decode (kv_block_size > 0) needs kv_pool_blocks "
                ">= 1 — the device pool shape is static")
        if decode_kernel not in (None, "pallas", "xla"):
            raise ValueError(
                f"decode_kernel must be None (resolve from "
                f"HOROVOD_SERVE_KERNEL at executor build), 'pallas' or "
                f"'xla'; got {decode_kernel!r}")
        if decode_kernel == "pallas" and not kv_block_size:
            raise ValueError(
                "decode_kernel='pallas' is paged-only (the fused kernel "
                "reads the block pool in place); set kv_block_size > 0 "
                "or keep the slotted XLA path")
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.mlp_dim = self.embed_dim * mlp_ratio
        self.max_seq_len = max_seq_len
        self.attention = attention   # dense | ring | ulysses | zigzag
        self.mesh = mesh
        self.sp_axis = sp_axis
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.dtype = dtype
        # None = auto (pallas on TPU, reference elsewhere);
        # "pallas" | "reference" | "interpret" to force
        self.attention_impl = attention_impl
        #: rematerialize each block on the backward pass (activation
        #: checkpointing, jax.checkpoint) — trades ~1/3 more FLOPs for
        #: O(layers) less activation HBM; essential at long context
        self.remat = remat
        #: lm_head compute dtype. float32 is the conservative default;
        #: bfloat16 runs the head matmul (the largest GEMM in the step)
        #: at MXU bf16 rate and halves the [B, S, V] logits/dlogits HBM
        #: traffic — the fused CE kernel upcasts to f32 INTERNALLY
        #: either way (ops/pallas_ce.py), so only the stored logit
        #: values lose precision (standard TPU LM recipe)
        self.logits_dtype = logits_dtype
        #: inference mode (horovod_tpu/serve): attention threads a
        #: slotted KV cache (flax "cache" collection) and __call__ takes
        #: per-row `positions` + `update_mask` at fixed [slots, T]
        #: shapes — the serving executor's no-recompile contract
        self.decode = decode
        #: paged decode: cache blocks of this many tokens in a pool of
        #: kv_pool_blocks (serve/kv_cache.py write_kv_paged), addressed
        #: by per-row block tables passed to __call__ — occupancy is
        #: bounded by tokens resident, not slots x max_seq_len. 0 keeps
        #: the slotted layout.
        self.kv_block_size = kv_block_size
        self.kv_pool_blocks = kv_pool_blocks
        #: paged decode attention implementation: "pallas" (the fused
        #: block-table-aware kernel, ops/pallas_paged.py — interpret
        #: mode off TPU), "xla" (the gather+masked-einsum oracle), or
        #: None — resolve from HOROVOD_SERVE_KERNEL once at executor
        #: build (serve/executor.py)
        self.decode_kernel = decode_kernel


class Attention(nn.Module):
    """Multi-head attention; `causal=False` makes it the encoder flavor
    (shared with models/vit.py)."""
    cfg: Any
    causal: bool = True

    @nn.compact
    def __call__(self, x, positions=None, update_mask=None,
                 block_tables=None):
        cfg = self.cfg
        B, S, _ = x.shape
        qkv = nn.Dense(3 * cfg.embed_dim, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="qkv")(x)
        qkv = qkv.reshape(B, S, 3, cfg.num_heads, cfg.head_dim)

        # getattr: this Attention is shared by ViT/MoE whose configs
        # predate the decode flag
        if getattr(cfg, "decode", False):
            # serving path: write the S new tokens' K/V into this
            # layer's cache at each row's offset, then attend over the
            # cached prefix (horovod_tpu/serve/kv_cache.py). Same
            # qkv/out params as training — the cache lives in the
            # separate "cache" collection. Paged configs store a block
            # POOL addressed through per-row block tables; slotted ones
            # a [slots, max_seq_len] row per sequence.
            from ..serve import kv_cache as kvc
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,S,H,D]
            if getattr(cfg, "kv_block_size", 0):
                if block_tables is None:
                    raise ValueError(
                        "paged decode needs per-row `block_tables` "
                        "(see horovod_tpu/serve/executor.py)")
                ck = self.variable(
                    "cache", "k", jnp.zeros,
                    (cfg.kv_pool_blocks, cfg.kv_block_size,
                     cfg.num_heads, cfg.head_dim), cfg.dtype)
                cv = self.variable(
                    "cache", "v", jnp.zeros,
                    (cfg.kv_pool_blocks, cfg.kv_block_size,
                     cfg.num_heads, cfg.head_dim), cfg.dtype)
                ck.value, cv.value = kvc.write_kv_paged(
                    ck.value, cv.value, k, v, positions, update_mask,
                    block_tables)
                if getattr(cfg, "decode_kernel", None) == "pallas":
                    from ..ops.pallas_paged import paged_attention_fused
                    o = paged_attention_fused(q, ck.value, cv.value,
                                              block_tables, positions)
                else:
                    o = kvc.paged_attention(q, ck.value, cv.value,
                                            block_tables, positions)
            else:
                ck = self.variable(
                    "cache", "k", jnp.zeros,
                    (B, cfg.max_seq_len, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                cv = self.variable(
                    "cache", "v", jnp.zeros,
                    (B, cfg.max_seq_len, cfg.num_heads, cfg.head_dim),
                    cfg.dtype)
                ck.value, cv.value = kvc.write_kv(
                    ck.value, cv.value, k, v, positions, update_mask)
                o = kvc.cached_attention(q, ck.value, cv.value, positions)
            o = o.reshape(B, S, cfg.embed_dim)
            return nn.Dense(cfg.embed_dim, dtype=cfg.dtype,
                            param_dtype=jnp.float32, name="out")(o)

        q, k, v = [qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3)]

        if cfg.attention in ("ring", "ulysses", "zigzag") \
                and cfg.mesh is not None:
            attn = {"ring": sp_lib.ring_attention,
                    "ulysses": sp_lib.ulysses_attention,
                    "zigzag": sp_lib.zigzag_ring_attention}[cfg.attention]
            sp_impl, vma = sp_lib.sp_impl_for(cfg.attention_impl)
            mesh_axes = cfg.mesh.axis_names
            b_ax = cfg.dp_axis if cfg.dp_axis in mesh_axes else None
            h_ax = cfg.tp_axis if cfg.tp_axis in mesh_axes else None
            spec = P(b_ax, h_ax, cfg.sp_axis, None)
            o = jax.shard_map(
                partial(attn, axis_name=cfg.sp_axis, causal=self.causal,
                        impl=sp_impl),
                mesh=cfg.mesh,
                in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=vma,
            )(q, k, v)
        else:
            # fused pallas kernel on TPU, dense reference elsewhere
            from ..ops.pallas_attention import fused_attention
            o = fused_attention(q, k, v, causal=self.causal,
                                force=cfg.attention_impl)

        o = o.transpose(0, 2, 1, 3).reshape(B, S, cfg.embed_dim)
        return nn.Dense(cfg.embed_dim, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name="out")(o)


class MLP(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.embed_dim, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name="down")(h)


class Block(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, x, positions=None, update_mask=None,
                 block_tables=None):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(cfg, name="attn")(h, positions=positions,
                                            update_mask=update_mask,
                                            block_tables=block_tables)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        return x + MLP(cfg, name="mlp")(h)


class GPT(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, tokens, positions=None, update_mask=None,
                 block_tables=None, logits_idx=None):
        cfg = self.cfg
        B, S = tokens.shape
        if cfg.decode and (positions is None or update_mask is None):
            raise ValueError(
                "decode mode needs per-row `positions` and `update_mask` "
                "(see horovod_tpu/serve/executor.py)")
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     param_dtype=jnp.float32, name="embed")(tokens)
        # decode: row i's S tokens sit at absolute positions
        # positions[i] + [0, S) of that row's sequence
        pos_idx = jnp.arange(S)[None] if positions is None \
            else positions[:, None] + jnp.arange(S)[None, :]
        pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                       param_dtype=jnp.float32, name="pos_embed")(pos_idx)
        x = (x + pos).astype(cfg.dtype)
        zig = (cfg.attention == "zigzag" and cfg.mesh is not None
               and cfg.sp_axis in cfg.mesh.axis_names)
        if zig:
            # residual stream in zigzag order between embed (positions
            # already added in natural order) and the final norm — see
            # models/llama.py; causal masks use true positions
            n_sp = cfg.mesh.shape[cfg.sp_axis]
            if S % (2 * n_sp):
                raise ValueError(f"zigzag needs seq {S} divisible by "
                                 f"2*sp={2 * n_sp}")
            x = sp_lib.zigzag_shard(x, n_sp, seq_axis=1)
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layers_{i}")(
                x, positions=positions, update_mask=update_mask,
                block_tables=block_tables)
        if zig:
            x = sp_lib.zigzag_unshard(x, n_sp, seq_axis=1)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if logits_idx is not None:
            # decode/prefill serving: only the per-row emitting
            # position's logits are ever consumed — gather it BEFORE
            # the lm_head so the largest GEMM of the step (and the
            # sampling work downstream) runs at [B, 1, V], not
            # [B, bucket, V] (serve/executor.py)
            x = jnp.take_along_axis(
                x, logits_idx.astype(jnp.int32)[:, None, None], axis=1)
        logits = nn.Dense(cfg.vocab_size, use_bias=False,
                          dtype=cfg.logits_dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits
