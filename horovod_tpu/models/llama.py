"""Llama-family decoder LM: RMSNorm, rotary embeddings, SwiGLU, GQA.

Extends the model zoo beyond GPT with the architecture that dominates
current open-weight LMs. The reference framework is model-agnostic (its
examples stop at ResNet/transformer encoders); this family exists so
the TPU framework's parallelism stack (TP partition rules, ring/Ulysses
sequence parallelism, DP/PP composition) is demonstrated on a modern
pretraining target, the same way models/gpt.py does for GPT-2.

TPU-first design notes:
* RoPE is computed in f32 and applied with rotate-half (two multiplies
  + one add — XLA fuses it into the surrounding matmuls' epilogue).
* GQA stores num_kv_heads K/V projections and keeps them at kv width
  everywhere: the Pallas flash kernels read kv head h // G via block
  index maps (never expanding K/V in HBM, forward or backward), and on
  the sequence-parallel path the kv-width tensors go through the
  ring/Ulysses collectives with heads broadcast locally — ICI traffic
  shrinks by H/H_kv, which is the point of GQA at long context.
* Attention runs through ops/pallas_attention.fused_attention (flash
  kernel on TPU) or parallel/sp ring/Ulysses under shard_map when a
  sequence axis is configured — identical plumbing to models/gpt.py.
* All matmuls are bf16 with f32 params (MXU-native); norms in f32.
"""
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import sp as sp_lib


class LlamaConfig:
    def __init__(self, vocab_size=256, num_layers=2, num_heads=4,
                 num_kv_heads: Optional[int] = None, head_dim=16,
                 mlp_dim: Optional[int] = None, max_seq_len=512,
                 rope_theta: float = 10000.0,
                 attention: str = "dense", mesh: Optional[Mesh] = None,
                 sp_axis: str = "sp", dp_axis: str = "dp",
                 tp_axis: str = "tp", dtype=jnp.bfloat16,
                 attention_impl: Optional[str] = None,
                 remat: bool = False,
                 logits_dtype=jnp.float32,
                 decode: bool = False,
                 kv_block_size: int = 0,
                 kv_pool_blocks: int = 0,
                 decode_kernel: Optional[str] = None):
        if decode_kernel not in (None, "pallas", "xla"):
            raise ValueError(
                f"decode_kernel must be None (resolve from "
                f"HOROVOD_SERVE_KERNEL at executor build), 'pallas' or "
                f"'xla'; got {decode_kernel!r}")
        if decode_kernel == "pallas" and not kv_block_size:
            raise ValueError(
                "decode_kernel='pallas' is paged-only (the fused kernel "
                "reads the block pool in place); set kv_block_size > 0 "
                "or keep the slotted XLA path")
        if decode and attention != "dense":
            raise ValueError(
                f"decode mode supports attention='dense' only (got "
                f"{attention!r}); sequence parallelism shards the axis "
                "the KV cache grows along")
        if kv_block_size and not decode:
            raise ValueError("kv_block_size is a decode-mode knob")
        if kv_block_size and kv_pool_blocks < 1:
            raise ValueError(
                "paged decode (kv_block_size > 0) needs kv_pool_blocks "
                ">= 1 — the device pool shape is static")
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        if num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads={num_heads} must be a multiple of "
                f"num_kv_heads={self.num_kv_heads}")
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        # Llama uses ~8/3 * d, rounded; keep it lane-aligned
        self.mlp_dim = mlp_dim or _round_up(8 * self.embed_dim // 3, 128)
        self.max_seq_len = max_seq_len
        self.rope_theta = rope_theta
        #: dense | ring | ulysses | zigzag (causally load-balanced ring;
        #: the residual stream runs zigzag-permuted between embed and
        #: final norm — user-invisible, logits return in natural order)
        self.attention = attention
        self.mesh = mesh
        self.sp_axis = sp_axis
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.dtype = dtype
        self.attention_impl = attention_impl
        #: per-block activation checkpointing (see GPTConfig.remat)
        self.remat = remat
        #: lm_head compute dtype (see GPTConfig.logits_dtype): float32
        #: is the conservative default; bfloat16 halves the [B, S, V]
        #: logits/dlogits HBM traffic — the fused CE kernel computes in
        #: f32 internally either way
        self.logits_dtype = logits_dtype
        #: inference mode (horovod_tpu/serve): attention threads a
        #: slotted KV cache at kv width (GQA's H/KV HBM saving carries
        #: straight into the cache) and __call__ takes per-row
        #: `positions` + `update_mask` at fixed [slots, T] shapes
        self.decode = decode
        #: paged decode (see GPTConfig.kv_block_size): block-pool cache
        #: at kv width, addressed by per-row block tables — GQA's HBM
        #: saving compounds with token-bounded occupancy
        self.kv_block_size = kv_block_size
        self.kv_pool_blocks = kv_pool_blocks
        #: paged decode attention implementation (see
        #: GPTConfig.decode_kernel): "pallas" | "xla" | None = resolve
        #: from HOROVOD_SERVE_KERNEL at executor build
        self.decode_kernel = decode_kernel


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def rope_frequencies(head_dim: int, max_seq_len: int,
                     theta: float) -> jax.Array:
    """[max_seq_len, head_dim/2] rotation angles, f32."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    return jnp.outer(jnp.arange(max_seq_len, dtype=jnp.float32), inv)


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate-half RoPE. x [B, H, S, D]; angles [S, D/2] or, for
    per-row windows (decode: each cache slot sits at its own absolute
    position), [B, S, D/2] (f32).

    Positions are absolute over the given angle slice, so sequence-
    parallel shards pass their own angle window (see Attention)."""
    B, H, S, D = x.shape
    xf = x.astype(jnp.float32).reshape(B, H, S, D // 2, 2)
    x1, x2 = xf[..., 0], xf[..., 1]
    if angles.ndim == 3:     # [B, S, D/2] -> broadcast over heads
        cos = jnp.cos(angles)[:, None]
        sin = jnp.sin(angles)[:, None]
    else:                    # [S, D/2] -> broadcast over batch + heads
        cos = jnp.cos(angles)[None, None]
        sin = jnp.sin(angles)[None, None]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(B, H, S, D).astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        norm = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class LlamaAttention(nn.Module):
    """Causal GQA attention with RoPE; dense / ring / ulysses dispatch
    mirrors models/gpt.py Attention."""
    cfg: Any

    @nn.compact
    def __call__(self, x, positions=None, update_mask=None,
                 block_tables=None):
        cfg = self.cfg
        B, S, _ = x.shape
        H, KV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(H * D, name="wq")(x).reshape(B, S, H, D)
        k = dense(KV * D, name="wk")(x).reshape(B, S, KV, D)
        v = dense(KV * D, name="wv")(x).reshape(B, S, KV, D)

        if cfg.decode:
            # serving path: rotate the S new tokens by each row's
            # absolute positions, write K/V (kv width — GQA) into this
            # layer's cache, attend over the cached prefix
            # (horovod_tpu/serve/kv_cache.py). Keys are cached
            # post-RoPE, the standard absolute-rotation layout (which
            # is also what makes a cached shared-prefix block reusable
            # verbatim across sequences: the rotation is absolute).
            from ..serve import kv_cache as kvc
            table = rope_frequencies(D, cfg.max_seq_len, cfg.rope_theta)
            win = table[positions[:, None] + jnp.arange(S)[None, :]]
            q = apply_rope(q.transpose(0, 2, 1, 3), win)
            k = apply_rope(k.transpose(0, 2, 1, 3), win)
            q, k = q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3)
            if cfg.kv_block_size:
                if block_tables is None:
                    raise ValueError(
                        "paged decode needs per-row `block_tables` "
                        "(see horovod_tpu/serve/executor.py)")
                ck = self.variable(
                    "cache", "k", jnp.zeros,
                    (cfg.kv_pool_blocks, cfg.kv_block_size, KV, D),
                    cfg.dtype)
                cv = self.variable(
                    "cache", "v", jnp.zeros,
                    (cfg.kv_pool_blocks, cfg.kv_block_size, KV, D),
                    cfg.dtype)
                ck.value, cv.value = kvc.write_kv_paged(
                    ck.value, cv.value, k, v, positions, update_mask,
                    block_tables)
                if getattr(cfg, "decode_kernel", None) == "pallas":
                    from ..ops.pallas_paged import paged_attention_fused
                    o = paged_attention_fused(q, ck.value, cv.value,
                                              block_tables, positions)
                else:
                    o = kvc.paged_attention(q, ck.value, cv.value,
                                            block_tables, positions)
            else:
                ck = self.variable("cache", "k", jnp.zeros,
                                   (B, cfg.max_seq_len, KV, D), cfg.dtype)
                cv = self.variable("cache", "v", jnp.zeros,
                                   (B, cfg.max_seq_len, KV, D), cfg.dtype)
                ck.value, cv.value = kvc.write_kv(
                    ck.value, cv.value, k, v, positions, update_mask)
                o = kvc.cached_attention(q, ck.value, cv.value, positions)
            return dense(cfg.embed_dim, name="wo")(
                o.reshape(B, S, H * D))

        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))

        sp = (cfg.attention in ("ring", "ulysses", "zigzag")
              and cfg.mesh is not None
              and cfg.sp_axis in cfg.mesh.axis_names)
        angles = rope_frequencies(D, cfg.max_seq_len, cfg.rope_theta)
        if sp:
            mesh_axes = cfg.mesh.axis_names
            b_ax = cfg.dp_axis if cfg.dp_axis in mesh_axes else None
            h_ax = cfg.tp_axis if cfg.tp_axis in mesh_axes else None
            spec = P(b_ax, h_ax, cfg.sp_axis, None)
            attn = {"ring": sp_lib.ring_attention,
                    "ulysses": sp_lib.ulysses_attention,
                    "zigzag": sp_lib.zigzag_ring_attention}[cfg.attention]
            sp_impl, vma = sp_lib.sp_impl_for(cfg.attention_impl)

            def sharded(q, k, v):
                # each sp shard rotates by its absolute position window;
                # k/v stay kv-width — ring/ulysses broadcast heads
                # locally, so ICI traffic is H/KV times smaller
                idx = jax.lax.axis_index(cfg.sp_axis)
                s_loc = q.shape[2]
                if cfg.attention == "zigzag":
                    # local rows are chunks (idx, 2n-1-idx) of 2n — the
                    # RoPE window follows the true zigzag positions
                    n_sp = jax.lax.psum(1, cfg.sp_axis)
                    c = s_loc // 2
                    win = jnp.concatenate([
                        jax.lax.dynamic_slice_in_dim(
                            angles, idx * c, c, axis=0),
                        jax.lax.dynamic_slice_in_dim(
                            angles, (2 * n_sp - 1 - idx) * c, c, axis=0),
                    ])
                else:
                    win = jax.lax.dynamic_slice_in_dim(
                        angles, idx * s_loc, s_loc, axis=0)
                qr = apply_rope(q, win)
                kr = apply_rope(k, win)
                return attn(qr, kr, v, axis_name=cfg.sp_axis, causal=True,
                            impl=sp_impl)

            o = jax.shard_map(sharded, mesh=cfg.mesh,
                              in_specs=(spec, spec, spec), out_specs=spec,
                              check_vma=vma)(q, k, v)
        else:
            q = apply_rope(q, angles[:S])
            k = apply_rope(k, angles[:S])
            # kv-width k/v go straight in: the pallas kernels are
            # GQA-aware (the reference fallback expands internally)
            from ..ops.pallas_attention import fused_attention
            o = fused_attention(q, k, v, causal=True,
                                force=cfg.attention_impl)

        o = o.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        return dense(cfg.embed_dim, name="wo")(o)


class SwiGLU(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        g = dense(cfg.mlp_dim, name="gate")(x)
        u = dense(cfg.mlp_dim, name="up")(x)
        return dense(cfg.embed_dim, name="down")(nn.silu(g) * u)


class LlamaBlock(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, x, positions=None, update_mask=None,
                 block_tables=None):
        x = x + LlamaAttention(self.cfg, name="attn")(
            RMSNorm(name="attn_norm")(x), positions=positions,
            update_mask=update_mask, block_tables=block_tables)
        return x + SwiGLU(self.cfg, name="mlp")(
            RMSNorm(name="mlp_norm")(x))


class Llama(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, tokens, positions=None, update_mask=None,
                 block_tables=None, logits_idx=None):
        cfg = self.cfg
        if cfg.decode and (positions is None or update_mask is None):
            raise ValueError(
                "decode mode needs per-row `positions` and `update_mask` "
                "(see horovod_tpu/serve/executor.py)")
        if tokens.shape[1] > cfg.max_seq_len:
            # fail loudly: the sp path would otherwise silently clamp
            # RoPE windows past the angle table (duplicated positions)
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds "
                f"max_seq_len={cfg.max_seq_len}")
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     param_dtype=jnp.float32, name="embed")(tokens)
        x = x.astype(cfg.dtype)
        zig = (cfg.attention == "zigzag" and cfg.mesh is not None
               and cfg.sp_axis in cfg.mesh.axis_names)
        if zig:
            # the residual stream runs in the zigzag order between the
            # embedding and the final norm: one gather each way for the
            # whole model, RMSNorm/SwiGLU are position-independent, and
            # attention masks/RoPE use the true positions
            n_sp = cfg.mesh.shape[cfg.sp_axis]
            if tokens.shape[1] % (2 * n_sp):
                raise ValueError(
                    f"zigzag needs seq {tokens.shape[1]} divisible by "
                    f"2*sp={2 * n_sp}")
            x = sp_lib.zigzag_shard(x, n_sp, seq_axis=1)
        block_cls = nn.remat(LlamaBlock) if cfg.remat else LlamaBlock
        for i in range(cfg.num_layers):
            x = block_cls(cfg, name=f"layers_{i}")(
                x, positions=positions, update_mask=update_mask,
                block_tables=block_tables)
        if zig:
            x = sp_lib.zigzag_unshard(x, n_sp, seq_axis=1)
        x = RMSNorm(name="norm_f")(x)
        if logits_idx is not None:
            # serving: gather each row's emitting position BEFORE the
            # lm_head so the step's largest GEMM runs at [B, 1, V]
            # (see models/gpt.py)
            x = jnp.take_along_axis(
                x, logits_idx.astype(jnp.int32)[:, None, None], axis=1)
        return nn.Dense(cfg.vocab_size, use_bias=False,
                        dtype=cfg.logits_dtype,
                        param_dtype=jnp.float32, name="lm_head")(x)


def llama_partition_rules(tp_axis: str = "tp"):
    """Megatron-style TP rules for the Llama family.

    Column-parallel: wq/wk/wv and gate/up (output features over tp);
    row-parallel: wo/down (input features over tp; XLA inserts the
    psum). With GQA, num_kv_heads must be divisible by the tp degree
    or XLA falls back to a halo exchange — keep kv_heads % tp == 0.
    """
    from ..parallel.tp import PartitionRules
    return PartitionRules([
        (r"attn/w[qkv]/kernel", P(None, tp_axis)),
        (r"attn/wo/kernel", P(tp_axis, None)),
        (r"mlp/(gate|up)/kernel", P(None, tp_axis)),
        (r"mlp/down/kernel", P(tp_axis, None)),
        (r"embed/embedding", P(None, tp_axis)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ])


#: ~1.1B-param pretraining shape (TinyLlama-class), for benchmarks
Llama_1B = partial(LlamaConfig, num_layers=22, num_heads=32,
                   num_kv_heads=4, head_dim=64, vocab_size=32000,
                   max_seq_len=2048)
