"""Benchmark-model builder shared by bench.py and
examples/synthetic_benchmark.py.

One place that knows how each zoo model is timed (the reference's
tf_cnn_benchmarks model registry role): resnets run the full SyncBN
train step; VGG/Inception time the train step with frozen norm/dropout
stats (identical conv/FC FLOPs, no per-step rng plumbing — Inception's
running stats ride the jit closure).
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

BENCH_MODELS = ("resnet18", "resnet50", "resnet101", "vgg16", "inception3")


def default_image_size(name: str, on_tpu: bool) -> int:
    """Canonical benchmark size on TPU; reduced CPU-smoke sizes that
    respect each topology's minimum (Inception needs >=75 for its VALID
    stem; VGG's 5 maxpools need >=32)."""
    if name == "inception3":
        return 299 if on_tpu else 80
    if name == "vgg16":
        return 224 if on_tpu else 32
    return 224 if on_tpu else 64


def build_benchmark_model(
    name: str, image_size: int, *, stem: str = "conv7",
    num_classes: int = 1000, seed: int = 0,
) -> Tuple[Callable, Any, Any, bool]:
    """Returns (apply_fn, params, batch_stats, has_batch_stats) ready for
    training.make_train_step: apply_fn(variables, images) for the frozen
    models, the raw module apply for resnets (SyncBN path)."""
    import jax
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    if name in ("resnet18", "resnet50", "resnet101"):
        from .resnet import ResNet18, ResNet50, ResNet101
        cls = {"resnet18": ResNet18, "resnet50": ResNet50,
               "resnet101": ResNet101}[name]
        model = cls(num_classes=num_classes, stem=stem)
        variables = model.init(rng, dummy, train=True)
        return (model.apply, variables["params"],
                variables["batch_stats"], True)
    if name == "vgg16":
        from .vgg import VGG16
        # always the canonical flatten+FC head — it adapts to any input
        # size (first FC width = (H/32)*(W/32)*512), so reduced smoke
        # sizes still run the VGG architecture, not a different head
        model = VGG16(num_classes=num_classes, classifier="flatten")
        variables = model.init(rng, dummy, train=False)
        apply_fn = lambda v, x: model.apply(v, x, train=False)  # noqa: E731
        return apply_fn, variables["params"], {}, False
    if name == "inception3":
        from .inception import InceptionV3
        model = InceptionV3(num_classes=num_classes)
        variables = model.init(rng, dummy, train=False)
        frozen = variables["batch_stats"]
        apply_fn = lambda v, x: model.apply(   # noqa: E731
            dict(v, batch_stats=frozen), x, train=False)
        return apply_fn, variables["params"], {}, False
    raise ValueError(f"unknown benchmark model {name!r}; "
                     f"choose from {BENCH_MODELS}")
