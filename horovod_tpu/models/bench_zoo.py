"""Benchmark-model builder shared by bench.py and
examples/synthetic_benchmark.py.

One place that knows how each zoo model is timed (the reference's
tf_cnn_benchmarks model registry role): resnets run the full SyncBN
train step; VGG/Inception time the train step with frozen norm/dropout
stats (identical conv/FC FLOPs, no per-step rng plumbing — Inception's
running stats ride the jit closure).
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

BENCH_MODELS = ("resnet18", "resnet50", "resnet101", "vgg16", "inception3")


def default_image_size(name: str, on_tpu: bool) -> int:
    """Canonical benchmark size on TPU; reduced CPU-smoke sizes that
    respect each topology's minimum (Inception needs >=75 for its VALID
    stem; VGG's 5 maxpools need >=32)."""
    if name == "inception3":
        return 299 if on_tpu else 80
    if name == "vgg16":
        return 224 if on_tpu else 32
    return 224 if on_tpu else 64


def build_benchmark_model(
    name: str, image_size: int, *, stem: str = "conv7",
    num_classes: int = 1000, seed: int = 0,
) -> Tuple[Callable, Any, Any, bool]:
    """Returns (apply_fn, params, batch_stats, has_batch_stats) ready for
    training.make_train_step: apply_fn(variables, images) for the frozen
    models, the raw module apply for resnets (SyncBN path)."""
    import jax
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    if name in ("resnet18", "resnet50", "resnet101"):
        from .resnet import ResNet18, ResNet50, ResNet101
        cls = {"resnet18": ResNet18, "resnet50": ResNet50,
               "resnet101": ResNet101}[name]
        model = cls(num_classes=num_classes, stem=stem)
        variables = model.init(rng, dummy, train=True)
        return (model.apply, variables["params"],
                variables["batch_stats"], True)
    if name == "vgg16":
        from .vgg import VGG16
        # always the canonical flatten+FC head — it adapts to any input
        # size (first FC width = (H/32)*(W/32)*512), so reduced smoke
        # sizes still run the VGG architecture, not a different head
        model = VGG16(num_classes=num_classes, classifier="flatten")
        variables = model.init(rng, dummy, train=False)
        apply_fn = lambda v, x: model.apply(v, x, train=False)  # noqa: E731
        return apply_fn, variables["params"], {}, False
    if name == "inception3":
        from .inception import InceptionV3
        model = InceptionV3(num_classes=num_classes)
        variables = model.init(rng, dummy, train=False)
        frozen = variables["batch_stats"]
        apply_fn = lambda v, x: model.apply(   # noqa: E731
            dict(v, batch_stats=frozen), x, train=False)
        return apply_fn, variables["params"], {}, False
    raise ValueError(f"unknown benchmark model {name!r}; "
                     f"choose from {BENCH_MODELS}")


#: rows the convergence harness (horovod_tpu/converge/) can train.
#: Deliberately NOT merged into BENCH_MODELS — bench.py keeps a literal
#: mirror of that tuple for its --help text (tests/test_models.py pins
#: them equal), and these rows are loss-curve fixtures, not throughput
#: subjects. gpt_tiny/moe_tiny pre-stage ROADMAP item 2's MoE rows.
CONVERGE_MODELS = ("resnet18", "gpt_tiny", "moe_tiny")

#: calibrated per-row SGD rates (used when HOROVOD_CONVERGE_LR is 0,
#: the default). Each rate clears the harness's converge gate (final
#: <= 0.9 x initial in 30 steps) while staying OUT of the row's
#: chaotic regime, where trajectory sensitivity amplifies ulp-level
#: wire noise into large final-loss scatter: resnet18 needs <= 0.1
#: (at 0.2 its bf16 cells scatter ~13-31% vs fp32), the transformers
#: need >= 0.2 to descend 10% (measured, docs/benchmarks.md).
CONVERGE_LRS = {"resnet18": 0.1, "gpt_tiny": 0.2, "moe_tiny": 0.2}


def build_converge_model(
    name: str, *, nranks: int, batch_size: int = 4, seed: int = 0,
) -> Tuple[Callable, Any, Callable]:
    """Returns (loss_fn, params, batch_fn) for the convergence harness:
    `loss_fn(params, batch) -> scalar fp32` for ONE rank's batch,
    `batch_fn(step) -> batch` stacked [nranks, batch_size, ...] (the
    harness vmaps the grad over the rank axis). Everything is float32
    end-to-end and CPU-smoke sized — the harness compares loss CURVES
    between wire formats, so model-compute rounding must stay far below
    the wire deltas under test.

    Data is a small fixed pool the model memorizes: two distinct
    deterministic batches per rank, cycled. Memorizing a fixed pool
    descends reliably for every optimizer cell, unlike fitting fresh
    noise (whose Bayes loss is flat)."""
    import jax
    import jax.numpy as jnp

    rng = jax.random.PRNGKey(seed)
    pool = 2                                 # distinct batches per rank
    rows = nranks * batch_size * pool

    if name == "resnet18":
        from .resnet import ResNet18
        size, classes = 32, 10
        # narrow fp32 variant: full ResNet-18 topology, 1/8 width —
        # the curve fixture needs the architecture, not the 11M params
        model = ResNet18(num_classes=classes, num_filters=8,
                         dtype=jnp.float32)
        variables = model.init(rng, jnp.zeros((1, size, size, 3)),
                               train=True)
        params, frozen = variables["params"], variables["batch_stats"]
        kx, ky = jax.random.split(jax.random.fold_in(rng, 1))
        images = jax.random.normal(kx, (rows, size, size, 3), jnp.float32)
        labels = jax.random.randint(ky, (rows,), 0, classes)

        def loss_fn(p, batch):
            x, y = batch
            # frozen init stats: differentiable, no per-step mutable
            # state to thread through the rank-stacked vmap
            logits = model.apply({"params": p, "batch_stats": frozen},
                                 x, train=False)
            return _xent(logits, y, classes)

        return loss_fn, params, _pool_batch_fn((images, labels),
                                               nranks, batch_size, pool)

    if name in ("gpt_tiny", "moe_tiny"):
        seq, vocab = 16, 64
        kx = jax.random.fold_in(rng, 2)
        tokens = jax.random.randint(kx, (rows, seq), 0, vocab)
        if name == "gpt_tiny":
            from .gpt import GPT, GPTConfig
            cfg = GPTConfig(vocab_size=vocab, num_layers=2, num_heads=2,
                            head_dim=8, mlp_ratio=2, max_seq_len=seq,
                            dtype=jnp.float32)
            model = GPT(cfg)
            params = model.init(rng, tokens[:1])["params"]

            def loss_fn(p, batch):
                logits = model.apply({"params": p}, batch)
                return _xent(logits[:, :-1], batch[:, 1:], vocab)
        else:
            from .moe import MoEGPT, MoEGPTConfig, moe_aux_loss
            cfg = MoEGPTConfig(vocab_size=vocab, num_layers=2,
                               num_heads=2, head_dim=8, mlp_ratio=2,
                               max_seq_len=seq, num_experts=4,
                               dtype=jnp.float32)
            model = MoEGPT(cfg)
            params = model.init(rng, tokens[:1])["params"]

            def loss_fn(p, batch):
                logits, mut = model.apply({"params": p}, batch,
                                          mutable=["intermediates"])
                ce = _xent(logits[:, :-1], batch[:, 1:], vocab)
                return ce + 0.01 * moe_aux_loss(mut["intermediates"])

        return loss_fn, params, _pool_batch_fn(tokens, nranks,
                                               batch_size, pool)

    raise ValueError(f"unknown converge model {name!r}; "
                     f"choose from {CONVERGE_MODELS}")


def _xent(logits, labels, num_classes):
    import jax
    import jax.numpy as jnp
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def _pool_batch_fn(data, nranks, batch_size, pool):
    """batch_fn over a fixed pool shaped [nranks*batch_size*pool, ...]:
    step t serves pool slot t % pool, reshaped [nranks, batch_size, ...]
    so every rank sees its own fixed shard — deterministic in (seed,
    step), independent of how many steps the caller runs."""
    import jax
    import jax.numpy as jnp

    def reshard(a):
        return a.reshape((pool, nranks, batch_size) + a.shape[1:])

    pooled = jax.tree_util.tree_map(reshard, data)

    def batch_fn(step):
        return jax.tree_util.tree_map(lambda a: a[step % pool], pooled)

    return batch_fn
