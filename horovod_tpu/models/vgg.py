"""VGG in flax, TPU-first.

One of the reference's three headline scaling-benchmark models
(docs/benchmarks.rst:13: VGG-16 at ~68% scaling efficiency on 512 GPUs —
the hardest of the trio because its ~138M dense parameters stress the
allreduce). NHWC, bfloat16 compute with float32 params; the three big FC
matmuls (25088x4096, 4096x4096, 4096xC) are exactly MXU-shaped.

`classifier="flatten"` is the classic 224x224 head (tf_cnn_benchmarks
layout); `classifier="avg"` global-average-pools first so any input size
works (used by the size-reduced tests).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# convs per stage (each stage ends in a 2x2 maxpool)
_VGG16_STAGES = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))
_VGG19_STAGES = ((64, 2), (128, 2), (256, 4), (512, 4), (512, 4))


class VGG(nn.Module):
    stages: Sequence = _VGG16_STAGES
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    #: BN variant (torchvision vgg16_bn); the reference benchmark model
    #: is the plain one
    batch_norm: bool = False
    classifier: str = "flatten"
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for filters, reps in self.stages:
            for _ in range(reps):
                x = conv(filters)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype,
                                     param_dtype=jnp.float32)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.classifier == "avg":
            x = jnp.mean(x, axis=(1, 2))
        else:
            x = x.reshape((x.shape[0], -1))
        for _ in range(2):
            x = nn.Dense(4096, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


VGG16 = partial(VGG, stages=_VGG16_STAGES)
VGG19 = partial(VGG, stages=_VGG19_STAGES)
