"""Pipelined GPT: the decoder transformer trained with the 1F1B schedule.

Composes models/gpt.py's Block with parallel/pp.pipeline_1f1b (beyond the
reference — SURVEY §2.6 lists PP as absent): the embedding (+positions)
runs replicated before the pipeline and trains through the returned input
grads; each pp-mesh device owns `num_layers / stages` Blocks; the final
LayerNorm + LM head live inside the pipeline loss (head grads returned
replicated). One SPMD program — stage hops are neighbor `ppermute`s on
ICI, live activations are bounded at 2S-1 microbatches per stage.

    embed_p, stage_p, head_p = gpt_pp_init(cfg, stages, rng)
    step = make_gpt_pp_step(cfg, mesh, num_microbatches=M)
    loss, grads = step((embed_p, stage_p, head_p), tokens, targets)
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.pp import pipeline_1f1b
from .gpt import Block


class StageBlocks(nn.Module):
    """One pipeline stage: a run of decoder Blocks (same shape in/out)."""
    cfg: Any
    blocks_per_stage: int

    @nn.compact
    def __call__(self, x):
        block_cls = nn.remat(Block) if self.cfg.remat else Block
        for i in range(self.blocks_per_stage):
            x = block_cls(self.cfg, name=f"blk_{i}")(x)
        return x


class EmbedIn(nn.Module):
    """Token + learned-position embedding (runs before the pipeline)."""
    cfg: Any

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        S = tokens.shape[-1]
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     param_dtype=jnp.float32, name="embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                       param_dtype=jnp.float32, name="pos_embed")(
            jnp.arange(S)[None])
        return (x + pos).astype(cfg.dtype)


class Head(nn.Module):
    """Final LayerNorm + LM head (lives inside the pipeline loss)."""
    cfg: Any

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(self.cfg.vocab_size, use_bias=False,
                        dtype=jnp.float32, param_dtype=jnp.float32,
                        name="lm_head")(x)


def gpt_pp_init(cfg, stages: int, rng, microbatch_size: int = 1,
                virtual: int = 1):
    """Initialize (embed_params, stage_params, head_params).

    stage_params is stacked [stages, ...] on the leading axis — shard it
    P('pp') into the step. With `virtual` > 1 (the interleaved
    schedule) it is stacked [stages, virtual, ...]: device i's chunk j
    holds GLOBAL stage i + j*stages, the interleaved assignment.
    cfg.num_layers must divide by stages*virtual."""
    if cfg.num_layers % (stages * virtual):
        raise ValueError(f"num_layers {cfg.num_layers} must divide by "
                         f"stages*virtual={stages * virtual}")
    bps = cfg.num_layers // (stages * virtual)
    r_e, r_s, r_h = jax.random.split(rng, 3)
    toks = jnp.zeros((microbatch_size, cfg.max_seq_len), jnp.int32)
    x = jnp.zeros((microbatch_size, cfg.max_seq_len, cfg.embed_dim),
                  cfg.dtype)
    embed_p = EmbedIn(cfg).init(r_e, toks)["params"]
    stage_mod = StageBlocks(cfg, bps)
    flat = jax.vmap(lambda r: stage_mod.init(r, x)["params"])(
        jax.random.split(r_s, stages * virtual))
    if virtual > 1:
        # [S*V, ...] in global-stage order -> [S, V, ...] where
        # [i, j] = global stage i + j*S
        order = jnp.asarray([[i + j * stages for j in range(virtual)]
                             for i in range(stages)])
        stage_p = jax.tree_util.tree_map(lambda a: a[order], flat)
    else:
        stage_p = flat
    head_p = Head(cfg).init(r_h, x)["params"]
    return embed_p, stage_p, head_p


def make_gpt_pp_step(cfg, mesh: Mesh, num_microbatches: int,
                     pp_axis: str = "pp", dp_axis: str = None,
                     virtual: int = 1):
    """Build the jitted 1F1B loss+grads step.

    Returned step(params, tokens, targets) takes
    params = (embed_p, stage_p[S, ...], head_p), tokens/targets [B, S]
    with B divisible by num_microbatches, and returns
    (loss, (embed_grads, stage_grads, head_grads)) — stage grads stay
    pp-sharded on their stacked axis; embed/head grads are replicated.

    With `dp_axis` set (a pp×dp hybrid mesh), the global batch shards
    over dp — each dp shard runs its own pipeline on B/dp examples (so
    B must divide by dp*num_microbatches per shard) — and the loss and
    every gradient family are pmean'd over dp (the DP allreduce riding
    the same compiled program).

    `virtual` > 1 selects the interleaved schedule (wave-scanned for
    num_microbatches > stages): stage_params from
    gpt_pp_init(..., virtual=V) is [stages, V, ...].
    """
    from ..parallel.pp import pipeline_interleaved_waves
    n_stages = mesh.shape[pp_axis]
    bps = cfg.num_layers // (n_stages * virtual)
    stage_mod = StageBlocks(cfg, bps)
    embed_mod = EmbedIn(cfg)
    head_mod = Head(cfg)
    M = num_microbatches
    vary = (dp_axis,) if dp_axis else ()

    def body(stage_p_stacked, embed_p, head_p, toks, tgts):
        stage_p = jax.tree_util.tree_map(lambda a: a[0], stage_p_stacked)
        if dp_axis:
            # everything the pipeline touches must be explicitly
            # dp-varying: each dp shard runs an independent pipeline and
            # the reduction happens ONCE, explicitly, at the end
            from ..parallel.pp import _pvary
            dpv = lambda t: jax.tree_util.tree_map(      # noqa: E731
                lambda a: _pvary(a, dp_axis), t)
            stage_p, embed_p, head_p = (dpv(stage_p), dpv(embed_p),
                                        dpv(head_p))
        mb = toks.shape[0] // M
        toks_mb = toks.reshape(M, mb, toks.shape[1])
        tgts_mb = tgts.reshape(M, mb, tgts.shape[1])

        def embed_fn(p):
            return jax.vmap(
                lambda t: embed_mod.apply({"params": p}, t))(toks_mb)

        xs, embed_vjp = jax.vjp(embed_fn, embed_p)

        def stage_fn(p, x):
            return stage_mod.apply({"params": p}, x)

        def loss_fn(hp, y, t):
            logp = jax.nn.log_softmax(
                head_mod.apply({"params": hp}, y))
            return -jnp.mean(
                jnp.take_along_axis(logp, t[..., None], axis=-1))

        # waves delegate to a single interleaved group when M <= stages
        pipeline = pipeline_1f1b if virtual == 1 \
            else pipeline_interleaved_waves
        loss, g_stage, aux = pipeline(
            stage_fn, stage_p, xs, tgts_mb, loss_fn, pp_axis,
            head_params=head_p, return_input_grads=True,
            vary_axes=vary)
        (g_embed,) = embed_vjp(aux["input_grads"])
        g_head = aux["head_grads"]
        if dp_axis:
            pm = lambda t: jax.tree_util.tree_map(       # noqa: E731
                lambda g: jax.lax.pmean(g, dp_axis), t)
            loss = jax.lax.pmean(loss, dp_axis)
            g_embed, g_stage, g_head = pm(g_embed), pm(g_stage), \
                pm(g_head)
        g_stage = jax.tree_util.tree_map(lambda g: g[None], g_stage)
        return loss, g_embed, g_stage, g_head

    batch_spec = P(dp_axis) if dp_axis else P()
    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(pp_axis), P(), P(), batch_spec, batch_spec),
        out_specs=(P(), P(), P(pp_axis), P())))

    def step(params, tokens, targets):
        embed_p, stage_p, head_p = params
        div = M * (mesh.shape[dp_axis] if dp_axis else 1)
        if tokens.shape[0] % div:
            raise ValueError(
                f"batch {tokens.shape[0]} must divide by "
                f"num_microbatches*dp = {div}")
        loss, g_embed, g_stage, g_head = mapped(
            stage_p, embed_p, head_p, tokens, targets)
        return loss, (g_embed, g_stage, g_head)

    return step
