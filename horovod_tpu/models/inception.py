"""Inception V3 in flax, TPU-first.

One of the reference's three headline scaling-benchmark models
(docs/benchmarks.rst:8-13: Inception V3 at ~90% scaling efficiency on
512 GPUs). Fresh NHWC implementation of the Szegedy et al. 2015 V3
topology — factorized 7x7 branches, grid reductions, BN on every conv —
bfloat16 compute with float32 params/batch-stats. The branch concats are
channel-major so XLA fuses each branch's convs and tiles them onto the
MXU independently.

The auxiliary logits head (training-regularization in the original) is
omitted: the reference benchmark path (tf_cnn_benchmarks inception3)
likewise trains the main head only. Minimum input 75x75 (three stride-2
reductions in the stem + two grid reductions).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    """conv -> BN -> relu, the V3 building unit (all convs carry BN)."""

    filters: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool-proj branches."""

    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(64, (1, 1))(x, train)
        b5 = cbn(48, (1, 1))(x, train)
        b5 = cbn(64, (5, 5))(b5, train)
        b3 = cbn(64, (1, 1))(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(self.pool_features, (1, 1))(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35x35 -> 17x17 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(384, (3, 3), (2, 2), padding="VALID")(x, train)
        bd = cbn(64, (1, 1))(x, train)
        bd = cbn(96, (3, 3))(bd, train)
        bd = cbn(96, (3, 3), (2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """17x17 block with factorized 7x7 (1x7 + 7x1) branches."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = cbn(192, (1, 1))(x, train)
        b7 = cbn(c7, (1, 1))(x, train)
        b7 = cbn(c7, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        bd = cbn(c7, (1, 1))(x, train)
        bd = cbn(c7, (7, 1))(bd, train)
        bd = cbn(c7, (1, 7))(bd, train)
        bd = cbn(c7, (7, 1))(bd, train)
        bd = cbn(192, (1, 7))(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(192, (1, 1))(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17x17 -> 8x8 grid reduction."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b3 = cbn(192, (1, 1))(x, train)
        b3 = cbn(320, (3, 3), (2, 2), padding="VALID")(b3, train)
        b7 = cbn(192, (1, 1))(x, train)
        b7 = cbn(192, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        b7 = cbn(192, (3, 3), (2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """8x8 block with split 3x3 (1x3 | 3x1) branches."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320, (1, 1))(x, train)
        b3 = cbn(384, (1, 1))(x, train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        bd = cbn(448, (1, 1))(x, train)
        bd = cbn(384, (3, 3))(bd, train)
        bd = jnp.concatenate([cbn(384, (1, 3))(bd, train),
                              cbn(384, (3, 1))(bd, train)], axis=-1)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(192, (1, 1))(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299 -> 35 (three stride-2 steps)
        x = cbn(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1), padding="VALID")(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35x35
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionB(dtype=self.dtype)(x, train)
        # 17x17
        x = InceptionC(128, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(160, dtype=self.dtype)(x, train)
        x = InceptionC(192, dtype=self.dtype)(x, train)
        x = InceptionD(dtype=self.dtype)(x, train)
        # 8x8
        x = InceptionE(dtype=self.dtype)(x, train)
        x = InceptionE(dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
