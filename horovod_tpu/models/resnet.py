"""ResNet v1.5 in flax, TPU-first.

The flagship benchmark model: the reference's headline numbers are ResNet
synthetic-benchmark img/sec (docs/benchmarks.rst,
examples/pytorch/pytorch_synthetic_benchmark.py uses torchvision resnet50).
This is a fresh flax implementation — NHWC layout, bfloat16 compute with
float32 params/batch-stats, stride-2 in the 3x3 (v1.5) — shaped so XLA tiles
the convs onto the MXU.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """Bottleneck residual block (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x: jnp.ndarray) -> jnp.ndarray:
    """[B, H, W, C] -> [B, H/2, W/2, 4C]; channel order (dh, dw, c).
    Requires even H and W (use stem="conv7" for odd image sizes)."""
    B, H, W, C = x.shape
    if H % 2 or W % 2:
        raise ValueError(
            f"space_to_depth stem needs even spatial dims, got {H}x{W}; "
            "use stem='conv7' for odd image sizes")
    x = x.reshape(B, H // 2, 2, W // 2, 2, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2, 4 * C)


def stem_kernel_to_s2d(k7: jnp.ndarray) -> jnp.ndarray:
    """Rearrange a [7, 7, C, F] stride-2 stem kernel into the equivalent
    [4, 4, 4C, F] space-to-depth kernel (zero 8th tap at offset -4)."""
    K, _, C, F = k7.shape
    k8 = jnp.zeros((8, 8, C, F), k7.dtype).at[1:, 1:].set(k7)
    k8 = k8.reshape(4, 2, 4, 2, C, F)          # (t_h, dh, t_w, dw, c, f)
    return k8.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * C, F)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    #: "conv7" = classic 7x7/2 stem; "space_to_depth" = the same linear
    #: map as a 4x4/1 conv on 2x2-blocked input (12 channels instead of
    #: 3) — the 3-channel 7x7 conv tiles poorly onto the 128-lane MXU,
    #: the blocked form fills it (MLPerf-style stem optimization)
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            # block pad (2,1) in block units == pixel pad (4,2); the
            # extra left pixel vs conv7's (3,3) meets the zero 8th tap,
            # so the map equals conv_init exactly (see stem_kernel_to_s2d)
            x = space_to_depth(x)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding=[(2, 1), (2, 1)], name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, act=act, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
