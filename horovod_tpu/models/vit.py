"""Vision Transformer, TPU-first.

Second dense model family beside ResNet (models/resnet.py) and GPT
(models/gpt.py). The reference frames its benchmarks around image
classifiers (docs/benchmarks.rst: Inception V3 / ResNet-101 / VGG-16);
ViT is the modern equivalent and maps better onto the MXU than VGG-era
convs: patch embedding is one strided conv, everything after is large
batched matmuls in bfloat16.

Design notes:
* pre-LN encoder blocks; fused (flash) attention kernel on TPU via
  ops/pallas_attention.fused_attention (non-causal);
* float32 params, bfloat16 activations (param_dtype/dtype split, same
  convention as models/gpt.py);
* mean-pool head by default (CLS token optional) — pooling keeps shapes
  static and avoids the concat that breaks fused attention block sizes;
* Megatron-style tensor-parallel partition rules in
  `vit_partition_rules` mirror parallel/tp.py:gpt_partition_rules.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel.tp import PartitionRules
from .gpt import MLP, Attention
from jax.sharding import PartitionSpec as P


class ViTConfig:
    def __init__(self, image_size=224, patch_size=16, num_classes=1000,
                 num_layers=12, num_heads=12, head_dim=64, mlp_ratio=4,
                 pool: str = "mean", dtype=jnp.bfloat16,
                 attention_impl: Optional[str] = None):
        assert image_size % patch_size == 0
        self.image_size = image_size
        self.patch_size = patch_size
        self.num_classes = num_classes
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.mlp_dim = self.embed_dim * mlp_ratio
        self.num_patches = (image_size // patch_size) ** 2
        self.pool = pool                    # "mean" | "cls"
        self.dtype = dtype
        # None = auto (pallas on TPU, dense reference elsewhere)
        self.attention_impl = attention_impl
        # gpt.Attention contract (dense path; no sp for images)
        self.attention = "dense"
        self.mesh = None
        self.dp_axis, self.tp_axis, self.sp_axis = "dp", "tp", "sp"


class EncoderBlock(nn.Module):
    """Pre-LN encoder block: gpt.Attention (causal=False) + gpt.MLP."""
    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(cfg, causal=False, name="attn")(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        return x + MLP(cfg, name="mlp")(h)


class ViT(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.cfg
        B = images.shape[0]
        p = cfg.patch_size
        # patchify: one strided conv = a single big matmul on the MXU
        x = nn.Conv(cfg.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=cfg.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.embed_dim)              # [B, N, D]
        S = x.shape[1]
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.num_patches, cfg.embed_dim), jnp.float32)
        x = x + pos[:, :S].astype(cfg.dtype)
        if cfg.pool == "cls":
            cls = self.param("cls", nn.initializers.zeros,
                             (1, 1, cfg.embed_dim), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls.astype(cfg.dtype),
                                  (B, 1, cfg.embed_dim)), x], axis=1)
        for i in range(cfg.num_layers):
            x = EncoderBlock(cfg, name=f"layers_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        x = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


def vit_partition_rules(tp_axis: str = "tp") -> PartitionRules:
    """Megatron-style TP rules for the ViT encoder (column-parallel qkv/up,
    row-parallel out/down), matching parallel/tp.py:gpt_partition_rules."""
    return PartitionRules([
        (r"attn/qkv/kernel", P(None, tp_axis)),
        (r"attn/out/kernel", P(tp_axis, None)),
        (r"mlp/up/kernel", P(None, tp_axis)),
        (r"mlp/down/kernel", P(tp_axis, None)),
        (r"attn/qkv/bias", P(tp_axis)),
        (r"mlp/up/bias", P(tp_axis)),
    ])


# -- presets ---------------------------------------------------------------

def ViT_S(num_classes: int = 1000, **kw) -> ViT:
    return ViT(ViTConfig(num_classes=num_classes, num_layers=12,
                         num_heads=6, head_dim=64, **kw))


def ViT_B(num_classes: int = 1000, **kw) -> ViT:
    return ViT(ViTConfig(num_classes=num_classes, num_layers=12,
                         num_heads=12, head_dim=64, **kw))


def ViT_Tiny(num_classes: int = 10, **kw) -> ViT:
    """Small enough for CPU-mesh tests."""
    kw.setdefault("image_size", 32)
    kw.setdefault("patch_size", 8)
    return ViT(ViTConfig(num_classes=num_classes, num_layers=2,
                         num_heads=2, head_dim=8, **kw))
