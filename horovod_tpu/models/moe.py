"""Mixture-of-Experts transformer over an expert-parallel mesh axis.

The reference lists EP as "absent as a strategy; alltoall + process sets
are the primitives an MoE implementation would use" (SURVEY §2.6,
operations.cc:1904 alltoall). parallel/ep.py supplies those primitives
TPU-natively (top-1 routing, capacity dispatch, lax.all_to_all across the
'ep' axis); this module is the model family built on them: a GPT-style
decoder whose MLPs are switch-style MoE layers.

Execution modes:
* `mesh` with an 'ep' axis of size > 1 — experts shard over 'ep'
  (leading axis of the stacked expert weights), tokens all_to_all to
  their experts inside shard_map, combine returns them (ep.moe_layer).
* otherwise — all experts local, same routing math (ep.moe_reference),
  so a single chip runs the identical model.

Router load-balancing aux loss (Switch Transformer eq. 4) is sowed under
("intermediates", "aux_loss"); `moe_aux_loss` sums it for the train step.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import ep as ep_lib
from ..parallel.tp import PartitionRules
from .gpt import Attention


class MoEGPTConfig:
    def __init__(self, vocab_size=256, num_layers=2, num_heads=4,
                 head_dim=16, mlp_ratio=4, max_seq_len=512,
                 num_experts=4, capacity_factor=1.25, router_top_k=1,
                 mesh: Optional[Mesh] = None, ep_axis: str = "ep",
                 dp_axis: str = "dp", tp_axis: str = "tp",
                 sp_axis: str = "sp", attention: str = "dense",
                 dtype=jnp.bfloat16, attention_impl: Optional[str] = None):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.mlp_dim = self.embed_dim * mlp_ratio
        self.max_seq_len = max_seq_len
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        #: 1 = Switch-style; 2 = GShard/Mixtral-style normalized top-2
        self.router_top_k = router_top_k
        self.mesh = mesh
        self.ep_axis = ep_axis
        self.dp_axis = dp_axis
        self.tp_axis = tp_axis
        self.sp_axis = sp_axis
        self.attention = attention
        self.dtype = dtype
        self.attention_impl = attention_impl

    @property
    def ep_size(self) -> int:
        if self.mesh is None or self.ep_axis not in self.mesh.axis_names:
            return 1
        return self.mesh.shape[self.ep_axis]


def _expert_fn(params, tokens):
    """One expert's FFN: tokens [C, D] -> [C, D]; vmapped over experts."""
    up_w, up_b, down_w, down_b = params
    h = tokens @ up_w + up_b
    h = nn.gelu(h)
    return h @ down_w + down_b


class MoEMLP(nn.Module):
    """Switch-style MoE FFN; drop-in for the dense MLP in a Block."""
    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, D = x.shape
        E, M = cfg.num_experts, cfg.mlp_dim
        router_w = self.param("router_kernel",
                              nn.initializers.normal(0.02), (D, E),
                              jnp.float32)
        init = nn.initializers.lecun_normal()
        up_w = self.param("up_kernel", init, (E, D, M), jnp.float32)
        up_b = self.param("up_bias", nn.initializers.zeros, (E, M),
                          jnp.float32)
        down_w = self.param("down_kernel", init, (E, M, D), jnp.float32)
        down_b = self.param("down_bias", nn.initializers.zeros, (E, D),
                            jnp.float32)

        x2 = x.reshape(B * S, D).astype(cfg.dtype)

        # router logits computed ONCE in fp32 — used both for the aux loss
        # and (passed down) for dispatch, so balance statistics and routing
        # decisions can never diverge on near-tie tokens
        logits = x2.astype(jnp.float32) @ router_w

        # Switch load-balancing aux loss: E * sum_e(frac_tokens_e * mean_prob_e)
        probs = jax.nn.softmax(logits, axis=-1)
        frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), E,
                                       dtype=jnp.float32), axis=0)
        aux = E * jnp.sum(frac * probs.mean(axis=0))
        self.sow("intermediates", "aux_loss", aux)

        params = (up_w.astype(cfg.dtype), up_b.astype(cfg.dtype),
                  down_w.astype(cfg.dtype), down_b.astype(cfg.dtype))
        if cfg.ep_size > 1:
            mesh = cfg.mesh
            tok_axes = tuple(a for a in (cfg.dp_axis, cfg.ep_axis)
                             if a in mesh.axis_names)
            tok_spec = P(tok_axes if len(tok_axes) > 1 else tok_axes[0],
                         None)
            e_spec = jax.tree_util.tree_map(
                lambda w: P(*((cfg.ep_axis,) + (None,) * (w.ndim - 1))),
                params)

            def _dispatch(xs, lg, ps):
                return ep_lib.moe_layer(
                    xs, None, _expert_fn, ps, axis_name=cfg.ep_axis,
                    capacity_factor=cfg.capacity_factor, logits=lg,
                    top_k=cfg.router_top_k)

            y = jax.shard_map(
                _dispatch,
                mesh=mesh,
                in_specs=(tok_spec, tok_spec, e_spec),
                out_specs=tok_spec,
            )(x2, logits, params)
        else:
            y = ep_lib.moe_reference(
                x2, None, _expert_fn, params,
                capacity_factor=cfg.capacity_factor, logits=logits,
                top_k=cfg.router_top_k)
        return y.reshape(B, S, D).astype(cfg.dtype)


class MoEBlock(nn.Module):
    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(cfg, name="attn")(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        return x + MoEMLP(cfg, name="moe")(h)


class MoEGPT(nn.Module):
    """Decoder LM: every block's FFN is expert-routed."""
    cfg: Any

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, S = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim,
                     param_dtype=jnp.float32, name="embed")(tokens)
        pos = nn.Embed(cfg.max_seq_len, cfg.embed_dim,
                       param_dtype=jnp.float32, name="pos_embed")(
            jnp.arange(S)[None])
        x = (x + pos).astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = MoEBlock(cfg, name=f"layers_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="lm_head")(x)


def moe_aux_loss(intermediates: Any) -> jax.Array:
    """Sum the sowed per-layer router aux losses (0.0 if none)."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(intermediates):
        total = total + jnp.sum(leaf)
    return jnp.asarray(total, jnp.float32)


def moe_partition_rules(tp_axis: str = "tp",
                        ep_axis: str = "ep") -> PartitionRules:
    """GSPMD rules: experts shard on their leading E axis over 'ep';
    attention follows Megatron TP; router replicated."""
    return PartitionRules([
        (r"moe/(up|down)_(kernel|bias)", P(ep_axis)),
        (r"moe/router_kernel", P(None, None)),
        (r"attn/qkv/kernel", P(None, tp_axis)),
        (r"attn/out/kernel", P(tp_axis, None)),
        (r"attn/qkv/bias", P(tp_axis)),
        (r"embed/embedding", P(None, tp_axis)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ])
