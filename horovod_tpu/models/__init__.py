"""Model families: image classifiers (ResNet, VGG, Inception V3, ViT) —
the reference's headline benchmark trio plus ViT — and language models
(GPT dense, MoE expert-parallel, Llama). All flax/linen, float32 params
with bfloat16 compute, built for dp/tp/sp/ep meshes."""
from .resnet import ResNet18, ResNet50          # noqa: F401
from .vgg import VGG, VGG16, VGG19              # noqa: F401
from .inception import InceptionV3              # noqa: F401
from .gpt import GPT, GPTConfig                 # noqa: F401
from .vit import (                              # noqa: F401
    ViT, ViTConfig, ViT_S, ViT_B, ViT_Tiny, vit_partition_rules,
)
from .moe import (                              # noqa: F401
    MoEGPT, MoEGPTConfig, moe_partition_rules, moe_aux_loss,
)
from .llama import (                            # noqa: F401
    Llama, LlamaConfig, Llama_1B, llama_partition_rules,
)
from .gpt_pp import gpt_pp_init, make_gpt_pp_step   # noqa: F401
