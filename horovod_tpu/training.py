"""SPMD train-step builders: the TPU-native hot loop.

Where the reference's hot loop is per-tensor async allreduce driven from
gradient hooks (SURVEY §3.2, torch/optimizer.py:225 -> nccl_operations.cc:185),
the TPU-native hot loop is ONE compiled XLA program per step: forward +
backward + gradient psum + optimizer update, shard_mapped over the device
mesh. XLA overlaps the gradient all-reduces with remaining backward compute
(the role of the reference's start/done custom-call split,
tensorflow/xla_mpi_ops.cc:176-227) and fuses everything else.

`make_train_step` is the canonical data-parallel recipe built on
`DistributedOptimizer(axis_name=...)`; batch-norm statistics are averaged
across the mesh like the reference's SyncBatchNorm option.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .core.mesh import GLOBAL_AXIS
from .core.types import ReduceOp
from .optim.optimizer import DistributedOptimizer


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean token cross entropy as plain XLA ops — the GSPMD-friendly
    form: the partitioner shards elementwise/reduce freely, so use this
    wherever logits are globally sharded (make_gspmd_train_step)."""
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def fused_cross_entropy_loss(logits: jax.Array,
                             labels: jax.Array) -> jax.Array:
    """Mean token cross entropy via the fused Pallas kernel on TPU
    (one HBM pass, ops/pallas_ce.py), optax elsewhere. Use on LOCAL
    shards (inside shard_map) — a bare pallas_call on globally-sharded
    logits would force the partitioner to gather them."""
    from .ops.pallas_ce import fused_cross_entropy
    return fused_cross_entropy(logits, labels)


def make_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis_name: str = GLOBAL_AXIS,
    has_batch_stats: bool = False,
    loss_fn: Callable = None,
    compression=None,
    op: ReduceOp = ReduceOp.AVERAGE,
    backward_passes_per_step: int = 1,
    donate: bool = True,
):
    """Build a jitted data-parallel train step over `mesh`.

    Returns `step(params, opt_state, batch_stats, images, labels) ->
    (params, opt_state, batch_stats, loss)`. Params/opt state are replicated;
    the batch is sharded along `axis_name`; gradients are reduced in-graph by
    `DistributedOptimizer`.
    """
    from .optim.compression import Compression
    if loss_fn is None:
        # local_step runs inside shard_map on local shards, where the
        # fused Pallas kernel applies without partitioning concerns
        loss_fn = fused_cross_entropy_loss
    dist_opt = DistributedOptimizer(
        optimizer, axis_name=axis_name, op=op,
        compression=compression or Compression.none,
        backward_passes_per_step=backward_passes_per_step)

    def local_step(params, opt_state, batch_stats, images, labels):
        def compute_loss(p):
            variables = {"params": p}
            if has_batch_stats:
                variables["batch_stats"] = batch_stats
                logits, mut = apply_fn(variables, images, train=True,
                                       mutable=["batch_stats"])
                return loss_fn(logits, labels), mut["batch_stats"]
            logits = apply_fn(variables, images)
            return loss_fn(logits, labels), batch_stats

        (loss, new_stats), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = dist_opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, axis_name)
        if has_batch_stats:
            # cross-replica BN statistics (reference SyncBatchNorm,
            # torch/sync_batch_norm.py:40)
            new_stats = jax.tree_util.tree_map(
                lambda s: lax.pmean(s, axis_name), new_stats)
        return params, opt_state, new_stats, loss

    repl = P()
    sharded = P(axis_name)
    # check_vma=False: the body may contain pallas_call (fused CE), whose
    # out_shape carries no varying-manual-axes info; jax's vma tracker
    # rejects it under shard_map (jax 0.9). out_specs stay authoritative:
    # params/opt/stats/loss are replicated via the explicit pmeans above.
    smapped = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(repl, repl, repl, sharded, sharded),
        out_specs=(repl, repl, repl, repl),
        check_vma=False)
    donate_argnums = (0, 1, 2) if donate else ()
    step = jax.jit(smapped, donate_argnums=donate_argnums)
    # expose the wrapped optimizer's init so callers build the right state
    step.init_opt_state = dist_opt.init
    return step


def make_gspmd_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    rules,
    *,
    batch_spec: P = None,
    loss_fn: Callable = cross_entropy_loss,
    aux_loss_fn: Callable = None,
    aux_loss_weight: float = 0.01,
):
    """Build a jitted hybrid-parallel (dp/tp/sp) train step via GSPMD.

    Parameters are sharded by `rules` (parallel/tp.py PartitionRules);
    the token batch is sharded by `batch_spec` (default P('dp','sp') reduced
    to the axes present on `mesh`). XLA inserts all collectives: dp gradient
    psums, tp row-parallel psums, sp attention comms (via the model's
    shard_map). This is the scaling-book path — the in-graph analog of the
    reference's DistributedOptimizer+XLA-custom-call overlap.

    `aux_loss_fn(intermediates) -> scalar` (e.g. models.moe.moe_aux_loss)
    adds `aux_loss_weight` times the model's sowed auxiliary losses to the
    objective; without it flax silently drops sowed values, so MoE routers
    would get no load-balancing gradient.
    """
    if batch_spec is None:
        axes = mesh.axis_names
        batch_spec = P("dp" if "dp" in axes else None,
                       "sp" if "sp" in axes else None)
    # restrict like param specs: axes the rule names but this mesh lacks
    # degrade to None (e.g. batch_spec=P("dp", None) on an sp-only mesh),
    # so call sites need not special-case degenerate meshes
    from .parallel.tp import _restrict_spec
    batch_sh = NamedSharding(mesh, _restrict_spec(batch_spec, mesh))

    def step(params, opt_state, tokens, targets):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sh)

        def compute_loss(p):
            if aux_loss_fn is not None:
                logits, mut = apply_fn({"params": p}, tokens,
                                       mutable=["intermediates"])
                return (loss_fn(logits, targets)
                        + aux_loss_weight
                        * aux_loss_fn(mut["intermediates"]))
            logits = apply_fn({"params": p}, tokens)
            return loss_fn(logits, targets)

        loss, grads = jax.value_and_grad(compute_loss)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Shardings are inferred from the (committed) input arrays: params
    # placed by parallel.tp.shard_params carry their NamedShardings, optax
    # state inherits them at init, and the batch is constrained above.
    return jax.jit(step, donate_argnums=(0, 1))


def init_replicated(tree: Any, mesh: Mesh) -> Any:
    """Pin a pytree to the replicated sharding of `mesh`.

    Multi-process safe: when the mesh spans processes every process
    contributes its identical copy (core.mesh.place_replicated).

    Note: device_put may alias the source buffers (e.g. CPU -> CPU mesh),
    and the train steps donate their param/opt arguments — so treat the
    ORIGINAL tree as consumed once its replicated copy has been through a
    donating step."""
    from .core.mesh import place_replicated
    return jax.tree_util.tree_map(lambda x: place_replicated(x, mesh), tree)


def shard_batch(batch: Any, mesh: Mesh, axis_name: str = GLOBAL_AXIS) -> Any:
    """Shard a host batch along its leading axis over the mesh.

    Single-process: `batch` is the full global batch. Multi-process: each
    process passes its LOCAL portion (what that worker's data loader
    produced — the reference's per-rank batch) and the global batch is the
    concatenation across processes in rank order."""
    from .core.mesh import mesh_is_multiprocess
    import numpy as _np
    if mesh_is_multiprocess(mesh):
        sh = NamedSharding(mesh, P(axis_name))
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sh, _np.asarray(x)), batch)
    sh = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
